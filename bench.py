"""Headline benchmark: link-updates/sec on a 100k-link Clos topology.

The reference's UpdateLinks path rebuilds qdiscs one link at a time through
netlink + tc execs (reference daemon/kubedtn/handler.go:634-671,
common/qdisc.go:201-290) — milliseconds per link, serial per daemon. Here
the same operation is one batched inverse-map update of the edge-state
arrays (kubedtn_tpu.ops.edge_state.update_links: one int32 scatter builds
the row→batch map, everything else is gathers/selects at HBM bandwidth),
so the unit of work is a whole topology-wide property update, and the
measured iterations run under one lax.scan so per-dispatch overhead is
amortized the way a production controller would batch its pushes.

Scenario: 2-tier Clos, 100 spines × 500 leaves × 2 parallel links = 100_000
p2p links (BASELINE.md 100k-link ladder rung), realized as 200_000 directed
edge rows. Each iteration updates the local end of every link (100_000 rows,
reference UpdateLinks semantics) with fresh properties, then the following
iteration updates the other end, alternating — no caching shortcuts.

Prints ONE JSON line:
  {"metric": "link-updates/sec", "value": ..., "unit": "links/s",
   "vs_baseline": value / 1e6}
vs_baseline is relative to the driver-set target of 1M link-updates/sec on
a 100k-link topology (BASELINE.json `metric`/`north_star`).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.models.topologies import clos, load_edge_list_into_state
from kubedtn_tpu.ops import edge_state as es

N_SPINE = 100
N_LEAF = 500
LINKS_PER_PAIR = 2  # 100 * 500 * 2 = 100_000 links
ITERS = 100


def build():
    el = clos(N_SPINE, N_LEAF, hosts_per_leaf=0,
              props=LinkProperties(latency="10ms", rate="10Gbit"),
              links_per_pair=LINKS_PER_PAIR)
    assert el.n_links == 100_000, el.n_links
    state, rows = load_edge_list_into_state(el)  # 200k rows, capacity 2^18
    return el, state, rows


def fresh_props(n, seed):
    """Pre-stage n random-but-valid property rows on device."""
    rng = np.random.default_rng(seed)
    base = np.zeros((n, es.NPROP), np.float32)
    base[:, es.P_LATENCY_US] = rng.integers(1_000, 100_000, n)
    base[:, es.P_JITTER_US] = rng.integers(0, 5_000, n)
    base[:, es.P_LOSS] = rng.uniform(0, 2, n)
    base[:, es.P_RATE_BPS] = rng.choice(
        [20e6, 50e6, 100e6, 1e9, 10e9], n)
    return jnp.asarray(base)


def main():
    import functools

    el, state, rows = build()
    L = el.n_links
    # local-end rows for each link are the first L directed rows; the
    # reverse direction occupies rows L..2L. Alternate ends per iteration.
    rows2 = jnp.stack([jnp.asarray(np.arange(0, L, dtype=np.int32)),
                       jnp.asarray(np.arange(L, 2 * L, dtype=np.int32))])
    props2 = jnp.stack([fresh_props(L, 1), fresh_props(L, 2)])
    valid = jnp.ones((L,), dtype=bool)

    # The iterations run under one lax.scan so dispatch overhead (large on
    # a tunneled chip) is paid once per ITERS, not per iteration — each
    # scan step is still a full 100k-row UpdateLinks with fresh property
    # rows (no caching shortcuts; the i%2 select swaps ends every step).
    @functools.partial(jax.jit, donate_argnums=0, static_argnums=1)
    def run(state, iters):
        def body(st, i):
            return es.update_links.__wrapped__(
                st, rows2[i % 2], props2[i % 2], valid), ()
        st, _ = jax.lax.scan(body, state, jnp.arange(iters))
        return st

    # warm up with the SAME static iters so the timed call below reuses
    # the compiled executable (a different iters would recompile)
    state = run(state, ITERS)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    state = run(state, ITERS)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    updates_per_sec = L * ITERS / dt
    print(json.dumps({
        "metric": "link-updates/sec",
        "value": round(updates_per_sec, 1),
        "unit": "links/s",
        "vs_baseline": round(updates_per_sec / 1e6, 3),
    }))


if __name__ == "__main__":
    main()
