"""Headline benchmark: link-updates/sec on a 100k-link Clos topology.

The reference's UpdateLinks path rebuilds qdiscs one link at a time through
netlink + tc execs (reference daemon/kubedtn/handler.go:634-671,
common/qdisc.go:201-290) — milliseconds per link, serial per daemon. Here
the same operation is one batched inverse-map update of the edge-state
arrays (kubedtn_tpu.ops.edge_state.update_links: one int32 scatter builds
the row→batch map, everything else is gathers/selects at HBM bandwidth),
so the unit of work is a whole topology-wide property update, and the
measured iterations run under one lax.scan so per-dispatch overhead is
amortized the way a production controller would batch its pushes.

Scenario: 2-tier Clos, 100 spines × 500 leaves × 2 parallel links = 100_000
p2p links (BASELINE.md 100k-link ladder rung), realized as 200_000 directed
edge rows. Each iteration updates the local end of every link (100_000 rows,
reference UpdateLinks semantics) with fresh properties, then the following
iteration updates the other end, alternating — no caching shortcuts.

Prints ONE JSON line:
  {"metric": "link-updates/sec", "value": ..., "unit": "links/s",
   "vs_baseline": value / 1e6, "extras": {...}}
vs_baseline is relative to the driver-set target of 1M link-updates/sec on
a 100k-link topology (BASELINE.json `metric`/`north_star`).

extras carries the other BASELINE evidence:
  - reconcile_100k: reconcile-to-steady through the REAL control path
    (store → reconciler → engine → device), target <5s @100k links, plus
    the churn and live-gRPC UpdateLinks round-trip numbers
    (kubedtn_tpu.scenarios.reconcile_100k);
  - shape_vmapped_pkts_per_s / shape_pallas_pkts_per_s: the netem shaping
    kernel timed on device both ways (ops/netem.shape_step vs
    ops/pallas/shaping.shape_step, interpret=False on TPU) — the on-
    hardware validation of the pallas-vs-XLA claim in ops/netem.py.

Robustness: the JAX backend behind the tunneled TPU chip can hang or come
up UNAVAILABLE. Backend init is probed in a KILLABLE subprocess with a
deadline and retried with backoff before this process commits to it; each
measurement phase retries transient failures; a phase that ultimately
fails reports its error in extras instead of killing the whole bench, and
a total failure still prints the one-line JSON (value 0, error set) so the
driver always gets a parseable record.

Two more outage lessons are structural (round-4 verdict):
- every phase's result is flushed to BENCH_partial.json the moment it
  completes, so a crash/outage mid-run loses at most the running phase,
  never the finished ones;
- the phases that can ONLY run on the real chip (pallas-tiled, scale_1m)
  run FIRST when the backend is TPU — if the tunnel dies mid-bench the
  on-chip-only evidence is already on disk.
- the record carries a host fingerprint (CPU model, loadavg, nproc) and
  the wire microbenches report medians over N>=5 repeats, so a slow host
  is distinguishable from a real regression.
"""

from __future__ import annotations

import json
import os
import platform
import statistics
import subprocess
import sys
import time
import traceback

N_SPINE = 100
N_LEAF = 500
LINKS_PER_PAIR = 2  # 100 * 500 * 2 = 100_000 links
ITERS = 100
SHAPE_ITERS = 100

PROBE_ATTEMPTS = 2
PROBE_TIMEOUT_S = 150
PHASE_ATTEMPTS = 2
WIRE_REPEATS = 5  # median-of-N for the gRPC microbenches

PARTIAL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_partial.json")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def host_fingerprint() -> dict:
    """CPU model + core count + loadavg: enough to tell 'the machine was
    slower this round' apart from 'the code got slower' when two records
    disagree (round-4 verdict weak-point 2)."""
    fp: dict = {}
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    fp["cpu_model"] = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    fp["nproc"] = os.cpu_count()
    try:
        fp["loadavg_start"] = [round(x, 2) for x in os.getloadavg()]
    except OSError:
        pass
    return fp


def flush_partial(extras: dict, phases_done: list[str]) -> None:
    """Persist everything measured so far: a mid-run crash or tunnel
    outage loses at most the phase in flight."""
    try:
        with open(PARTIAL_PATH, "w") as f:
            json.dump({"phases_done": phases_done, "extras": extras,
                       "ts": time.time()}, f, indent=1)
    except OSError as e:
        log(f"partial flush failed: {e!r}")


def probe_backend() -> bool:
    """Initialize the JAX backend in a killable subprocess first: a hung
    device tunnel then costs one bounded probe, not the whole bench."""
    code = "import jax; print(jax.default_backend(), len(jax.devices()))"
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            if r.returncode == 0:
                log(f"backend probe ok: {r.stdout.strip()}")
                return True
            log(f"backend probe attempt {attempt} rc={r.returncode}: "
                f"{r.stderr.strip()[-400:]}")
        except subprocess.TimeoutExpired:
            log(f"backend probe attempt {attempt} timed out "
                f"after {PROBE_TIMEOUT_S}s")
        time.sleep(5 * attempt)
    return False


def with_retry(phase: str, fn, extras: dict):
    """Run one measurement phase with bounded retries; on final failure
    record the error in extras and return None."""
    for attempt in range(1, PHASE_ATTEMPTS + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — any backend error retries
            log(f"{phase} attempt {attempt} failed: {e!r}")
            if attempt == PHASE_ATTEMPTS:
                extras[f"{phase}_error"] = f"{type(e).__name__}: {e}"[:300]
                log(traceback.format_exc())
            else:
                time.sleep(3 * attempt)
    return None


def build():
    from kubedtn_tpu.api.types import LinkProperties
    from kubedtn_tpu.models.topologies import clos, load_edge_list_into_state

    el = clos(N_SPINE, N_LEAF, hosts_per_leaf=0,
              props=LinkProperties(latency="10ms", rate="10Gbit"),
              links_per_pair=LINKS_PER_PAIR)
    assert el.n_links == 100_000, el.n_links
    state, rows = load_edge_list_into_state(el)  # 200k rows, capacity 2^18
    return el, state, rows


def fresh_props(n, seed):
    """Pre-stage n random-but-valid property rows on device."""
    import jax.numpy as jnp

    from kubedtn_tpu.models.topologies import random_link_props

    return jnp.asarray(random_link_props(n, seed))


def bench_link_updates(extras: dict) -> float:
    """Headline: batched UpdateLinks throughput under one lax.scan.

    The updated rows are the engine's natural layout — each end's rows
    are one consecutive block (the allocator hands out consecutive rows,
    and the engine's flush coalesces a whole drain into one sorted
    batch) — so the headline uses update_links' contiguous streaming
    path. extras also records the general inverse-map path driven with
    SORTED-but-non-contiguous rows ("scattered"): the engine's realistic
    non-contiguous case, since its flush always sorts a batch. (A fully
    unsorted order would be slower still, but no engine path produces
    one.)
    """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubedtn_tpu.ops import edge_state as es

    el, state, rows = build()
    L = el.n_links
    # local-end rows for each link are the first L directed rows; the
    # reverse direction occupies rows L..2L. Alternate ends per iteration.
    rows2 = jnp.stack([jnp.asarray(np.arange(0, L, dtype=np.int32)),
                       jnp.asarray(np.arange(L, 2 * L, dtype=np.int32))])
    perm = np.random.default_rng(3).permutation(2 * L)[:L].astype(np.int32)
    rows_scat = jnp.stack([jnp.asarray(np.sort(perm)),
                           jnp.asarray(np.sort((perm + L) % (2 * L)))])
    props2 = jnp.stack([fresh_props(L, 1), fresh_props(L, 2)])
    valid = jnp.ones((L,), dtype=bool)

    # The iterations run under one lax.scan so dispatch overhead (large on
    # a tunneled chip) is paid once per ITERS, not per iteration — each
    # scan step is still a full 100k-row UpdateLinks with fresh property
    # rows (no caching shortcuts; the i%2 select swaps ends every step).
    def timed(rows_pair, contiguous):
        @functools.partial(jax.jit, donate_argnums=0, static_argnums=1)
        def run(st, iters):
            def body(st, i):
                return es.update_links.__wrapped__(
                    st, rows_pair[i % 2], props2[i % 2], valid,
                    contiguous), ()
            st, _ = jax.lax.scan(body, st, jnp.arange(iters))
            return st

        # warm up with the SAME static iters so the timed call reuses the
        # compiled executable (a different iters would recompile);
        # median-of-3 timing — at the degraded iteration count a single
        # sample swung 40-99M/s run to run on the shared build host
        st = run(jax.tree.map(lambda x: x.copy(), state), ITERS)
        jax.block_until_ready(st)
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            st = run(st, ITERS)
            jax.block_until_ready(st)
            samples.append(time.perf_counter() - t0)
        return L * ITERS / statistics.median(samples)

    scattered = timed(rows_scat, False)
    extras["link_updates_scattered_per_s"] = round(scattered, 1)
    return timed(rows2, True)


def bench_shape_step(extras: dict) -> None:
    """Time the netem shaping kernel on device: XLA-vmapped vs Pallas
    (interpret=False on TPU), same key — turns the '~12% faster' claim in
    ops/netem.py into recorded on-hardware evidence."""
    import functools

    import jax
    import jax.numpy as jnp

    from kubedtn_tpu.ops import netem

    el, state, rows = build()
    E = state.capacity
    n_active = int(jnp.sum(state.active))
    sizes = jnp.full((E,), 1500.0, jnp.float32)
    t0s = jnp.zeros((E,), jnp.float32)
    key = jax.random.key(7)

    def timed(step_fn, label):
        @functools.partial(jax.jit, donate_argnums=0, static_argnums=1)
        def run(st, iters):
            def body(st, i):
                st, _res = step_fn(st, sizes, st.active, t0s,
                                   jax.random.fold_in(key, i))
                return st, ()
            st, _ = jax.lax.scan(body, st, jnp.arange(iters))
            return st

        # run donates its argument — hand each timing its own copy so the
        # shared baseline state survives for the next variant; report the
        # median of 3 (the tunneled chip's run-to-run variance is large)
        samples = []
        for _ in range(3):
            st = run(jax.tree.map(lambda x: x.copy(), state), SHAPE_ITERS)
            jax.block_until_ready(st.props)
            t0 = time.perf_counter()
            st = run(st, SHAPE_ITERS)
            jax.block_until_ready(st.props)
            samples.append(time.perf_counter() - t0)
        dt = statistics.median(samples)
        extras[label] = round(n_active * SHAPE_ITERS / dt, 1)

    timed(netem.shape_step, "shape_vmapped_pkts_per_s")
    if jax.default_backend() == "tpu":
        from kubedtn_tpu.ops.pallas import shaping

        timed(lambda st, s, h, t, k: shaping.shape_step(
            st, s, h, t, k, interpret=False), "shape_pallas_pkts_per_s")

        # persistent-tiled + on-core PRNG variant (one step per call)
        # and the FUSED multi-step form (S steps per pallas_call, state
        # crossing steps in-kernel — the one-step variant still pays
        # the full state HBM round-trip per step; the fused one only
        # writes the depart+flags it actually produces, see
        # ARCHITECTURE.md roofline note). ONE warm/time/median harness
        # for both so the figures stay methodology-comparable.
        act_i32 = state.active.astype(jnp.int32)

        def timed_tiled(steps_per_call: int, label: str):
            # delivery accounting stays ON DEVICE (shaping.flag_counts):
            # the [steps, R, 128] flags slab reduces to one scalar per
            # scan step inside the jit — the timed loop transfers
            # nothing per step, and the delivered total still comes out
            # as evidence that the kernel shaped real traffic
            @functools.partial(jax.jit, donate_argnums=0,
                               static_argnums=1)
            def run(ts, iters):
                sizes_t = shaping.tile_vec(sizes, ts)
                act_t = shaping.tile_vec(act_i32, ts)
                t_arr_t = shaping.tile_vec(t0s, ts)

                def body(carry, i):
                    ts, delivered = carry
                    ts, _d, f = shaping.shape_steps_tiled.__wrapped__(
                        ts, sizes_t, act_t, t_arr_t, i, steps_per_call,
                        interpret=False)
                    delivered += shaping.flag_counts.__wrapped__(
                        f)["delivered"]
                    return (ts, delivered), ()

                carry, _ = jax.lax.scan(body, (ts, jnp.int32(0)),
                                        jnp.arange(iters))
                return carry

            iters = max(1, SHAPE_ITERS // steps_per_call)
            samples = []
            delivered = 0
            for _ in range(3):
                ts = shaping.tile_state(jax.tree.map(
                    lambda x: x.copy(), state))
                ts, _n = run(ts, iters)
                jax.block_until_ready(ts.tokens)
                t0 = time.perf_counter()
                ts, n_del = run(ts, iters)
                jax.block_until_ready(ts.tokens)
                samples.append(time.perf_counter() - t0)
                delivered = int(n_del)
            dt = statistics.median(samples)
            extras[label] = round(
                n_active * steps_per_call * iters / dt, 1)
            extras[f"{label}_delivered"] = delivered

        timed_tiled(1, "shape_pallas_tiled_pkts_per_s")
        timed_tiled(10, "shape_pallas_fused_pkts_per_s")
    else:
        extras["shape_pallas_pkts_per_s"] = None
        extras["shape_pallas_tiled_pkts_per_s"] = None
        extras["shape_pallas_fused_pkts_per_s"] = None
        extras["shape_pallas_note"] = "skipped: non-TPU backend"


def bench_wire_streaming(extras: dict) -> None:
    """Frame-forwarding microbench over a real loopback gRPC daemon:
    per-frame unary SendToOnce (the reference's hot loop,
    grpcwire.go:452) vs one client-streaming SendToStream batch — the
    evidence that the streaming egress path beats unary."""
    from kubedtn_tpu.topology import SimEngine, TopologyStore
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient
    from kubedtn_tpu.wire.server import Daemon, make_server

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    daemon = Daemon(engine)
    server, port = make_server(daemon, port=0, host="127.0.0.1")
    server.start()
    client = DaemonClient(f"127.0.0.1:{port}")
    wire = daemon._add_wire(pb.WireDef(
        local_pod_name="w", kube_ns="default", link_uid=1,
        intf_name_in_pod="eth0", peer_ip="10.0.0.2"))
    n = 2000
    pkts = [pb.Packet(remot_intf_id=wire.wire_id, frame=b"f" * 200)
            for _ in range(n)]
    client.SendToOnce(pkts[0])  # warm the channel

    median = statistics.median

    # median-of-N so one scheduler hiccup can't halve the recorded rate
    # (the r3→r4 record moved -48% on this phase with no code change on
    # the measured path — indistinguishable from noise at N=1)
    unary_ss, stream_ss = [], []
    for _ in range(WIRE_REPEATS):
        t0 = time.perf_counter()
        for p in pkts:
            client.SendToOnce(p)
        unary_ss.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        client.SendToStream(iter(pkts))
        stream_ss.append(time.perf_counter() - t0)
    assert len(wire.egress) == 2 * n * WIRE_REPEATS + 1
    unary_s, stream_s = median(unary_ss), median(stream_ss)

    # the coalesced transport the daemons actually use for egress
    # (runtime._flush_remote → SendToBulk): ~256 frames per gRPC message
    # instead of one, which is what lifts the streamed path past the
    # ~25k msg/s Python-gRPC ceiling
    n_bulk, chunk = 100_000, 256
    batches = [pb.PacketBatch(packets=[pkts[0]] * chunk)
               for _ in range(n_bulk // chunk)]
    client.SendToBulk(iter(batches[:4]))  # warm
    bulk_ss = []
    n_bulk_done = 0
    for _ in range(WIRE_REPEATS):
        wire.egress.clear()
        t0 = time.perf_counter()
        client.SendToBulk(iter(batches))
        bulk_ss.append(time.perf_counter() - t0)
        n_bulk_done = len(wire.egress)
        assert n_bulk_done == (n_bulk // chunk) * chunk
    bulk_s = median(bulk_ss)
    client.close()
    server.stop(0)
    extras["wire_unary_frames_per_s"] = round(n / unary_s, 1)
    extras["wire_stream_frames_per_s"] = round(n / stream_s, 1)
    extras["wire_stream_speedup"] = round(unary_s / stream_s, 2)
    extras["wire_bulk_frames_per_s"] = round(n_bulk_done / bulk_s, 1)
    extras["wire_bulk_speedup_vs_stream"] = round(
        (n_bulk_done / bulk_s) / (n / stream_s), 1)
    extras["wire_repeats"] = WIRE_REPEATS
    extras["wire_unary_samples_s"] = [round(s, 4) for s in unary_ss]
    extras["wire_stream_samples_s"] = [round(s, 4) for s in stream_ss]
    extras["wire_bulk_samples_s"] = [round(s, 4) for s in bulk_ss]


def main() -> None:
    global ITERS, SHAPE_ITERS
    t_bench = time.perf_counter()
    extras: dict = {}
    extras["host"] = host_fingerprint()
    phases_done: list[str] = []

    degraded = not probe_backend()
    if degraded:
        extras["backend_probe"] = "failed; forcing CPU fallback"
        os.environ["JAX_PLATFORMS"] = "cpu"
        extras["degraded"] = True
        # a degraded (CPU) run exists to keep the record parseable, not
        # to produce meaningful throughput — shrink the iteration counts
        # so the fallback finishes in minutes
        ITERS = 4
        SHAPE_ITERS = 4

    try:
        import jax
    except Exception as e:  # even a broken install must yield the JSON line
        print(json.dumps({
            "metric": "link-updates/sec", "value": 0.0, "unit": "links/s",
            "vs_baseline": 0.0, "error": f"jax import failed: {e}",
            "extras": extras,
        }))
        sys.exit(1)

    if degraded:
        # the axon TPU-tunnel platform IGNORES JAX_PLATFORMS; only the
        # explicit config update actually pins the CPU backend (and keeps
        # this process from hanging on the dead tunnel). Non-fatal like
        # the cache config below: the env var is already set as a second
        # line of defense.
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception as e:
            log(f"jax_platforms config unavailable: {e!r}")

    # persistent compilation cache: repeat driver runs skip the big
    # scatter/kernel compiles entirely
    try:
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # cache is an optimization, never fatal
        log(f"compilation cache unavailable: {e!r}")

    try:
        extras["backend"] = jax.default_backend()
    except Exception as e:
        extras["backend"] = f"unavailable: {e}"

    if extras.get("backend") == "tpu":
        # Host↔device transfer bandwidth probe: under the axon tunnel
        # the "PCIe" hop is a network link, and transfer-bound phases
        # (scale_1m's control path ships ~100MB of edge-state arrays)
        # inherit ITS bandwidth, not the chip's. Recording the measured
        # rate lets the reader split a slow realize into transfer cost
        # vs host/compute cost instead of guessing.
        try:
            import numpy as _np

            buf = _np.zeros((16 << 20) // 4, _np.float32)  # 16 MB
            dev = jax.device_put(buf)  # warm the path
            jax.block_until_ready(dev)
            t0 = time.perf_counter()
            dev = jax.device_put(buf)
            jax.block_until_ready(dev)
            t_put = time.perf_counter() - t0
            t0 = time.perf_counter()
            _ = _np.asarray(dev)
            t_get = time.perf_counter() - t0
            extras["host"]["device_put_MBps"] = round(16 / t_put, 1)
            extras["host"]["device_get_MBps"] = round(16 / t_get, 1)
        except Exception as e:
            log(f"transfer probe failed: {e!r}")

    def phase(name: str, fn) -> object:
        """with_retry + incremental flush: the partial record on disk is
        always current through the last finished phase. A phase that
        exhausted its retries is recorded as failed, not done — the
        partial file exists to answer 'which evidence is banked'."""
        r = with_retry(name, fn, extras)
        phases_done.append(
            name if f"{name}_error" not in extras else f"{name}:failed")
        flush_partial(extras, phases_done)
        return r

    def run_reconcile():
        from kubedtn_tpu.scenarios import reconcile_100k

        r = reconcile_100k()
        extras["reconcile_100k"] = {
            k: r[k] for k in ("reconcile_s", "churn_s", "teardown_s",
                              "grpc_update_s", "links", "topologies",
                              "device_calls", "meets_target")
        }

    def _isolated_scenario(func: str, kwargs: dict,
                           timeout_s: float = 900.0,
                           env_extra: dict | None = None) -> dict:
        """Run one live-plane scenario in a FRESH subprocess. The live
        phases measure a steady-state plane, but by the time they run,
        this process carries every earlier phase's jit caches, device
        arrays, and allocator high-water — on a small shared host that
        ballast visibly depresses (lat) or decays (tbf) the soak's
        early/late windows, where a standalone run of the identical
        scenario is flat. Isolation makes `python bench.py` report the
        same plane a standalone run measures; the persistent
        compilation cache keeps the fresh process's compile cost near
        zero."""
        src = ("import json, sys\n"
               "from kubedtn_tpu import scenarios\n"
               "r = getattr(scenarios, sys.argv[1])("
               "**json.loads(sys.argv[2]))\n"
               "print('___RESULT___' + json.dumps(r))\n")
        env = dict(os.environ,
                   JAX_COMPILATION_CACHE_DIR=os.path.join(
                       os.path.dirname(os.path.abspath(__file__)),
                       ".jax_cache"),
                   JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="1.0")
        if degraded:
            env["JAX_PLATFORMS"] = "cpu"
        if env_extra:
            env.update(env_extra)
        p = subprocess.run(
            [sys.executable, "-c", src, func, json.dumps(kwargs)],
            capture_output=True, text=True, timeout=timeout_s, env=env)
        for line in reversed(p.stdout.splitlines()):
            if line.startswith("___RESULT___"):
                return json.loads(line[len("___RESULT___"):])
        raise RuntimeError(
            f"{func} subprocess rc={p.returncode}: "
            f"{(p.stderr or p.stdout)[-400:]}")

    def run_live_plane():
        r = _isolated_scenario("live_plane", {
            "pairs": 8,
            "frames_per_wire": 8_000 if degraded else 40_000})
        extras["live_plane"] = {
            k: r[k] for k in ("pairs", "frames_per_wire", "frames_per_s",
                              "frames_per_s_best", "rounds_frames_per_s",
                              "warmup_rounds", "dropped", "tick_errors",
                              "mesh_shape", "shard_imbalance")
            if k in r
        }

    SOAK_KEYS = ("shaping", "injector_chunk", "settle_s", "seconds",
                 "sustained_frames_per_s", "worst_window_frames_per_s",
                 "flatness", "windows_frames_per_s",
                 "end_ingress_backlog", "gc_pause_s", "host_steal_s",
                 "stage_breakdown", "dropped", "tick_errors",
                 "stalled_first_attempt", "mesh_shape",
                 "shard_imbalance")

    def _soak_stall_retry(run):
        """One re-measure when a SINGLE window collapsed ≥25% below the
        median while every other window held within 10% of it: that
        shape is an exogenous host stall (a shared/throttled core lost
        mid-window — invisible to the recorded gc_pause_s/host_steal_s
        when it's cgroup-quota throttling), not plane decay, which
        would show a trend across windows. The stalled measurement is
        kept in the record as evidence, never silently discarded."""
        r = run()
        ws = sorted(r.get("windows_frames_per_s", []))
        med = statistics.median(ws) if ws else 0.0
        if (len(ws) >= 4 and med > 0 and ws[0] < 0.75 * med
                and ws[1] >= 0.9 * med):
            r2 = run()
            r2["stalled_first_attempt"] = {
                k: r[k] for k in ("windows_frames_per_s", "flatness",
                                  "sustained_frames_per_s")}
            return r2
        return r

    def run_live_soak():
        r = _soak_stall_retry(lambda: _isolated_scenario(
            "live_plane_soak",
            {"pairs": 8, "seconds": 12.0 if degraded else 25.0}))
        extras["live_soak"] = {k: r[k] for k in SOAK_KEYS if k in r}

    def run_live_soak_tbf():
        # the SAME sustained soak over RATE-LIMITED wires: before the
        # max-plus TBF batch kernel (round 5), every frame on these
        # wires went through the seq_slots-capped scan — 8 wires ×
        # 6.4-32k frames/s was the aggregate ceiling this record is
        # compared against. 2Gbit per wire ≫ offered load, so the
        # bucket never throttles and the number measures the plane.
        # chunk=512 keeps the offered rate itself below the shaped
        # plane's capacity (the phase's design: keep-up under a token
        # bucket, backlog bounded, not a transport-capacity contest —
        # the lat soak at the full INJECTOR_CHUNK measures capacity).
        r = _soak_stall_retry(lambda: _isolated_scenario(
            "live_plane_soak",
            {"pairs": 8, "rate": "2Gbit",
             "seconds": 12.0 if degraded else 25.0, "chunk": 512}))
        extras["live_soak_tbf"] = {k: r[k] for k in SOAK_KEYS if k in r}

    def run_sharded_soak():
        # MULTICHIP record: the edge-sharded live plane vs the same
        # plane on one device (no-regression headline), plus mesh
        # shape, per-shard imbalance, cross-shard frames/tick and the
        # mailbox/exchange counters. On a TPU backend the mesh is the
        # real chips and the exchange is the Pallas remote-DMA ring;
        # on a CPU host the subprocess forces 8 virtual devices so the
        # mailbox layout/accounting are exercised end to end with the
        # ppermute ring (same bits, no bandwidth claim).
        env_extra: dict = {}
        if extras.get("backend") != "tpu":
            flags = os.environ.get("XLA_FLAGS", "")
            if "--xla_force_host_platform_device_count" not in flags:
                env_extra["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
            env_extra["JAX_PLATFORMS"] = "cpu"
        r = _isolated_scenario(
            "sharded_soak",
            {"pairs": 24 if degraded else 48,
             "frames_per_wire": 2_000 if degraded else 6_000},
            timeout_s=1800.0, env_extra=env_extra)
        extras["sharded_soak"] = {
            k: r[k] for k in (
                "record", "backend", "remote_dma", "pairs", "devices",
                "mesh_shape", "edges_per_shard", "shard_imbalance",
                "colocated_frac", "xshard_frames_total",
                "xshard_frames_per_tick", "mailbox_hwm",
                "exchange_seconds", "single_device_frames_per_s",
                "sharded_frames_per_s", "sharded_over_single",
                "dropped", "tick_errors") if k in r}
        # standalone MULTICHIP record beside the driver's dryrun ones
        try:
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "MULTICHIP_sharded_soak.json"), "w") as f:
                json.dump(r, f, indent=1)
        except OSError as e:
            log(f"MULTICHIP record write failed: {e!r}")

    def run_chaos_soak():
        # fault-domain evidence: peer flapping at 1 Hz under live load
        # must lose ZERO frames (breaker + bounded outage buffer +
        # retry), complete >=1 full breaker recovery cycle, and keep
        # tick_errors at 0 — the robustness counterpart of the
        # throughput soaks above
        r = _isolated_scenario("chaos_soak", {
            "pairs": 4, "seconds": 6.0 if degraded else 12.0,
            "offered_frames_per_s": 8_000 if degraded else 20_000})
        extras["chaos_soak"] = {
            k: r[k] for k in (
                "pairs", "seconds", "flap_hz", "offered_frames_per_s",
                "frames_fed", "frames_delivered", "frames_lost",
                "windows_frames_per_s",
                "sustained_under_flap_frames_per_s", "breaker_cycles",
                "peer_retries", "peer_buffer_dropped", "tick_errors",
                "forward_errors", "degrade_level_end",
                "sampled_frames", "trace_ok", "trace_id",
                "trace_hops", "trace_stages", "trace_nodes",
                "telemetry_windows_closed") if k in r}

    def run_staged_update_soak():
        # planned-update change-gate evidence: a clean delta claims,
        # twin-verifies, and stages through the LIVE plane under load
        # (gate latency, rounds, throughput during staging vs steady),
        # and a regressing delta is rejected by the gate before
        # touching the plane — with zero frame loss across the run.
        # Process-isolated like the other live phases.
        r = _isolated_scenario("staged_update_soak", {
            "pairs": 2 if degraded else 4,
            "steady_s": 2.0 if degraded else 3.0,
            "staging_s": 2.0 if degraded else 3.0,
            "offered_frames_per_s": 4_000 if degraded else 8_000})
        extras["staged_update_soak"] = {
            k: r[k] for k in (
                "pairs", "offered_frames_per_s", "frames_fed",
                "frames_delivered", "frames_lost",
                "steady_frames_per_s", "staging_frames_per_s",
                "staging_over_steady", "clean_plans_verified",
                "clean_plans", "rounds_staged", "rollbacks", "gate_s",
                "stage_s", "regressing_rejected",
                "gate_left_plane_untouched", "tick_errors") if k in r}

    def run_tenant_soak():
        # multi-tenant plane evidence: three QoS-laddered tenants share
        # one live plane (real gRPC server + runner), each with its own
        # out-of-process injector; per-tenant sustained throughput /
        # p99 / admission-throttle counts land in the record, with the
        # bronze tenant capped so enforcement shows under a real
        # runner. Process-isolated like the other live phases.
        r = _isolated_scenario("tenant_soak", {
            "tenants": 3,
            "pairs_per_tenant": 1 if degraded else 2,
            "seconds": 4.0 if degraded else 8.0,
            "budget_fps": 5_000})
        extras["tenant_soak"] = {
            k: r[k] for k in (
                "tenants", "pairs_per_tenant", "seconds",
                "per_tenant", "plane_frames_per_s",
                "throttled_tenant", "dropped", "tick_errors")
            if k in r}

    def run_noisy_neighbor():
        # tenant-isolation chaos evidence: the deterministic
        # aggressor-vs-victim scenario at the bench shape — the
        # aggressor throttled at its admission budget (typed verdicts,
        # frames queued never dropped), the victim with zero loss and
        # p99 inside guardrails. In-process is fine (explicit clock),
        # but isolation keeps earlier phases' ballast out like the
        # other live phases.
        r = _isolated_scenario("noisy_neighbor", {
            "victim_pairs": 1 if degraded else 2,
            "aggressor_pairs": 1 if degraded else 2,
            "seconds": 2.0 if degraded else 4.0})
        extras["noisy_neighbor"] = {
            k: r[k] for k in (
                "victim_fed", "victim_delivered",
                "victim_delivery_ratio", "victim_p99_us",
                "aggressor_fed", "aggressor_admitted",
                "aggressor_budget_fps", "aggressor_queued_not_dropped",
                "throttle_events", "aggressor_throttled_at_budget",
                "victim_unharmed", "in_guardrails", "tick_errors")
            if k in r}

    def run_shm_soak():
        # shared-memory ingest transport evidence: a real producer
        # subprocess streams indexed frames through its ring while the
        # daemon batch-dequeues (one native call + one columnar
        # regroup per drain), with an exact exactly-once index audit.
        # The gRPC ladder (unary/stream/bulk — the compat fallback) is
        # RE-MEASURED inside the same isolated session so the quoted
        # speedups compare the same host at the same moment; the
        # scenario's `caveats` field records the honesty notes
        # (single Python producer = feed-side floor, no shaping —
        # live_plane_soak bounds end-to-end). Process-isolated like
        # the other live phases.
        r = _isolated_scenario("shm_soak", {
            "frames": 100_000 if degraded else 200_000,
            "grpc_stream_n": 8_000 if degraded else 20_000,
            "grpc_bulk_n": 20_000 if degraded else 50_000})
        extras["shm_soak"] = {
            k: r[k] for k in (
                "frames", "frame_size", "shm_frames_ingested",
                "shm_frames_per_s", "shm_bytes_per_s",
                "shm_frames_per_dequeue", "shm_ring_full_failures",
                "shm_audit_exact_once", "grpc_unary_frames_per_s",
                "grpc_stream_frames_per_s", "grpc_bulk_frames_per_s",
                "shm_over_grpc_unary", "shm_over_grpc_stream",
                "shm_over_grpc_bulk", "same_session_grpc_rerun",
                "caveats", "in_guardrails") if k in r}

    def run_shm_producer_crash():
        # shm crash-safety evidence: SIGKILL a real producer mid-burst
        # — zero committed-frame loss (contiguous delivered-index
        # prefix covering every progress report), torn reservations
        # skipped only after the pid provably died, dead ring retired,
        # and a producer-minted trace id spanning the ring.
        r = _isolated_scenario("shm_producer_crash", {})
        extras["shm_producer_crash"] = {
            k: r[k] for k in (
                "frames_target", "reported_at_kill", "delivered",
                "delivered_prefix_ok", "committed_lost",
                "torn_skipped", "rings_retired",
                "ring_traces_spanning", "trace_ok", "tick_errors",
                "dropped", "in_guardrails") if k in r}

    def run_noisy_neighbor_shm():
        # the same tenant-isolation contract with the aggressor on the
        # shm transport: admission evaluated at the RING HEAD, the
        # over-budget backlog parked in the segment — throttled, never
        # dropped, victim untouched.
        r = _isolated_scenario("noisy_neighbor", {
            "victim_pairs": 1 if degraded else 2,
            "aggressor_pairs": 1 if degraded else 2,
            "seconds": 2.0 if degraded else 4.0,
            "aggressor_via_shm": True})
        extras["noisy_neighbor_shm"] = {
            k: r[k] for k in (
                "victim_lost", "aggressor_fed", "aggressor_admitted",
                "aggressor_queued_not_dropped", "aggressor_transport",
                "throttle_events", "shm",
                "aggressor_throttled_at_budget", "victim_unharmed",
                "in_guardrails", "tick_errors") if k in r}

    def run_migration_under_flap():
        # federation evidence: a live tenant migration lands while the
        # src→dst peer breaker cycles — must complete (or roll back)
        # with frames_lost == 0, byte-exact fed == delivered_src +
        # delivered_dst accounting, window-ring totals agreeing with
        # the counter slices on both planes, and the
        # accounting-mismatch gauge at 0. Process-isolated like the
        # other live phases.
        r = _isolated_scenario("migration_under_flap", {
            "pairs": 2,
            "seconds": 4.0 if degraded else 6.0,
            "offered_frames_per_s": 2_000 if degraded else 4_000})
        extras["migration_under_flap"] = {
            k: r[k] for k in (
                "pairs", "seconds", "flap_hz", "offered_frames_per_s",
                "outcome", "steps_done", "resumed", "frames_fed",
                "frames_delivered", "frames_lost",
                "transferred_frames", "accounting",
                "accounting_mismatch_gauge", "ring_totals_agree",
                "step_seconds", "breaker_cycles", "tick_errors",
                "in_guardrails") if k in r}

    def run_plane_failover():
        # fleet-supervision evidence: SIGKILL a loaded plane
        # mid-migration; the supervisor detects death over real gRPC
        # probes (hysteresis), evacuates with no operator action
        # (journal-fork rollforward + checkpoint cold-restore), the
        # restored rows are byte-identical to the capture, and the
        # failover accounting is EXACT — fed == delivered_src +
        # delivered_dst + reported_lost with the mismatch gauge 0.
        r = _isolated_scenario("plane_failover", {"pairs": 2})
        extras["plane_failover"] = {
            k: r[k] for k in (
                "pairs", "fed", "delivered", "delivered_before_kill",
                "gap_frames", "sweeps_to_dead", "evacuation",
                "restored_rows_byte_identical", "accounting",
                "accounting_mismatch_gauge", "reported_lost_gauge",
                "transitions", "in_guardrails") if k in r}

    def run_fleet_rolling_upgrade():
        # zero-loss rolling-upgrade evidence: two real gRPC daemons
        # with live runners; cordon → drain via live migration →
        # restart on the same port → health-verify over the wire →
        # refill, under a retrying producer — every accepted frame
        # delivered (frames_lost == 0), mismatch gauge 0.
        r = _isolated_scenario("fleet_rolling_upgrade", {
            "pairs": 1,
            "steady_s": 1.0 if degraded else 1.5,
            "offered_frames_per_s": 1_000 if degraded else 2_000})
        extras["fleet_rolling_upgrade"] = {
            k: r[k] for k in (
                "pairs", "frames_fed", "frames_delivered",
                "frames_lost", "migrations", "reports",
                "pending_restored", "accounting_mismatch_gauge",
                "migrations_completed", "in_guardrails")
            if k in r}

    def run_telemetry_overhead():
        # observability cost evidence: the SAME plane-only workload
        # with the link-telemetry window ring + flight recorder off vs
        # on at the default 1/256 sampling, rounds interleaved. The
        # acceptance bar is < 5% overhead (telemetry rides the fused
        # dispatch — no extra device calls, no per-tick host sync).
        # Process-isolated like the live phases so earlier phases'
        # ballast can't skew the comparison.
        r = _isolated_scenario("telemetry_overhead", {
            "pairs": 4,
            "frames_per_wire": 8_000 if degraded else 20_000,
            "rounds": 3 if degraded else 5})
        extras["telemetry_overhead"] = {
            k: r[k] for k in (
                "pairs", "frames_per_wire", "rounds", "sample_period",
                "rounds_off_frames_per_s", "rounds_on_frames_per_s",
                "frames_per_s_off", "frames_per_s_on", "overhead_pct",
                "overhead_pct_best", "stalled_first_attempt",
                "meets_5pct_target", "sampled_frames",
                "recorder_events", "telemetry_windows_closed",
                "tick_errors_off", "tick_errors_on") if k in r}

    def run_slo_overhead():
        # SLO-plane cost evidence: the SAME multi-tenant plane-only
        # workload with the SLO evaluator's continuous rollover loop
        # off vs on (telemetry on in BOTH — this isolates evaluation
        # cost from telemetry cost, which telemetry_overhead already
        # measures), rounds interleaved. Acceptance bar < 1%: the
        # evaluator is a sidecar thread doing one counter read per
        # poll and O(tenants) arithmetic per window rollover, never
        # tick-path work. Process-isolated like the live phases.
        r = _isolated_scenario("slo_overhead", {
            "pairs": 3 if degraded else 4,
            "frames_per_wire": 8_000 if degraded else 20_000,
            "rounds": 3 if degraded else 5})
        extras["slo_overhead"] = {
            k: r[k] for k in (
                "pairs", "tenants", "frames_per_wire", "rounds",
                "rounds_off_frames_per_s", "rounds_on_frames_per_s",
                "frames_per_s_off", "frames_per_s_on", "overhead_pct",
                "overhead_pct_best", "stalled_first_attempt",
                "meets_1pct_target", "slo_evaluations",
                "slo_windows_evaluated", "tenants_evaluated",
                "all_ok", "tick_errors_off", "tick_errors_on")
            if k in r}

    def run_pause_observability():
        # pause/stall observability evidence, the two numbers the
        # savail availability budget judges: (1) ledger hook overhead
        # on the plane-only probe, off vs on interleaved, bar < 2%;
        # (2) a live plane under load takes a forced checkpoint
        # (save_live barrier), real churn + compact(), and one staged
        # update through the real stager — every pause attributed in
        # the ledger with cause + duration + rows touched.
        # Process-isolated like the live phases.
        r = _isolated_scenario("pause_observability", {
            "pairs": 4,
            "frames_per_wire": 8_000 if degraded else 20_000,
            "rounds": 3 if degraded else 5,
            "load_frames_per_wire": 10_000 if degraded else 20_000})
        extras["pause_observability"] = {
            k: r[k] for k in (
                "pairs", "frames_per_wire", "rounds",
                "rounds_off_frames_per_s", "rounds_on_frames_per_s",
                "frames_per_s_off", "frames_per_s_on",
                "hook_overhead_pct", "hook_overhead_pct_best",
                "meets_2pct_target", "stalled_first_attempt",
                "load_window_s", "causes", "all_attributed",
                "compact_moved", "staged_rounds", "dropped_events",
                "tick_errors_off", "tick_errors_on") if k in r}
        # standalone record: the artifact `python -m kubedtn_tpu.analysis
        # --scale` (savail rule) gates against — wall_s is the measured
        # load window, causes are the ledger aggregates inside it
        try:
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "BENCH_pauses.json"), "w") as f:
                json.dump({
                    "record": "pause_observability",
                    "note": (
                        "Barrier-pause attribution record "
                        "(process-isolated plane-only probe): a live "
                        "plane under load takes a forced live "
                        "checkpoint, churn + compact(), and one "
                        "staged update; every pause lands in the "
                        "PauseLedger with cause/duration/rows, and "
                        "the ledger's own hook overhead is measured "
                        "off-vs-on (< 2% bar). Checked by the savail "
                        "rule in `python -m kubedtn_tpu.analysis "
                        "--scale` against SCALE_BUDGET.json "
                        "`availability`. Reproduce: python bench.py "
                        "(pause_observability phase) or python -m "
                        "kubedtn_tpu.cli scenario pause_observability."),
                    "host": {
                        "platform": platform.platform(),
                        "cpus": os.cpu_count(),
                    },
                    "when": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime()),
                    "wall_s": r.get("load_window_s"),
                    "hook_overhead_pct": r.get("hook_overhead_pct"),
                    "hook_overhead_pct_best":
                        r.get("hook_overhead_pct_best"),
                    "causes": r.get("causes", {}),
                    "forced": r.get("forced", {}),
                    "all_attributed": r.get("all_attributed"),
                    "tick_hist": r.get("tick_hist", {}),
                    "tick_edges_s": r.get("tick_edges_s", []),
                }, f, indent=1)
        except OSError as e:
            log(f"pause record write failed: {e!r}")

    def run_burn_recovery():
        # SLO-autopilot closed-loop evidence: inject loss on a gold
        # tenant until the fast burn pages, then the autopilot's whole
        # loop on the live plane — candidate grid scored as ONE
        # batched twin sweep (compile/run split recorded), winner
        # gated and staged, burn back below page, and the post-cutover
        # feed delivered in FULL (post_frames_lost == 0). Explicit
        # tick clock, so the record is deterministic per seed.
        # Process-isolated like the other live phases.
        r = _isolated_scenario("burn_recovery", {
            "pairs": 1 if degraded else 2,
            "steps": 120 if degraded else 200,
            "max_polls": 40 if degraded else 60})
        extras["burn_recovery"] = {
            k: r[k] for k in (
                "pairs", "loss_pct", "warm_severity", "paged",
                "page_fast_burn", "searches_run",
                "candidates_evaluated", "sweep_compile_s",
                "sweep_run_s", "staged", "staged_candidate",
                "staged_kind", "plans_staged", "deltas_rolled_back",
                "polls_to_green", "time_to_green_s",
                "recovered_severity", "post_frames_fed",
                "post_frames_delivered", "post_frames_lost",
                "tick_errors", "in_guardrails") if k in r}
        # standalone record beside the shm one: the autopilot's
        # headline evidence, readable without digging through extras
        try:
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "BENCH_autopilot.json"), "w") as f:
                json.dump({
                    "record": "burn_recovery",
                    "note": (
                        "SLO-autopilot closed-loop record "
                        "(process-isolated): injected loss pages the "
                        "gold tenant's fast burn; the autopilot "
                        "searches its candidate grid as one batched "
                        "twin sweep on the tenant snapshot fork, "
                        "stages the gate-approved winner, and the "
                        "burn clears with zero post-cutover frame "
                        "loss. Reproduce: python bench.py "
                        "(burn_recovery phase) or python -m "
                        "kubedtn_tpu.cli scenario burn_recovery."),
                    "host": {
                        "platform": platform.platform(),
                        "cpus": os.cpu_count(),
                    },
                    "when": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime()),
                    "result": r,
                }, f, indent=1)
        except OSError as e:
            log(f"autopilot record write failed: {e!r}")

    def run_whatif_sweep():
        # what-if plane evidence: >=64 perturbed replicas × >=10k virtual
        # ticks advanced by ONE compiled program, recorded as
        # replicas·steps/s plus the compile/run split (the twin engine's
        # AOT cache compiles once per (N, T, capacity) shape).
        # Process-isolated like the live phases so earlier phases'
        # ballast can't depress the measured scan.
        # on a CPU-only host the 640k replica-step scan is op-dispatch
        # bound (~1k replica-steps/s measured) — give it headroom well
        # past the default 900s; the TPU path is data-bound and fast
        r = _isolated_scenario("whatif_sweep", {
            "replicas": 16 if degraded else 64,
            "steps": 2_000 if degraded else 10_000},
            timeout_s=2400.0)
        extras["whatif_sweep"] = {
            k: r[k] for k in (
                "nodes", "links", "replicas", "steps", "compile_s",
                "run_s", "replicas_steps_per_s", "virtual_speedup",
                "baseline_delivery_ratio", "worst_delivery_ratio",
                "baseline_p99_us") if k in r}

    def run_verify_gate():
        # dtnverify trajectory: the jaxpr-layer gate's per-entry-point
        # compiled cost (XLA flops/bytes at the canonical harness
        # shapes) and the fused tick's measured dispatches/tick land in
        # the bench record, so cost drift across PRs is readable from
        # the BENCH_r*.json series, not just pass/fail in tier-1.
        # Subprocess-isolated like the live phases (it builds and ticks
        # its own probe plane).
        out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           ".bench_verify.json")
        try:
            p = subprocess.run(
                [sys.executable, "-m", "kubedtn_tpu.analysis",
                 "--verify", "-q", "--json", out],
                capture_output=True, text=True, timeout=900.0,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            # returncode first: a crashed run writes no artifact, and
            # the traceback in stderr beats a FileNotFoundError
            if p.returncode != 0:
                raise RuntimeError(
                    f"verify gate failed rc={p.returncode}: "
                    f"{(p.stderr or p.stdout)[-400:]}")
            with open(out) as fh:
                doc = json.load(fh)
        finally:
            if os.path.exists(out):
                os.unlink(out)
        j = doc.get("jaxpr", {})
        extras["verify_gate"] = {
            "exit_code": p.returncode,
            "ast_findings": doc.get("summary", {}),
            "jaxpr_findings": j.get("summary", {}),
            "dispatch": j.get("dispatch", {}),
            "entry_costs": {
                name: {k: ep[k] for k in ("flops", "bytes", "eqns")
                       if k in ep}
                for name, ep in j.get("entry_points", {}).items()},
        }

    def run_host_scale():
        # dtnscale empirical half at bench scale: the same probe the
        # tier-1 smoke runs at small sizes, here at 10k/100k/1M rows
        # in a FRESH subprocess (a 1M-row engine's arrays + allocator
        # high-water must not ballast later phases). Fitted host-path
        # slopes land in the record next to the SCALE_BUDGET.json
        # ceilings, so the host-scalability trajectory is readable
        # from the BENCH_r* series like the device-cost one.
        sizes = ([10_000, 50_000] if degraded
                 else [10_000, 100_000, 1_000_000])
        src = ("import json, sys\n"
               "from kubedtn_tpu.analysis.scale.probe import run_probe\n"
               "print('___RESULT___' + json.dumps("
               "run_probe(json.loads(sys.argv[1]))))\n")
        p = subprocess.run(
            [sys.executable, "-c", src, json.dumps(sizes)],
            capture_output=True, text=True, timeout=1800.0,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        r = None
        for line in reversed(p.stdout.splitlines()):
            if line.startswith("___RESULT___"):
                r = json.loads(line[len("___RESULT___"):])
                break
        if r is None:
            raise RuntimeError(
                f"host_scale probe rc={p.returncode}: "
                f"{(p.stderr or p.stdout)[-400:]}")
        # the SAME ceiling resolution the verify gate uses (file values
        # over configured defaults) — the bench record and
        # `--scale` must never disagree about one slope
        from pathlib import Path

        from kubedtn_tpu.analysis.scale import budget as _sbudget

        root = Path(os.path.dirname(os.path.abspath(__file__)))
        ceilings = _sbudget.probe_slopes(_sbudget.load_budget(root))
        extras["host_scale"] = {
            "sizes": r["sizes"],
            "phases": r["phases"],
            "ceilings": ceilings,
            "in_budget": {
                name: ph["slope"] <= ceilings.get(name, float("inf"))
                for name, ph in r["phases"].items()},
        }

    def run_reconverge_10k():
        from kubedtn_tpu.scenarios import reconverge_10k

        r = reconverge_10k(events=2 if degraded else 4)
        extras["reconverge_10k"] = {
            k: r[k] for k in ("nodes", "links", "full_recompute_s",
                              "reconverge_s_steady", "speedup_vs_full",
                              "matches_full_recompute", "flap10_down_s",
                              "flap10_up_s", "flap10_cells")
        }

    def run_scale_1m():
        from kubedtn_tpu.scenarios import reconcile_100k, scale_1m

        r = scale_1m()
        extras["scale_1m"] = {
            k: r[k] for k in ("links", "directed_rows", "load_s",
                              "updates_per_sec", "shape_pkts_per_sec")
        }
        # the FULL control path at 1M links (store → reconciler →
        # engine → device), not just the device primitives: every link
        # enters as a Link in a Topology CR. Round-4 target:
        # realize < 15s.
        c = reconcile_100k(n_spine=200, n_leaf=2500)
        cp = {
            "realize_s": c["reconcile_s"],
            "churn_s": c["churn_s"],
            "teardown_s": c["teardown_s"],
            "device_calls": c["device_calls"],
            "realize_under_15s": c["reconcile_s"] < 15.0,
        }
        if not cp["realize_under_15s"]:
            cp["note"] = ("realize ships ~100MB of edge-state arrays; "
                          "compare host.device_put_MBps — under the "
                          "axon tunnel the device hop is a network "
                          "link, and this phase is transfer-bound")
        extras["scale_1m"]["control_path"] = cp

    # ON-CHIP-ONLY phases run FIRST on a live TPU backend: two rounds of
    # tunnel outages taught that the evidence that can only come from the
    # chip must be banked before anything else gets a chance to outlive
    # the tunnel. (On CPU, shape_step still records the vmapped number.)
    if not degraded:
        phase("shape_step", lambda: bench_shape_step(extras))
        # 10× the BASELINE top rung — scale headroom evidence; skipped on
        # the CPU fallback, where 2M-row device ops would dominate the
        # degraded run's time budget without measuring anything real
        phase("scale_1m", run_scale_1m)

    ups = phase("link_updates", lambda: bench_link_updates(extras))

    if degraded:
        phase("shape_step", lambda: bench_shape_step(extras))
        extras["scale_1m"] = None

    phase("reconcile_100k", run_reconcile)
    phase("wire_streaming", lambda: bench_wire_streaming(extras))
    phase("live_plane", run_live_plane)
    phase("live_soak", run_live_soak)
    phase("live_soak_tbf", run_live_soak_tbf)
    phase("sharded_soak", run_sharded_soak)
    phase("chaos_soak", run_chaos_soak)
    phase("staged_update_soak", run_staged_update_soak)
    phase("tenant_soak", run_tenant_soak)
    phase("noisy_neighbor", run_noisy_neighbor)
    phase("shm_soak", run_shm_soak)
    phase("shm_producer_crash", run_shm_producer_crash)
    phase("noisy_neighbor_shm", run_noisy_neighbor_shm)
    phase("migration_under_flap", run_migration_under_flap)
    phase("plane_failover", run_plane_failover)
    phase("fleet_rolling_upgrade", run_fleet_rolling_upgrade)
    phase("telemetry_overhead", run_telemetry_overhead)
    phase("slo_overhead", run_slo_overhead)
    phase("pause_observability", run_pause_observability)
    phase("burn_recovery", run_burn_recovery)
    phase("whatif_sweep", run_whatif_sweep)
    phase("reconverge_10k", run_reconverge_10k)
    phase("host_scale", run_host_scale)
    phase("verify_gate", run_verify_gate)

    try:
        extras["host"]["loadavg_end"] = [round(x, 2)
                                         for x in os.getloadavg()]
    except OSError:
        pass
    extras["bench_wall_s"] = round(time.perf_counter() - t_bench, 1)
    flush_partial(extras, phases_done)
    if ups is None:
        print(json.dumps({
            "metric": "link-updates/sec", "value": 0.0, "unit": "links/s",
            "vs_baseline": 0.0,
            "error": extras.get("link_updates_error", "unknown"),
            "extras": extras,
        }))
        sys.exit(1)
    print(json.dumps({
        "metric": "link-updates/sec",
        "value": round(ups, 1),
        "unit": "links/s",
        "vs_baseline": round(ups / 1e6, 3),
        "extras": extras,
    }))


def _index_entry(name: str, doc: dict) -> dict:
    """Pull the cross-run key series out of one BENCH record, whatever
    its vintage/shape: full run records ({parsed: {extras}} or
    {extras}), partial snapshots ({phases_done, extras}), and the
    standalone {record, result}/flat records each keep their series
    under a different roof."""
    entry: dict = {"file": name}
    parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
        else None
    body = parsed or doc
    if isinstance(body.get("value"), (int, float)):
        entry["link_updates_per_s"] = body["value"]
    extras = body.get("extras") or doc.get("extras") or {}
    result = doc.get("result") or {}

    def series(src: dict, path: list, out_key: str, rnd: int = 1):
        v = src
        for k in path:
            v = v.get(k) if isinstance(v, dict) else None
            if v is None:
                return
        if isinstance(v, (int, float)):
            entry[out_key] = round(v, rnd)

    series(extras, ["live_soak", "sustained_frames_per_s"],
           "soak_frames_per_s")
    series(extras, ["live_plane", "frames_per_s"], "plane_frames_per_s")
    series(extras, ["telemetry_overhead", "overhead_pct"],
           "telemetry_overhead_pct", 2)
    series(extras, ["slo_overhead", "overhead_pct"],
           "slo_overhead_pct", 2)
    series(extras, ["pause_observability", "hook_overhead_pct"],
           "pause_hook_overhead_pct", 2)
    # host-scale slopes: in-run extras or the standalone record's
    # top-level `phases`
    phases = ((extras.get("host_scale") or {}).get("phases")
              or (doc.get("phases") if doc.get("record") ==
                  "host_scale_1m" or "in_budget" in doc else None))
    if isinstance(phases, dict):
        slopes = {n: round(ph["slope"], 3) for n, ph in phases.items()
                  if isinstance(ph, dict)
                  and isinstance(ph.get("slope"), (int, float))}
        if slopes:
            entry["host_scale_slopes"] = slopes
    # pause totals: the standalone pause record (or this run's extras)
    causes = (doc.get("causes")
              or (extras.get("pause_observability") or {}).get("causes"))
    if isinstance(causes, dict) and causes:
        entry["pause_seconds_by_cause"] = {
            c: round(float(s.get("seconds", 0.0)), 4)
            for c, s in causes.items() if isinstance(s, dict)}
        entry["pause_seconds_total"] = round(sum(
            entry["pause_seconds_by_cause"].values()), 4)
    for k in ("record", "when", "note"):
        if k in doc and k != "note":
            entry[k] = doc[k]
    if isinstance(doc.get("n"), int):
        entry["run"] = doc["n"]
    return entry


def history() -> int:
    """`python bench.py --history`: index every banked BENCH_*.json
    into BENCH_INDEX.json — one entry per record with the key series
    (soak frames/s, plane probe, host_scale slopes, pause totals,
    overhead pcts), sorted by run — so cross-PR trajectory questions
    read from one file instead of N shapes."""
    here = os.path.dirname(os.path.abspath(__file__))
    entries = []
    skipped = []
    for name in sorted(os.listdir(here)):
        if (not name.startswith("BENCH_") or not name.endswith(".json")
                or name in ("BENCH_INDEX.json", "BENCH_partial.json")):
            continue
        try:
            with open(os.path.join(here, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            skipped.append({"file": name, "error": repr(e)})
            continue
        entries.append(_index_entry(name, doc))
    # run-numbered records first in run order, then the standalone
    # records alphabetically — "sorted by run"
    entries.sort(key=lambda e: (0, e["run"]) if "run" in e
                 else (1, e["file"]))
    out = {
        "note": ("Cross-run bench index, regenerated by `python "
                 "bench.py --history` — key series per BENCH_* "
                 "record; see each source file for full evidence."),
        "generated": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "records": entries,
        **({"skipped": skipped} if skipped else {}),
    }
    path = os.path.join(here, "BENCH_INDEX.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps({"indexed": len(entries),
                      "skipped": len(skipped), "path": path}))
    return 0


if __name__ == "__main__":
    if "--history" in sys.argv[1:]:
        sys.exit(history())
    main()
