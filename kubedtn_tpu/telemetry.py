"""Link telemetry plane — per-edge window ring, sampled frame flight
recorder, and cross-node trace correlation.

The reference daemon exports aggregate latency histograms and interface
counters only (reference daemon/metrics/): answering "why did THIS flow
degrade two minutes ago?" needs out-of-band tcpdump. This module gives
the TPU plane the primitive tail diagnosis actually needs — per-link
time-series — plus a sampled per-frame lifecycle record:

- **Per-edge window ring** (`LinkTelemetry`): the fused tick reduces
  per-edge delivered / bytes / drop-by-cause / latency-sum + bucket
  counts into an on-device `[E, KCOLS]` accumulator that is CHAINED
  through in-flight dispatches exactly like the dynamic edge-state
  columns — no per-tick host sync. Once per window (wall-clock
  `window_s`, checked at dispatch under the tick lock) the open
  accumulator is swapped into a bounded ring of `windows` closed
  windows and a fresh zero accumulator starts; a closed window's device
  array is only materialized to the host lazily, on first query, so
  the drain is amortized and off the tick critical path. Logical
  layout: a `[W, E, KCOLS]` ring of per-window per-edge stat rows.
- **Drop-cause taxonomy**: the `[R, K]` drop masks the shaping kernels
  compute (netem loss vs TBF 50ms-queue overflow, see
  `ops/netem.cause_codes`) are accumulated PER CAUSE instead of
  collapsing into one `dropped` total; the partition invariant
  (delivered + dropped_loss + dropped_queue == offered, exactly) is
  pinned by tests/test_drop_causes.py.
- **Flight recorder** (`FlightRecorder`): a deterministic sampled
  subset of frames carries a compact lifecycle record — ingress →
  classify/bypass → kernel-class → shaped → delivered/dropped(cause) —
  into a bounded host ring. Sampling contract: frames are counted per
  edge row in drain order, and the i-th frame ever drained onto row r
  is sampled iff `(i + phase(r)) % period == 0` with
  `phase(r) = (r * 2654435761) % period` — arithmetic on counters, no
  per-frame hashing on the hot path, and a fixed (row, index) schedule
  that replays exactly for a deterministic drain order.
- **Cross-node correlation**: a sampled frame's 64-bit trace id rides
  the peer gRPC hop in `Packet.trace_id` (wire/proto.py field 3 — an
  extension reference-built daemons simply skip as an unknown field),
  so the sender's outage-buffered/retried/sent events and the remote
  daemon's received/delivered events attach to the SAME trace;
  `merge_trace` reconstructs the hop-by-hop path from both daemons'
  recorders (the `cli trace` verb).

The latency bucket ladder is the reference daemon's request-duration
ladder (metrics.BUCKETS, milliseconds) scaled to µs — the SAME
reduction the what-if plane's replica sweeps use (twin/engine.py
imports the edges and the histogram_quantile percentiles from here).
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from kubedtn_tpu.contracts import guarded_by
from kubedtn_tpu.metrics.metrics import BUCKETS

# Latency histogram bin upper edges in µs — the reference bucket ladder
# scaled to the data plane's native unit, one overflow bin at the end.
BUCKET_EDGES_US = tuple(float(b) * 1000.0 for b in BUCKETS[1:])
N_BINS = len(BUCKET_EDGES_US) + 1

# -- window-ring column layout (the K axis of the [W, E, K] ring) ------
T_TX = 0           # slots offered to the shaping kernels
T_DELIVERED = 1    # left the qdisc chain
T_BYTES = 2        # delivered bytes
T_DROP_LOSS = 3    # netem loss
T_DROP_QUEUE = 4   # TBF 50ms-queue overflow
T_CORRUPT = 5      # delivered but corrupt-flagged
T_LAT_SUM_US = 6   # sum of delivered shaping latency (µs)
T_QDEPTH = 7       # frames deferred to the holdback buffer (queue depth)
T_HIST0 = 8        # first latency bucket; N_BINS buckets follow
KCOLS = T_HIST0 + N_BINS

COLUMN_NAMES = ("tx", "delivered", "bytes", "dropped_loss",
                "dropped_queue", "corrupted", "latency_sum_us",
                "queue_depth") + tuple(
                    f"lat_le_{int(e / 1000)}ms" for e in BUCKET_EDGES_US
                ) + ("lat_overflow",)

# -- per-slot cause codes (see ops/netem.cause_codes) ------------------
CAUSE_INVALID = 0    # padding / inactive lane
CAUSE_DELIVERED = 1
CAUSE_LOSS = 2       # netem loss
CAUSE_QUEUE = 3      # TBF queue overflow
CAUSE_NAMES = {CAUSE_INVALID: "invalid", CAUSE_DELIVERED: "delivered",
               CAUSE_LOSS: "dropped_loss", CAUSE_QUEUE: "dropped_queue"}


def tel_matrix(sizes, valid, res, row_counts=None):
    """The per-row `[R, KCOLS]` window contribution of one shaped group
    — the compute half of `tel_accumulate`, split out so the SHARDED
    fused tick can compute the matrix replicated and scatter only each
    shard's owned rows into its local accumulator slice (runtime
    `_make_sharded_fused`). Bitwise: the adds that land on a row are
    identical to the unsharded scatter's."""
    import jax.numpy as jnp

    f32 = jnp.float32
    deliv = res.delivered.astype(f32)
    vald = valid.astype(f32)
    # delivered lanes' depart is finite; dropped lanes are +inf — the
    # where() keeps inf out of the sums (inf * 0 would be nan)
    lat = jnp.where(res.delivered, res.depart_us, 0.0)
    if row_counts is not None:
        loss_r, queue_r, corr_r = row_counts
    else:
        loss_r = res.dropped_loss.astype(f32).sum(1)
        queue_r = res.dropped_queue.astype(f32).sum(1)
        corr_r = res.corrupted.astype(f32).sum(1)
    # per-row CUMULATIVE bucket counts from `lat` (already 0 for
    # non-delivered lanes): ONE compare+reduce per edge — the masked
    # lanes all land at 0 <= edge_j, so subtracting the per-row
    # non-delivered count (a scalar) corrects every cumulative at once.
    # This is half the elementwise work of comparing depart & delivered
    # per lane; per-bin counts are first differences (overflow bin =
    # delivered_total - last cumulative).
    edges = jnp.asarray(BUCKET_EDGES_US, f32)
    deliv_total = deliv.sum(1)
    not_deliv = jnp.float32(res.delivered.shape[1]) - deliv_total
    cum = (lat[..., None] <= edges).sum(axis=1).astype(f32) \
        - not_deliv[:, None]                               # [R, 11]
    hist = jnp.concatenate(
        [cum[:, :1], cum[:, 1:] - cum[:, :-1],
         (deliv_total - cum[:, -1])[:, None]], axis=1)  # [R, N_BINS]
    return jnp.concatenate([jnp.stack([
        vald.sum(1),
        deliv_total,
        (sizes * deliv).sum(1),
        loss_r,
        queue_r,
        corr_r,
        lat.sum(1),
        jnp.zeros_like(deliv_total),               # T_QDEPTH: host-side
    ], axis=1), hist], axis=1)                     # [R, KCOLS]


def tel_accumulate(acc, row_idx, sizes, valid, res, row_counts=None):
    """Fold one shaped group's results into the open window accumulator
    — traced INSIDE the fused tick (and the ladder's per-class
    dispatches), so telemetry rides the existing device program with no
    extra dispatch and no host sync. `acc` is the `[E, KCOLS]` open
    window; `row_idx` `[R]` (padding rows index >= E and drop out of
    every scatter); `sizes`/`valid` `[R, K]`; `res` the group's
    ShapeResult with `[R, K]` leaves; `row_counts` the fused tick's
    already-reduced (loss[R], queue[R], corrupt[R]) sums — passing them
    reuses the transfer-set reductions instead of re-reducing (XLA
    would CSE anyway; this keeps the dependency explicit). Returns the
    advanced accumulator.

    Cost discipline (the <5% overhead acceptance): everything here is
    elementwise compare/reduce over the class's [R, K] batch plus ONE
    [R]-indexed row scatter — no [R, K] scatters (XLA lowers element
    scatters to a serial loop on CPU: ~0.5 ms/tick at K=4096, the
    whole overhead budget) and no searchsorted (its binary-search
    gather measured 2× the cost of comparing against all 11 edges)."""
    mat = tel_matrix(sizes, valid, res, row_counts=row_counts)
    # ONE row-indexed scatter-add per class (padding rows drop)
    return acc.at[row_idx].add(mat, mode="drop")


def tel_row_host(sizes, valid, delivered, depart_us) -> np.ndarray:
    """Host-side twin of `tel_accumulate` for ONE row: the `[KCOLS]`
    contribution of (sizes[K], valid[K], delivered[K], depart_us[K]).
    Used to patch windows for the rare TBF-fallback re-shapes, whose
    exact results only exist host-side at completion (the device
    accumulation masked those rows out / saw stale results).
    `dropped_loss`/`dropped_queue`/`corrupted` legs are passed by the
    caller via `extra` columns because the fallback path only has the
    per-row sums."""
    out = np.zeros(KCOLS, np.float64)
    v = np.asarray(valid, bool)
    d = np.asarray(delivered, bool) & v
    dep = np.asarray(depart_us, np.float64)
    out[T_TX] = v.sum()
    out[T_DELIVERED] = d.sum()
    out[T_BYTES] = float(np.asarray(sizes, np.float64)[d].sum())
    lat = dep[d]
    out[T_LAT_SUM_US] = float(lat.sum())
    if lat.size:
        bidx = np.minimum(np.searchsorted(BUCKET_EDGES_US, lat,
                                          side="left"), N_BINS - 1)
        np.add.at(out, T_HIST0 + bidx, 1.0)
    return out


def quantile_label(q: float) -> str:
    """Stable dict-key stem for a quantile: 0.5 → "p50", 0.99 → "p99",
    0.999 → "p99_9". The historical `int(q * 100)` naming is preserved
    for every quantile it could represent; finer quantiles (the SLO
    plane's p99.9 / p99.99) get an unambiguous suffix instead of
    silently colliding with p99."""
    s = f"{q * 100:.10g}"
    return "p" + s.replace(".", "_")


def percentiles_from_hist(hist_row: np.ndarray,
                          qs=(0.5, 0.9, 0.99)) -> dict:
    """histogram_quantile over the reference bucket ladder: linear
    interpolation inside a bin, None when the histogram is empty. The
    ONE percentile implementation shared by the what-if plane's sweep
    metrics (twin/engine.py) and the link telemetry query surface.

    CENSORING: the top bucket is OPEN (everything slower than the last
    edge lands there), so a quantile whose target mass falls inside it
    is unknowable from the histogram alone — Prometheus semantics CLAMP
    it to the last edge, which silently UNDERSTATES the tail. The clamp
    is kept (callers compare against historical series), but every
    quantile now carries a companion `<p>_censored` flag: True means
    "the real value is ≥ this, render it `>Xms`, never X". The SLO
    plane's `slo.tail.estimate_quantile` fits the upper buckets'
    log-survival slope to estimate PAST the edge when the flag would
    be set (ARCHITECTURE.md "SLO plane")."""
    edges = np.asarray(BUCKET_EDGES_US)
    total = float(np.asarray(hist_row).sum())
    out = {}
    for q in qs:
        stem = quantile_label(q)
        key = f"{stem}_us"
        cens = f"{stem}_censored"
        if total <= 0:
            out[key] = None
            out[cens] = False
            continue
        target = q * total
        cum = np.cumsum(hist_row)
        b = int(np.searchsorted(cum, target, side="left"))
        if b >= len(edges):
            out[key] = float(edges[-1])
            out[cens] = True
            continue
        lo = 0.0 if b == 0 else float(edges[b - 1])
        hi = float(edges[b])
        below = 0.0 if b == 0 else float(cum[b - 1])
        inbin = float(hist_row[b])
        frac = 0.0 if inbin <= 0 else (target - below) / inbin
        out[key] = round(lo + (hi - lo) * frac, 3)
        out[cens] = False
    return out


class _Window:
    """One closed window of the ring: the device accumulator it closed
    with (materialized lazily, then the device reference is dropped)
    plus a sparse host-side patch for completion-time corrections."""

    __slots__ = ("start_s", "end_s", "dev", "patch", "_np")

    def __init__(self, start_s: float, end_s: float, dev,
                 patch: dict) -> None:
        self.start_s = start_s
        self.end_s = end_s
        self.dev = dev
        self.patch = patch  # {(row, col): float} sparse corrections
        self._np: np.ndarray | None = None

    def arr(self) -> np.ndarray:
        # lock-free against concurrent query threads (scrape +
        # ObserveLinks + cli top can all race here): read `dev` into a
        # local BEFORE the cache check resolves, publish `_np` BEFORE
        # clearing `dev` — two racers at worst both materialize the
        # same value; neither can ever see dev=None with _np unset
        a = self._np
        if a is not None:
            return a
        dev = self.dev
        if dev is None:  # another thread just finished publishing
            return self._np
        a = np.asarray(dev, np.float32).astype(np.float64)
        for (r, c), v in self.patch.items():
            if r < a.shape[0]:
                a[r, c] += v
        self._np = a
        self.dev = None  # release device memory once drained
        return a


@guarded_by("_lock", "_acc", "_patch", "_start_s", "_now_s", "_ring",
            "windows_closed")
class LinkTelemetry:
    """The per-edge window ring's host-side controller. The plane calls
    `open_acc()` at every dispatch (under the tick lock) to fetch the
    device accumulator the fused tick chains through, and `set_acc()`
    with the dispatch's returned accumulator; window rollover happens
    inside `open_acc()` on the dispatch clock, so every dispatch's
    reductions land wholly in one window. Queries (`window_sum`,
    `link_rows`) run on other threads and only touch closed windows
    plus an immutable snapshot of the open accumulator."""

    def __init__(self, capacity: int, window_s: float = 1.0,
                 windows: int = 12) -> None:
        import jax.numpy as jnp

        self.window_s = float(window_s)
        self.windows = int(windows)
        self._lock = threading.Lock()
        self._acc = jnp.zeros((capacity, KCOLS), jnp.float32)
        self._patch: dict = {}
        self._start_s: float | None = None
        self._now_s: float | None = None
        self._ring: deque[_Window] = deque(maxlen=self.windows)
        self.windows_closed = 0

    @property
    def capacity(self) -> int:
        with self._lock:  # _acc is swapped under the lock (rollover/grow)
            return self._acc.shape[0]

    # -- tick-path API (tick lock held by the caller) ------------------

    def open_acc(self, now_s: float, capacity: int):
        """The open window's device accumulator for this dispatch,
        rolling the window over / resizing for engine growth first."""
        import jax.numpy as jnp

        with self._lock:
            if self._acc.shape[0] != capacity:
                grown = jnp.zeros((capacity, KCOLS), jnp.float32)
                if self._acc.shape[0] < capacity:
                    grown = grown.at[:self._acc.shape[0]].set(self._acc)
                self._acc = grown
            if self._start_s is None:
                self._start_s = now_s
            elif now_s - self._start_s >= self.window_s:
                # the closed window ENDS at the last tick observed
                # inside it, not at this (possibly much later) clock —
                # an idle gap must not inflate covered_seconds and
                # deflate the reported rates
                end = self._now_s if self._now_s is not None else now_s
                end = min(max(end, self._start_s), now_s)
                self._ring.append(_Window(self._start_s, end,
                                          self._acc, self._patch))
                self.windows_closed += 1
                self._acc = jnp.zeros((capacity, KCOLS), jnp.float32)
                self._patch = {}
                self._start_s = now_s
            self._now_s = now_s
            return self._acc

    def touch(self, now_s: float) -> None:
        """Advance the window clock on an idle tick (nothing
        dispatched): without this a quiet plane would hold one window
        open forever and rates would divide by a stale span."""
        with self._lock:
            started = self._start_s is not None
        if started:  # open_acc re-checks under the lock; a racing
            self.open_acc(now_s, self.capacity)  # first-dispatch wins

    def set_acc(self, acc) -> None:
        with self._lock:
            self._acc = acc

    def patch_add(self, row: int, col: int, val: float) -> None:
        """Sparse completion-time correction into the OPEN window (TBF
        fallback re-shapes, holdback queue depth). ±1-window attribution
        skew vs the device adds is possible when a correction lands just
        after rollover — documented, bounded, and never lost."""
        if not val:
            return
        with self._lock:
            key = (int(row), int(col))
            self._patch[key] = self._patch.get(key, 0.0) + float(val)

    def patch_row(self, row: int, cols: np.ndarray) -> None:
        for c in range(KCOLS):
            if cols[c]:
                self.patch_add(row, c, float(cols[c]))

    def remap_rows(self, old_rows, n_active: int, capacity: int) -> None:
        """Carry the ring through compact()'s row renumbering (the same
        permutation the plane applies to its cumulative counters). The
        caller has already flushed the pipeline, so materializing the
        open accumulator here is safe and rare."""
        import jax.numpy as jnp

        sel = np.asarray(old_rows[:n_active], np.int64)

        def permute(a: np.ndarray) -> np.ndarray:
            out = np.zeros((capacity, KCOLS), a.dtype)
            keep = sel < a.shape[0]
            idx = np.nonzero(keep)[0]
            out[idx] = a[sel[keep]]
            return out

        with self._lock:
            # np.array (copy!) — np.asarray of a device array is a
            # READ-ONLY view and the patch fold-in below writes
            acc = np.array(self._acc, np.float32)
            for (r, c), v in self._patch.items():
                if r < acc.shape[0]:
                    acc[r, c] += v
            self._patch = {}
            self._acc = jnp.asarray(permute(acc))
            for w in self._ring:
                w._np = permute(w.arr())

    # -- query API -----------------------------------------------------

    def window_sum(self, last: int | None = None,
                   include_open: bool = True):
        """(per-edge stats summed over the newest `last` closed windows
        [+ the open one], covered wall seconds). The open accumulator is
        an immutable chain head: np.asarray blocks the QUERY thread
        until its value is ready, never the tick."""
        with self._lock:
            wins = list(self._ring)
            acc = self._acc if include_open else None
            patch = dict(self._patch) if include_open else {}
            start = self._start_s
            now = self._now_s
        if last is not None:
            wins = wins[-last:]
        cap = self.capacity
        total = np.zeros((cap, KCOLS), np.float64)
        seconds = 0.0
        for w in wins:
            a = w.arr()
            total[:a.shape[0]] += a[:cap]
            seconds += w.end_s - w.start_s
        if acc is not None and start is not None and now is not None:
            a = np.asarray(acc, np.float64)
            for (r, c), v in patch.items():
                if r < a.shape[0]:
                    a[r, c] += v
            total[:a.shape[0]] += a[:cap]
            seconds += max(now - start, 0.0)
        return total, seconds

    def link_rows(self, engine, last: int | None = None,
                  limit: int = 10_000):
        """Ranked per-link rows for the query surfaces (`cli top`,
        `Local.ObserveLinks`, the `kubedtn_link_*` collector): one dict
        per realized link end with traffic in the covered windows,
        busiest first, truncated to `limit` via the engine's own
        metrics snapshot (the InterfaceStatsCollector truncation-guard
        pattern). Returns (rows, covered_seconds, truncated)."""
        total, seconds = self.window_sum(last=last)
        snapshot, total_active, _rows = engine.metrics_snapshot(
            limit=limit)
        truncated = max(0, total_active - len(snapshot))
        out = []
        secs = max(seconds, 1e-9)
        for pod_key, uid, row, _rev in snapshot:
            if row >= total.shape[0]:
                continue
            t = total[row]
            if not t[T_TX] and not t[T_QDEPTH]:
                continue
            ns, _, pod = pod_key.partition("/")
            delivered = float(t[T_DELIVERED])
            pcts = percentiles_from_hist(t[T_HIST0:],
                                         qs=(0.5, 0.99))
            out.append({
                "pod": pod, "namespace": ns, "uid": int(uid),
                "row": int(row),
                "tx": float(t[T_TX]),
                "delivered": delivered,
                "delivered_pps": delivered / secs,
                "bytes_ps": float(t[T_BYTES]) / secs,
                "dropped_loss": float(t[T_DROP_LOSS]),
                "dropped_queue": float(t[T_DROP_QUEUE]),
                "corrupted": float(t[T_CORRUPT]),
                "queue_depth": float(t[T_QDEPTH]),
                "mean_lat_us": (float(t[T_LAT_SUM_US]) / delivered
                                if delivered else None),
                "p50_us": pcts["p50_us"],
                "p99_us": pcts["p99_us"],
                # censored = the quantile clamped at the open top
                # bucket's edge (render `>Xms`, never X)
                "p99_censored": pcts["p99_censored"],
            })
        out.sort(key=lambda r: -r["delivered_pps"])
        return out, seconds, truncated


# -- sampled frame flight recorder -------------------------------------

# lifecycle stage names (the docs' state machine):
#   ingress → [bypass] → shaped → delivered | dropped
#   cross-node tail: staged-peer → [outage-buffered → retried]* →
#   peer-sent ∥ received → delivered-remote
ST_INGRESS = "ingress"
ST_BYPASS = "bypass"
ST_SHAPED = "shaped"
ST_DELIVERED = "delivered"
ST_DROPPED = "dropped"
ST_STAGED = "staged-peer"
ST_OUTAGE = "outage-buffered"
ST_RETRIED = "retried"
ST_SENT = "peer-sent"
ST_RECEIVED = "received"
ST_DELIVERED_REMOTE = "delivered-remote"
ST_REQUEUED = "requeued"
ST_EGRESS_DROP = "dropped-egress"

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1
_GOLDEN64 = 0x9E3779B97F4A7C15


def _fnv64(*ints) -> int:
    """FNV-1a over the ints' bytes — init-time only (node-name hash);
    the per-frame id path uses the O(1) `_mix64`."""
    h = _FNV64_OFFSET
    for v in ints:
        v &= _MASK64
        while True:
            h = ((h ^ (v & 0xFF)) * _FNV64_PRIME) & _MASK64
            v >>= 8
            if not v:
                break
    return h


def _mix64(x: int) -> int:
    """splitmix64 finalizer: a handful of arithmetic ops per id (the
    byte-looped FNV measured ~3µs/id in pure Python — at default
    sampling that alone was ~1% of the plane)."""
    x &= _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


@guarded_by("_lock", "_seq", "sampled", "recorded")
class FlightRecorder:
    """Bounded host ring of lifecycle events for a deterministic sampled
    subset of frames (module docstring has the sampling contract).
    `record` is append-to-deque cheap and thread-safe (tick thread,
    per-peer sender threads, and gRPC workers all write)."""

    def __init__(self, node: str = "", sample_period: int = 256,
                 capacity: int = 65_536, seed: int = 0) -> None:
        self.node = node or "local"
        self.period = max(1, int(sample_period))
        self.seed = int(seed)
        self._node_h = _fnv64(*self.node.encode()) ^ self.seed
        self._seq: dict[int, int] = {}      # row -> frames seen
        self._lock = threading.Lock()
        self.events: deque = deque(maxlen=int(capacity))
        self.sampled = 0      # frames that entered the recorder
        self.recorded = 0     # events appended (incl. remote-origin)

    # -- sampling ------------------------------------------------------

    def _phase(self, row: int) -> int:
        return (row * 2654435761) % self.period

    def trace_id(self, row: int, seq: int) -> int:
        tid = _mix64(self._node_h ^ (row * _GOLDEN64 + seq))
        return tid or 1  # 0 means "untraced" on the wire

    def sample_batch(self, row: int, m: int) -> list[tuple[int, int]]:
        """Advance row `row`'s frame counter by `m` and return
        [(offset_in_batch, trace_id)] for the sampled frames — pure
        counter arithmetic, no per-frame work."""
        with self._lock:
            s0 = self._seq.get(row, 0)
            self._seq[row] = s0 + m
        first = (-(s0 + self._phase(row))) % self.period
        out = [(off, self.trace_id(row, s0 + off))
               for off in range(first, m, self.period)]
        if out:
            with self._lock:
                self.sampled += len(out)
        return out

    def unsample_batch(self, row: int, m: int, sampled: int) -> None:
        """Roll a sample_batch back (a failed dispatch requeues its
        undecided frames to the FRONT of their ingress deques): the
        next drain re-counts the same physical frames at the same
        global indices, so the determinism contract — and the trace
        ids already minted — replay exactly instead of double
        advancing."""
        with self._lock:
            self._seq[row] = max(0, self._seq.get(row, 0) - m)
            self.sampled -= sampled

    # -- events --------------------------------------------------------

    def record(self, trace_id: int, stage: str, **detail) -> None:
        self.events.append((trace_id, time.time(), self.node, stage,
                            detail))
        with self._lock:  # += is not atomic; writers span many threads
            self.recorded += 1

    def events_for(self, trace_id: int) -> list:
        tid = int(trace_id)
        return [e for e in list(self.events) if e[0] == tid]

    def recent_traces(self, limit: int = 50) -> list[int]:
        """Newest distinct trace ids, most recent first."""
        out: list[int] = []
        seen: set[int] = set()
        for e in reversed(list(self.events)):
            if e[0] not in seen:
                seen.add(e[0])
                out.append(e[0])
                if len(out) >= limit:
                    break
        return out

    def export(self, trace_id: int = 0, limit: int = 1000) -> list[dict]:
        """Events as dicts for the wire (trace_id=0: newest `limit`)."""
        if trace_id:
            evs = self.events_for(trace_id)
        else:
            evs = list(self.events)[-limit:]
        return [{"trace_id": t, "t": ts, "node": node, "stage": stage,
                 "detail": dict(detail)}
                for t, ts, node, stage, detail in evs[:limit]]


def merge_trace(trace_id: int, *event_sources) -> list[dict]:
    """Reconstruct one trace's hop-by-hop path from any number of
    sources (FlightRecorder instances or already-exported dict lists),
    time-ordered — the shared core of `cli trace` and the chaos-soak
    trace assertion."""
    tid = int(trace_id)
    merged: list[dict] = []
    for src in event_sources:
        if isinstance(src, FlightRecorder):
            merged.extend(src.export(tid))
        else:
            merged.extend(e for e in src if int(e["trace_id"]) == tid)
    merged.sort(key=lambda e: e["t"])
    return merged


def find_cross_node_trace(rec_a: FlightRecorder, rec_b: FlightRecorder,
                          require=(ST_INGRESS, ST_OUTAGE, ST_RETRIED,
                                   ST_SENT)) -> tuple[int, list[dict]]:
    """First sampled trace whose A-side path contains every stage in
    `require` AND that node B received — the chaos soak's proof that the
    recorder survives the fault path. Returns (trace_id, merged path),
    or (0, []) when none qualifies."""
    b_received = {e[0] for e in list(rec_b.events)
                  if e[3] in (ST_RECEIVED, ST_DELIVERED_REMOTE)}
    stages_by_tid: dict[int, set] = {}
    for e in list(rec_a.events):
        stages_by_tid.setdefault(e[0], set()).add(e[3])
    for tid, stages in stages_by_tid.items():
        if tid in b_received and all(s in stages for s in require):
            return tid, merge_trace(tid, rec_a, rec_b)
    return 0, []


def render_trace(path: list[dict], header: str | None = None) -> str:
    """Human-readable hop-by-hop rendering of a merged trace — ONE
    renderer for the in-process form (detail dicts) and the wire form
    (detail already stringified by ObserveTrace); `cli trace` and the
    chaos tooling both use it."""
    if not path:
        return "(no events)"
    t0 = path[0]["t"]
    lines = [header if header is not None
             else f"trace {path[0]['trace_id']:#018x}"]
    for e in path:
        d = e["detail"]
        det = (d if isinstance(d, str)
               else " ".join(f"{k}={v}" for k, v in sorted(d.items())))
        lines.append(f"  +{(e['t'] - t0) * 1e3:9.3f}ms  "
                     f"{e['node']:<22} {e['stage']:<18} {det}")
    return "\n".join(lines)
