"""Multi-tenant serving plane — many topologies, one shared SoA.

The ROADMAP's "millions of users" means many independent topologies
sharing ONE high-performance data plane, not one big topology. This
package generalizes the engine/runtime to a tenant axis, following the
composable per-tenant claims-over-a-shared-plane API shape of the
Kubernetes Network Driver Model (PAPERS.md, arxiv 2506.23628):

- **TenantRegistry** (registry.py): tenants map namespaces to a QoS
  class, admission quotas and (optionally) a reserved CONTIGUOUS edge
  block in the shared SoA, carved with parallel.partition.tenant_block
  so tenant blocks compose with shard blocks — a block that fits inside
  one shard never pays the cross-shard mailbox for intra-tenant hops.
- **AdmissionController** (admission.py): host-side token buckets per
  tenant (frames/s + bytes/s) enforced at the DRAIN stage — an
  over-budget tenant's wires are skipped for the tick with a typed,
  metered ThrottleVerdict; frames stay queued, never silently dropped.
- **QoS classes** gold/silver/bronze map onto drain-budget priority
  (per-tick drain share weights 1 / 0.5 / 0.25) over the existing
  shaping kernels — a bronze tenant's wires drain at a quarter of the
  budget a gold tenant's do under contention.
- **Per-tenant observability**: the telemetry window ring and the
  plane's cumulative counters slice per tenant (row sets derived from
  the engine registries, exact through compact()'s renumbering) into
  `kubedtn_tenant_*` Prometheus series, `Local.Tenant*` RPCs and
  `kdt tenant`.

The headline ISOLATION CONTRACT: a tenant's delivered byte stream and
telemetry totals in a cohabited plane are BYTE-IDENTICAL to a solo
plane running only that tenant's topology with the same seed. The
mechanism is per-row fold_in keys (ops/netem.row_keys, keyed by
engine.link_key_id): a row's uniforms depend on the link's declared
identity, never on batch composition. Pinned cohabited-vs-solo at
pipeline depths 1 and 2, unsharded and on an 8-device mesh
(tests/test_tenant_isolation.py); dtnverify's `jtenant` pass audits
the compiled tick for cross-tenant scatter index arithmetic.
"""

from kubedtn_tpu.tenancy.admission import (AdmissionController,
                                           HostTokenBucket,
                                           ThrottleVerdict)
from kubedtn_tpu.tenancy.registry import (QOS_CLASSES, QOS_LEVELS,
                                          Tenant, TenantRegistry)

__all__ = [
    "QOS_CLASSES", "QOS_LEVELS", "Tenant", "TenantRegistry",
    "AdmissionController", "HostTokenBucket", "ThrottleVerdict",
]
