"""Per-tenant admission control — host-side token buckets at ingress.

The WhatIf plane's budget/semaphore discipline (twin/query.py: bounded
work per request, refuse loudly rather than park) applied to the DATA
path: each tenant carries a frames/s and a bytes/s token bucket, and
the plane's drain stage consults them per tick. An over-budget
tenant's wires are simply not drained that tick — the frames stay on
their ingress deques (bounded by the daemon's existing high-water
backpressure), a typed ThrottleVerdict is recorded and metered, and
the bucket refills with (virtual or wall) time. Nothing is ever
silently dropped by admission.

Buckets are HOST state driven by the tick clock (`now_s`), so
explicit-clock runs (tests, fast_forward, the noisy_neighbor scenario
smoke) enforce deterministically.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque

__all__ = ["HostTokenBucket", "ThrottleVerdict", "AdmissionController"]


class HostTokenBucket:
    """Classic token bucket on the caller's clock. `rate_per_s` tokens
    accrue per second up to `burst`; `charge()` debits (may overdraw —
    batch-granular admission charges what was actually drained), and
    the tenant throttles while the fill is non-positive. rate 0 means
    unlimited (never throttles, never charges)."""

    __slots__ = ("rate_per_s", "burst", "fill", "_last_s")

    def __init__(self, rate_per_s: float, burst: float | None = None,
                 ) -> None:
        self.rate_per_s = float(rate_per_s)
        self.burst = float(burst) if burst else max(self.rate_per_s, 1.0)
        self.fill = self.burst
        self._last_s: float | None = None

    def _refill(self, now_s: float) -> None:
        if self._last_s is not None and now_s > self._last_s:
            self.fill = min(self.burst,
                            self.fill + (now_s - self._last_s)
                            * self.rate_per_s)
        self._last_s = now_s if self._last_s is None \
            else max(self._last_s, now_s)

    def ok(self, now_s: float) -> bool:
        if self.rate_per_s <= 0:
            return True
        self._refill(now_s)
        return self.fill > 0.0

    def charge(self, n: float, now_s: float) -> None:
        if self.rate_per_s <= 0:
            return
        self._refill(now_s)
        self.fill -= float(n)

    def reconfigure(self, rate_per_s: float,
                    burst: float | None = None) -> None:
        self.rate_per_s = float(rate_per_s)
        self.burst = float(burst) if burst else max(self.rate_per_s, 1.0)
        self.fill = min(self.fill, self.burst)


@dataclasses.dataclass(frozen=True)
class ThrottleVerdict:
    """One typed admission refusal: which tenant, which wire, why, and
    how many frames were left queued (not dropped) at that instant."""

    tenant: str
    wire_id: int
    queued_frames: int
    reason: str          # "frame-budget" | "byte-budget"
    at_s: float


class AdmissionController:
    """Per-tenant bucket enforcement + verdict metering. One instance
    per TenantRegistry; the plane reaches it through
    `registry.drain_policy(...)` (runtime._tick_inner)."""

    VERDICT_RING = 1024

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.verdicts: deque[ThrottleVerdict] = deque(
            maxlen=self.VERDICT_RING)
        # per-tenant cumulative meters (scrape-tolerant counters)
        self.throttle_events: dict[str, int] = {}
        self.throttled_frame_ticks: dict[str, int] = {}

    def record(self, verdict: ThrottleVerdict) -> None:
        with self._lock:
            self.verdicts.append(verdict)
            t = verdict.tenant
            self.throttle_events[t] = self.throttle_events.get(t, 0) + 1
            self.throttled_frame_ticks[t] = (
                self.throttled_frame_ticks.get(t, 0)
                + verdict.queued_frames)

    def recent(self, limit: int = 50) -> list[ThrottleVerdict]:
        with self._lock:
            return list(self.verdicts)[-limit:]

    def stats_for(self, tenant: str) -> dict:
        with self._lock:
            return {
                "throttle_events": self.throttle_events.get(tenant, 0),
                "throttled_frame_ticks":
                    self.throttled_frame_ticks.get(tenant, 0),
            }
