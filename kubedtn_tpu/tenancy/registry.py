"""TenantRegistry — tenants, edge blocks, QoS, and per-tenant slicing.

The registry is the single source of truth for tenant identity:

- **namespace → tenant**: every pod key's namespace maps to at most
  one tenant (default: a tenant named after the namespace, auto-bound
  by the reconciler's `ensure_namespace` hook). Untenanted namespaces
  keep the historical shared-pool behavior everywhere.
- **edge blocks**: a tenant may reserve a contiguous row range in the
  shared SoA (`parallel.partition.tenant_block` — composes with shard
  blocks). The engine's allocator consults `alloc_row`/`alloc_pair`
  first, so the tenant's links pack into its block; freed block rows
  return to the tenant's pool, never to another tenant.
- **accounting row sets**: per-tenant counter/telemetry slices come
  from COLUMNAR OWNERSHIP MASKS (one capacity-sized bool column per
  tenant plus a row→tenant int column), maintained incrementally at
  every row bind/unbind (`note_bind`/`note_unbind`, O(1) per row) and
  permuted through `compact()`'s renumbering with the same vectorized
  gather the SoA columns use — exact through the repack, whether or
  not blocks are reserved. The historical accounting re-derived each
  tenant's row set from the engine registries per registry
  generation: an O(all-rows) Python walk after EVERY alloc/free,
  which the dtnscale layer now budgets out. A namespace-binding
  change (tenant create/delete, bind_namespace) is the one slow
  path: it marks the masks stale and the next `rows_of` rebuilds
  them in one pass. Blocks are an allocation and isolation-audit
  structure, not the accounting source of truth; a global compact
  dissolves them (rows were renumbered) and the registry immediately
  re-carves each tenant's reservation at its full requested size from
  the repacked free list (`on_compact`); when a re-carve no longer
  fits, the tenant heals on the next compact or
  `create(block_edges=...)`.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from kubedtn_tpu.tenancy.admission import (AdmissionController,
                                           HostTokenBucket,
                                           ThrottleVerdict)
from kubedtn_tpu.utils.logging import fields as _fields
from kubedtn_tpu.utils.logging import get_logger

# QoS class → drain-budget weight (share of the plane's per-wire drain
# budget a tenant's wires get under contention) and stable level code
# for metrics (0 = gold).
QOS_CLASSES: dict[str, float] = {"gold": 1.0, "silver": 0.5,
                                 "bronze": 0.25}
QOS_LEVELS: dict[str, int] = {"gold": 0, "silver": 1, "bronze": 2}


@dataclasses.dataclass
class Tenant:
    """One tenant's identity, quotas, and cumulative meters."""

    name: str
    qos: str = "gold"
    frame_budget_per_s: float = 0.0   # 0 = unlimited
    byte_budget_per_s: float = 0.0    # 0 = unlimited
    namespaces: set = dataclasses.field(default_factory=set)
    block: tuple[int, int] | None = None   # reserved [lo, hi) or None
    block_free: list = dataclasses.field(default_factory=list)
    # rows the tenant's reservation was REQUESTED with (block_edges):
    # survives compact()'s dissolve so the re-carve restores the full
    # entitlement, not whatever happened to be unused at repack time
    block_rows: int = 0
    bucket_frames: HostTokenBucket = None
    bucket_bytes: HostTokenBucket = None
    admitted_frames: int = 0
    admitted_bytes: int = 0
    created_at: float = dataclasses.field(default_factory=time.time)

    def __post_init__(self) -> None:
        if self.qos not in QOS_CLASSES:
            raise ValueError(f"unknown QoS class {self.qos!r}; "
                             f"choices: {', '.join(QOS_CLASSES)}")
        if self.bucket_frames is None:
            self.bucket_frames = HostTokenBucket(self.frame_budget_per_s)
        if self.bucket_bytes is None:
            self.bucket_bytes = HostTokenBucket(self.byte_budget_per_s)

    @property
    def weight(self) -> float:
        return QOS_CLASSES[self.qos]


class TenantRegistry:
    """Tenant control plane over one engine (and, once attached via
    `WireDataPlane.attach_tenancy`, one live plane)."""

    # default_qos MUST be the weight-1.0 class: cmd_daemon attaches a
    # registry unconditionally and the reconciler auto-registers a
    # tenant per namespace, so any other default would silently scale
    # every wire's drain budget on a plane nobody configured tenancy on
    # ("empty registry = zero enforcement" is a documented contract)
    def __init__(self, engine, default_qos: str = "gold") -> None:
        self.engine = engine
        self.default_qos = default_qos
        self.plane = None                  # set by attach_tenancy
        self.admission = AdmissionController()
        self.log = get_logger("tenancy")
        self._lock = threading.RLock()
        self._tenants: dict[str, Tenant] = {}
        self._ns_map: dict[str, str] = {}  # namespace -> tenant name
        # tenants under a migration hold: drain budget 0 this tick,
        # frames queue (never dropped) — the THROTTLE clamp of the
        # federation migration state machine (process state, not
        # persisted: a restarted daemon resumes the migration from its
        # journal, which re-applies the hold)
        self._holds: set[str] = set()
        # -- columnar per-tenant accounting (see module docstring) ----
        # row → tenant id (-1 = untenanted) and one bool ownership
        # mask per tenant, maintained incrementally by note_bind/
        # note_unbind and permuted vectorized through compact
        cap = int(engine._state.capacity)
        self._cap = cap
        self._row_tenant: np.ndarray = np.full((cap,), -1, np.int32)
        # row → id of the tenant whose RESERVED BLOCK contains it
        # (-1 = global pool): release_row resolves a freed row's pool
        # in O(1) instead of scanning every tenant's block bounds
        # per row (freeing N rows was O(N·tenants))
        self._block_owner: np.ndarray = np.full((cap,), -1, np.int32)
        self._masks: dict[str, np.ndarray] = {}
        self._tenant_ids: dict[str, int] = {}   # name → stable int id
        self._tenant_names: list[str] = []      # id → name
        # namespace bindings changed since the masks were built: the
        # next rows_of rebuilds them in ONE pass (the rare control-
        # plane path; the steady alloc/free path stays incremental)
        self._masks_stale: bool = True
        # unused rows currently held inside tenant blocks, maintained
        # as ONE counter at carve/alloc/release/dissolve time — the
        # engine's _ensure_capacity reads it on barrier paths, where
        # a per-call walk of every tenant's pool was a redundant
        # accounting re-derive (dtnscale scost)
        self._reserved_free_n: int = 0
        engine.tenancy = self

    # -- lifecycle -----------------------------------------------------

    def create(self, name: str, qos: str | None = None,
               frame_budget_per_s: float | None = None,
               byte_budget_per_s: float | None = None,
               block_edges: int = 0,
               namespaces=None) -> Tenant:
        """Register a tenant; with `block_edges` > 0, reserve that many
        contiguous SoA rows for it now (growing capacity first if the
        free list cannot hold a run). Idempotent on name: re-creating
        binds any NEW namespaces and updates only the quotas actually
        PROVIDED — `None` budgets/qos leave the existing values alone
        (so the reconciler's `ensure_namespace` path can never wipe an
        operator-set budget back to unlimited) — and never moves an
        EXISTING block, but does reserve one when `block_edges` > 0
        and the tenant has none (the lazy half of post-compact block
        recovery — see `on_compact`). On a NEW tenant, `None` budgets
        mean unlimited.

        Lock order is ENGINE lock before registry lock everywhere (the
        allocator hooks run under the engine lock and read the
        registry), so block reservation — which needs the engine lock
        — always happens OUTSIDE the registry lock."""
        with self._lock:
            existing = self._tenants.get(name)
            if existing is not None:
                for ns in (set(namespaces) if namespaces else {name}):
                    newly = ns not in self._ns_map
                    # never steal a namespace already mapped elsewhere
                    if self._ns_map.setdefault(ns, name) == name:
                        existing.namespaces.add(ns)
                        if newly:
                            # a new binding may adopt already-realized
                            # rows: rebuild the masks on next query
                            self._masks_stale = True
                out = self.set_quota(name, qos=qos,
                                     frame_budget_per_s=
                                     frame_budget_per_s,
                                     byte_budget_per_s=byte_budget_per_s)
                need_block = block_edges > 0 and existing.block is None
                size_kept = (block_edges > 0
                             and existing.block is not None
                             and existing.block_rows != block_edges)
        if existing is not None:
            if need_block:
                self._reserve_block(existing, int(block_edges))
                self.log.info("tenant block reserved %s", _fields(
                    tenant=name,
                    block=list(existing.block) if existing.block
                    else None))
            elif size_kept:
                # blocks never move or resize once reserved — say so
                # instead of silently ignoring the differing request
                self.log.info("tenant block size kept %s", _fields(
                    tenant=name, requested=int(block_edges),
                    reserved=existing.block_rows))
            return out
        t = Tenant(name=name, qos=qos or self.default_qos,
                   frame_budget_per_s=frame_budget_per_s or 0.0,
                   byte_budget_per_s=byte_budget_per_s or 0.0,
                   namespaces=set(namespaces)
                   if namespaces else {name})
        # publish BEFORE reserving: a block carved for an unpublished
        # tenant would be invisible to a concurrent compact() —
        # on_compact walks only published tenants, so the rebuilt
        # global free list would recycle the carved rows while the
        # tenant still held them (the same SoA rows allocatable from
        # two pools). Published first, the tenant is dissolved and
        # re-carved by on_compact like any other. When two creates
        # race, the FIRST reservation to land wins (the loser may even
        # carve it on the winner's behalf below); a racing different
        # block_edges is ignored like any re-create's — blocks never
        # move or resize once reserved.
        with self._lock:
            won = self._tenants.setdefault(name, t)
            if won.name not in self._tenant_ids:
                # stable accounting id (never reused — a deleted
                # tenant's residual _row_tenant entries must not alias
                # a later tenant's mask)
                self._tenant_ids[won.name] = len(self._tenant_names)
                self._tenant_names.append(won.name)
            for ns in t.namespaces:
                # bind this call's namespaces to whoever WON the
                # publish race: admission (ns_map) and accounting
                # (won.namespaces) must agree on every namespace
                if self._ns_map.setdefault(ns, won.name) == won.name:
                    won.namespaces.add(ns)
            self._masks_stale = True
            need_block = block_edges > 0 and won.block is None
        if need_block:
            # a reservation failure (ValueError) leaves the tenant
            # registered without a block; the next
            # create(block_edges=...) retries via the lazy path
            self._reserve_block(won, int(block_edges))
        self.log.info("tenant created %s", _fields(
            tenant=name, qos=won.qos,
            frame_budget=frame_budget_per_s,
            byte_budget=byte_budget_per_s,
            block=list(won.block) if won.block else None))
        return won

    @staticmethod
    def _block_free_of(blk: tuple[int, int]) -> list[int]:
        # descending free list: consecutive pops hand out consecutive
        # rows, so link pairs colocate exactly like the global pool's
        return list(range(blk[1] - 1, blk[0] - 1, -1))

    def _reserve_block(self, t: Tenant, n_rows: int) -> None:
        """Reserve a contiguous block for `t`, repacking once if the
        free list is too fragmented to hold a run. First reservation
        wins: if a concurrent reserver (or the repack's own on_compact
        re-carve, which uses the tenant's REMEMBERED block_rows)
        established a block of a different size meanwhile, that block
        is kept — blocks never move or resize — and the mismatch is
        logged rather than silently absorbed."""
        if not self._carve_and_publish(t, n_rows):
            # fragmented free list: one repack restores contiguity
            # (compact dissolves every existing block — the rows were
            # renumbered — and on_compact eagerly re-carves the OTHER
            # tenants' reservations; ours comes from what remains.
            # Accounting is row-set based and unaffected)
            self.engine.compact()
            if not self._carve_and_publish(t, n_rows):
                raise ValueError(
                    f"cannot reserve {n_rows} contiguous rows for "
                    f"tenant {t.name} (capacity "
                    f"{self.engine._state.capacity})")
        with self._lock:
            reserved = t.block_rows
        if reserved != n_rows:
            self.log.info("tenant block size kept %s", _fields(
                tenant=t.name, requested=int(n_rows),
                reserved=reserved))

    def _carve_and_publish(self, t: Tenant, n_rows: int) -> bool:
        """Carve a contiguous run off the engine free list and publish
        it as `t.block` in ONE engine-lock hold: a compact() cannot
        interleave and recycle the carved-but-unpublished rows into
        its rebuilt global free list, and a published tenant's
        allocator hooks (which run under the engine lock) never see a
        half-built reservation. `t` must already be in `_tenants`
        (create publishes the tenant BEFORE reserving) — a block on an
        unregistered tenant would be invisible to on_compact. True
        when `t` has a block on return — ours, or a racing reserver's
        (first publish wins)."""
        from kubedtn_tpu.parallel.partition import tenant_block

        engine = self.engine
        with engine._lock:
            with self._lock:
                if t.block is not None:
                    return True
            engine._ensure_capacity(n_rows)
            blk = tenant_block(engine._free, engine._state.capacity,
                               getattr(engine, "shard_count", 1),
                               n_rows)
            if blk is None:
                return False
            with self._lock:
                t.block = blk
                t.block_rows = n_rows
                t.block_free = self._block_free_of(blk)
                self._reserved_free_n += len(t.block_free)
                self._set_block_owner_locked(t, blk)
        return True

    def _set_block_owner_locked(self, t: Tenant,
                                blk: tuple[int, int]) -> None:
        """Vectorized range-write of the block-owner column (caller
        holds the registry lock; the tenant must be published so it
        has a stable id)."""
        tid = self._tenant_ids.get(t.name)
        if tid is None:
            return
        if blk[1] > self._block_owner.shape[0]:
            grown = np.full((blk[1],), -1, np.int32)
            grown[:self._block_owner.shape[0]] = self._block_owner
            self._block_owner = grown
        self._block_owner[blk[0]:blk[1]] = tid

    def set_quota(self, name: str, qos: str | None = None,
                  frame_budget_per_s: float | None = None,
                  byte_budget_per_s: float | None = None) -> Tenant:
        with self._lock:
            t = self._tenants[name]
            if qos:
                if qos not in QOS_CLASSES:
                    raise ValueError(f"unknown QoS class {qos!r}")
                t.qos = qos
            if frame_budget_per_s is not None:
                t.frame_budget_per_s = float(frame_budget_per_s)
                t.bucket_frames.reconfigure(t.frame_budget_per_s)
            if byte_budget_per_s is not None:
                t.byte_budget_per_s = float(byte_budget_per_s)
                t.bucket_bytes.reconfigure(t.byte_budget_per_s)
            return t

    def bind_namespace(self, namespace: str, tenant: str) -> None:
        with self._lock:
            t = self._tenants[tenant]
            t.namespaces.add(namespace)
            self._ns_map[namespace] = tenant
            self._masks_stale = True

    def ensure_namespace(self, namespace: str) -> Tenant | None:
        """Reconciler hook: namespace → tenant mapping. An unmapped
        namespace gets a default-QoS, unlimited-quota tenant named
        after it, so every reconciled topology is attributable from
        its first link."""
        if not namespace:
            return None
        with self._lock:
            name = self._ns_map.get(namespace)
            if name is not None:
                return self._tenants.get(name)
        return self.create(namespace)

    def hold(self, name: str) -> None:
        """Migration hold: the tenant's wires get drain budget 0 every
        tick (typed "migration-hold" verdicts, frames kept queued —
        the daemon's ingress high-water backpressure bounds the
        backlog). Idempotent; quotas are untouched."""
        with self._lock:
            self._holds.add(name)

    def release_hold(self, name: str) -> None:
        with self._lock:
            self._holds.discard(name)

    def held(self, name: str) -> bool:
        with self._lock:
            return name in self._holds

    def delete(self, name: str) -> bool:
        """Deregister a tenant: unbind its namespaces, dissolve its
        reserved block (unused reserve rows return to the GLOBAL free
        list; rows still realized inside the former block stay bound to
        their links and drain back to the global pool as they free),
        and drop the registry entry. Admission/QoS enforcement for the
        namespaces ends immediately; accounting row sets are registry-
        derived, so the next `rows_of` of a recreated tenant is exact.
        Idempotent — False when the tenant does not exist. (The
        federation RELEASE step and `kdt tenant delete` both land
        here.)"""
        engine = self.engine
        with engine._lock:
            with self._lock:
                t = self._tenants.pop(name, None)
                if t is None:
                    return False
                for ns in list(t.namespaces):
                    if self._ns_map.get(ns) == name:
                        del self._ns_map[ns]
                self._holds.discard(name)
                freed = list(t.block_free)
                if t.block is not None:
                    self._block_owner[t.block[0]:t.block[1]] = -1
                t.block = None
                t.block_free = []
                self._reserved_free_n -= len(freed)
                self._masks.pop(name, None)
                self._masks_stale = True
            if freed:
                # descending like the global pool: consecutive pops
                # keep handing out consecutive rows (vectorized fold —
                # the extend is one numpy copy, not a per-row append)
                engine._free.extend(sorted(freed, reverse=True))
        self.log.info("tenant deleted %s", _fields(
            tenant=name, freed_reserve=len(freed)))
        return True

    def export_config(self) -> dict:
        """The tenancy section of a checkpoint manifest: quotas, QoS,
        block entitlement (`block_rows` — the reservation re-carves at
        restore, position is an allocation detail), namespace bindings
        and admitted meters. Restored by `checkpoint.load_tenancy` so
        a daemon restart never silently resets tenants to unenforced."""
        with self._lock:
            return {
                "default_qos": self.default_qos,
                "tenants": [{
                    "name": t.name,
                    "qos": t.qos,
                    "frame_budget_per_s": t.frame_budget_per_s,
                    "byte_budget_per_s": t.byte_budget_per_s,
                    "block_rows": int(t.block_rows),
                    "namespaces": sorted(t.namespaces),
                    "admitted_frames": int(t.admitted_frames),
                    "admitted_bytes": int(t.admitted_bytes),
                } for t in self._tenants.values()],
            }

    def get(self, name: str) -> Tenant | None:
        with self._lock:
            return self._tenants.get(name)

    def list(self) -> list[Tenant]:
        with self._lock:
            return list(self._tenants.values())

    def tenant_of_pod_key(self, pod_key: str) -> Tenant | None:
        ns, _, _name = pod_key.partition("/")
        with self._lock:
            t = self._ns_map.get(ns)
            return self._tenants.get(t) if t is not None else None

    # -- engine allocator hooks (engine lock held by the caller) -------

    def alloc_row(self, pod_key: str) -> int | None:
        t = self.tenant_of_pod_key(pod_key)
        if t is None or not t.block_free:
            return None
        row = t.block_free.pop()
        with self._lock:
            self._reserved_free_n -= 1
        return row

    def alloc_pair(self, k1: str, k2: str) -> tuple[int, int] | None:
        t1 = self.tenant_of_pod_key(k1)
        t2 = self.tenant_of_pod_key(k2)
        if t1 is None or t1 is not t2 or len(t1.block_free) < 2:
            return None
        pair = t1.block_free.pop(), t1.block_free.pop()
        with self._lock:
            self._reserved_free_n -= 2
        return pair

    def release_row(self, row: int) -> bool:
        with self._lock:
            # O(1) via the columnar block-owner column — the per-row
            # scan of every tenant's block bounds made freeing N rows
            # O(N·tenants) (dtnscale scost on the alloc path). The
            # positional re-check keeps a stale column entry (block
            # dissolved out-of-band) from resurrecting a dead pool.
            tid = (int(self._block_owner[row])
                   if row < self._block_owner.shape[0] else -1)
            if tid < 0:
                return False
            t = self._tenants.get(self._tenant_names[tid])
            if t is None or t.block is None or \
                    not t.block[0] <= row < t.block[1]:
                return False
            t.block_free.append(row)
            self._reserved_free_n += 1
            return True

    def reserved_free(self) -> int:
        """Unused rows inside tenant blocks — ONE incrementally-
        maintained counter (O(1)); callers on the barrier paths
        (engine._ensure_capacity) read it per operation."""
        with self._lock:
            return self._reserved_free_n

    def reserved_free_rows(self) -> list[int]:
        """Every unused row currently held inside a tenant block. The
        checkpoint writer folds these back into the SAVED free list:
        a reservation is registry state re-carved at restore
        (`load_or_rebuild` → `load_tenancy`), so leaving the rows out
        of the persisted pool would leak them — absent from the global
        free list AND from the freshly-carved blocks — on every
        restart."""
        with self._lock:
            out: list[int] = []
            for t in self._tenants.values():
                out.extend(t.block_free)
            return out

    def on_compact(self, old_rows: np.ndarray, n_active: int,
                   capacity: int) -> None:
        """compact() renumbered every row (new row i held
        ``old_rows[i]``): the old contiguous blocks are gone (their
        active rows moved into [0, n), their unused reserve returned
        to the rebuilt global free list). Each tenant's reservation is
        immediately re-carved at its FULL requested size
        (`block_rows`) — never just the unused remainder, which would
        decay the entitlement on every compact/free cycle (rows
        allocated before the repack live outside the new block and
        drain back to the global pool as they free) — so one tenant's
        repack can never silently strip or shrink another tenant's
        reservation. A re-carve that no longer fits (capacity claimed
        by active rows, shard-locality fragmentation from earlier
        re-carves) leaves that tenant dissolved — with `block_rows`
        remembered, so the NEXT compact or `create(block_edges=...)`
        heals it. The accounting masks permute with the SAME
        vectorized `old_rows` gather the SoA columns used, staying
        exact through the renumbering. Called by engine.compact with
        the ENGINE lock held (re-entrant here — the lock order is
        engine before registry)."""
        from kubedtn_tpu.parallel.partition import tenant_blocks

        engine = self.engine
        with engine._lock, self._lock:
            if not self._masks_stale:
                rt = np.full((capacity,), -1, np.int32)
                rt[:n_active] = self._row_tenant[old_rows]
                self._row_tenant = rt
                for name, m in list(self._masks.items()):
                    nm = np.zeros((capacity,), bool)
                    nm[:n_active] = m[old_rows]
                    self._masks[name] = nm
                self._cap = capacity
            tenants = list(self._tenants.values())
            self._block_owner = np.full(
                (capacity,), -1, np.int32)  # blocks dissolve wholesale
            for t in tenants:
                self._reserved_free_n -= len(t.block_free)
                t.block = None
                t.block_free = []
            # ONE sorted pass over the free list for the whole
            # registry — per-tenant carving would re-sort and rebuild
            # the list T times under the engine lock the tick path's
            # allocator is waiting on
            blks = tenant_blocks(engine._free, engine._state.capacity,
                                 getattr(engine, "shard_count", 1),
                                 [t.block_rows for t in tenants])
            for t, blk in zip(tenants, blks):
                if t.block_rows <= 0:
                    continue
                if blk is None:
                    self.log.warning(
                        "tenant block not re-carved after compact %s",
                        _fields(tenant=t.name, rows=t.block_rows))
                    continue
                t.block = blk
                t.block_free = self._block_free_of(blk)
                self._reserved_free_n += len(t.block_free)
                self._set_block_owner_locked(t, blk)

    # -- admission + QoS (the plane's tick-path surface) ---------------

    def drain_policy(self, base_budget: int, now_s: float):
        """Per-wire drain budget callable for daemon.drain_ingress:
        QoS weight scales the budget; an over-budget tenant's wires get
        0 (skipped this tick, typed verdict recorded, frames kept).
        Tenant → verdict resolution is snapshotted ONCE per tick here,
        not per wire — O(tenants) per tick, O(1) per wire."""
        with self._lock:
            snap = {}
            for name, t in self._tenants.items():
                if name in self._holds:
                    # migration hold: frames queue on their wires until
                    # the cutover redirects (or a rollback releases)
                    snap[name] = (0, "migration-hold")
                elif not t.bucket_frames.ok(now_s):
                    snap[name] = (0, "frame-budget")
                elif not t.bucket_bytes.ok(now_s):
                    snap[name] = (0, "byte-budget")
                else:
                    snap[name] = (max(1, int(base_budget * t.weight)),
                                  None)
            # inside the same lock block as `snap`: a tenant published
            # between the two copies would be in ns_map but not snap
            ns_map = dict(self._ns_map)
        admission = self.admission

        def budget_for(wire) -> int:
            ns, _, _ = wire.pod_key.partition("/")
            name = ns_map.get(ns)
            if name is None:
                return base_budget
            entry = snap.get(name)
            if entry is None:
                return base_budget  # created after the snapshot
            budget, reason = entry
            if budget == 0:
                admission.record(ThrottleVerdict(
                    tenant=name, wire_id=wire.wire_id,
                    queued_frames=len(wire.ingress), reason=reason,
                    at_s=now_s))
            return budget

        return budget_for

    def charge_drained(self, drained, now_s: float) -> None:
        """Debit each drained batch against its tenant's buckets and
        advance the admitted meters (batch-granular: what was drained
        was admitted)."""
        per_tenant: dict[str, tuple[int, int]] = {}
        for wire, _row, lens, _parts in drained:
            t = self.tenant_of_pod_key(wire.pod_key)
            if t is None:
                continue
            frames = len(lens)
            nbytes = int(np.asarray(lens, np.float64).sum())
            f0, b0 = per_tenant.get(t.name, (0, 0))
            per_tenant[t.name] = (f0 + frames, b0 + nbytes)
        if not per_tenant:
            return
        with self._lock:
            for name, (frames, nbytes) in per_tenant.items():
                t = self._tenants.get(name)
                if t is None:
                    continue
                t.admitted_frames += frames
                t.admitted_bytes += nbytes
                t.bucket_frames.charge(frames, now_s)
                t.bucket_bytes.charge(nbytes, now_s)

    # -- columnar accounting maintenance (engine lock held) ------------

    def note_bind(self, row: int, pod_key: str) -> None:
        """Engine hook at row bind: set the owning tenant's mask bit —
        O(1) per row, the incremental half of the columnar accounting.
        Skipped while the masks are stale (the pending rebuild will
        see this row in the registries)."""
        with self._lock:
            if self._masks_stale:
                return
            name = self._ns_map.get(pod_key.partition("/")[0])
            if name is None:
                return
            m = self._masks.get(name)
            if m is None or row >= m.shape[0]:
                # capacity raced ahead of on_capacity (defensive):
                # fall back to a rebuild
                self._masks_stale = True
                return
            m[row] = True
            self._row_tenant[row] = self._tenant_ids[name]

    def note_unbind(self, row: int) -> None:
        """Engine hook at row free: clear the owner's mask bit."""
        with self._lock:
            if self._masks_stale:
                return
            tid = int(self._row_tenant[row]) \
                if row < self._row_tenant.shape[0] else -1
            if tid < 0:
                return
            self._row_tenant[row] = -1
            m = self._masks.get(self._tenant_names[tid])
            if m is not None and row < m.shape[0]:
                m[row] = False

    def on_capacity(self, new_cap: int) -> None:
        """Engine hook at capacity growth: pad the accounting columns
        (vectorized copies, like the SoA growth itself)."""
        with self._lock:
            if new_cap <= self._cap:
                return
            # the block-owner column is allocation state, correct even
            # while the accounting masks are stale — pad unconditionally
            bo = np.full((new_cap,), -1, np.int32)
            bo[:self._block_owner.shape[0]] = self._block_owner
            self._block_owner = bo
            if not self._masks_stale:
                rt = np.full((new_cap,), -1, np.int32)
                rt[:self._row_tenant.shape[0]] = self._row_tenant
                self._row_tenant = rt
                for name, m in list(self._masks.items()):
                    nm = np.zeros((new_cap,), bool)
                    nm[:m.shape[0]] = m
                    self._masks[name] = nm
            self._cap = new_cap

    def _rebuild_masks_locked(self) -> None:
        """ONE pass over the engine registries rebuilds every mask —
        the namespace-binding slow path (tenant create/delete/bind);
        the steady alloc/free path never lands here. Caller holds the
        engine lock AND the registry lock."""
        cap = int(self.engine._state.capacity)
        self._cap = cap
        self._row_tenant = np.full((cap,), -1, np.int32)
        self._masks = {name: np.zeros((cap,), bool)
                       for name in self._tenants}
        for (pod_key, _uid), row in self.engine._rows.items():
            name = self._ns_map.get(pod_key.partition("/")[0])
            if name is None or name not in self._masks:
                continue
            self._masks[name][row] = True
            self._row_tenant[row] = self._tenant_ids[name]
        self._masks_stale = False

    # -- per-tenant slicing (counters + telemetry window ring) ---------

    def rows_of(self, name: str) -> np.ndarray:
        """Current SoA rows owned by the tenant's namespaces — one
        vectorized `flatnonzero` over the tenant's incrementally-
        maintained ownership mask (exact through compact: the mask
        permutes with the engine's own row gather). The historical
        implementation re-walked every engine row per registry
        generation."""
        engine = self.engine
        with engine._lock:
            with self._lock:
                if self._masks_stale:
                    self._rebuild_masks_locked()
                m = self._masks.get(name)
                if m is None:
                    return np.asarray([], np.int64)
                return np.flatnonzero(m).astype(np.int64)

    def tenant_counters(self, plane, name: str) -> dict:
        """This tenant's slice of the plane's cumulative per-edge
        counters (tx/delivered/bytes/drops by cause)."""
        rows = self.rows_of(name)
        c = plane.counters
        cap = np.asarray(c.tx_packets).shape[0]
        rows = rows[rows < cap]

        def s(arr) -> float:
            return float(np.asarray(arr)[rows].sum())

        return {
            "links": int(rows.size),
            "tx_packets": s(c.tx_packets),
            "tx_bytes": s(c.tx_bytes),
            "delivered_packets": s(c.rx_packets),
            "delivered_bytes": s(c.rx_bytes),
            "dropped_loss": s(c.dropped_loss),
            "dropped_queue": s(c.dropped_queue),
            "dropped_ring": s(c.dropped_ring),
            "corrupted": s(c.rx_corrupted),
        }

    def tenant_window(self, plane, name: str,
                      last: int | None = None, window=None) -> dict:
        """This tenant's slice of the telemetry window ring: delivery
        rate and latency percentiles over the covered span (empty dict
        when telemetry is off). `window` takes a precomputed
        `window_sum(...)` result so a caller slicing MANY tenants (the
        metrics collector) reduces the ring once, not once per
        tenant."""
        from kubedtn_tpu import telemetry as tele

        if window is None:
            tel = getattr(plane, "telemetry", None)
            if tel is None:
                return {}
            window = tel.window_sum(last=last)
        total, seconds = window
        rows = self.rows_of(name)
        rows = rows[rows < total.shape[0]]
        t = total[rows].sum(axis=0)
        delivered = float(t[tele.T_DELIVERED])
        secs = max(seconds, 1e-9)
        pcts = tele.percentiles_from_hist(t[tele.T_HIST0:],
                                          qs=(0.5, 0.99))
        return {
            "window_seconds": float(seconds),
            "tx": float(t[tele.T_TX]),
            "delivered": delivered,
            "delivered_pps": delivered / secs,
            "bytes_ps": float(t[tele.T_BYTES]) / secs,
            "dropped_loss": float(t[tele.T_DROP_LOSS]),
            "dropped_queue": float(t[tele.T_DROP_QUEUE]),
            "queue_depth": float(t[tele.T_QDEPTH]),
            "p50_us": pcts["p50_us"],
            "p99_us": pcts["p99_us"],
            # censored = clamped at the open top bucket (render >X)
            "p99_censored": pcts["p99_censored"],
            # the tenant's window histogram on the shared reference
            # ladder, as plain floats: JSON-safe (the migration
            # journal FREEZES this dict at reconcile) and exactly
            # mergeable across planes (slo.fleet)
            "hist": [float(x) for x in t[tele.T_HIST0:]],
        }

    def stats(self, plane, name: str) -> dict:
        """The Local.TenantStats payload: identity + quotas + admitted
        meters + throttle meters + counter slice + window slice."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                raise KeyError(name)
            base = {
                "name": t.name,
                "qos": t.qos,
                "namespaces": sorted(t.namespaces),
                "frame_budget_per_s": t.frame_budget_per_s,
                "byte_budget_per_s": t.byte_budget_per_s,
                "block_lo": t.block[0] if t.block else -1,
                "block_hi": t.block[1] if t.block else -1,
                "admitted_frames": t.admitted_frames,
                "admitted_bytes": t.admitted_bytes,
            }
        base.update(self.admission.stats_for(name))
        if plane is not None:
            base.update(self.tenant_counters(plane, name))
            base["window"] = self.tenant_window(plane, name)
        return base

    # -- tenant-scoped twin forks --------------------------------------

    def tenant_snapshot(self, plane_or_engine, name: str, q: int = 32):
        """Snapshot-fork the live plane (or bare engine) SCOPED to one
        tenant: every edge row outside the tenant's set is deactivated
        in the fork, so a per-tenant what-if sweep answers "what would
        MY slice do" without seeing (or paying for) neighbors. The live
        plane keeps ticking — same consistency barrier as
        twin.snapshot.snapshot_from_plane."""
        import dataclasses as dc

        import jax.numpy as jnp

        from kubedtn_tpu.twin.snapshot import (snapshot_from_engine,
                                               snapshot_from_plane)

        rows = self.rows_of(name)
        if hasattr(plane_or_engine, "_tick_lock"):
            snap = snapshot_from_plane(plane_or_engine, q=q)
        else:
            snap = snapshot_from_engine(plane_or_engine, q=q)
        edges = snap.sim.edges
        mask = jnp.zeros((edges.capacity,), bool)
        if rows.size:
            mask = mask.at[jnp.asarray(rows)].set(True)
        edges = dc.replace(edges, active=edges.active & mask)
        return dc.replace(snap, sim=dc.replace(snap.sim, edges=edges))
