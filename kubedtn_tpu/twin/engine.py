"""Batched replica engine: N perturbed futures × T virtual ticks in ONE
compiled program.

The replica axis is just another array axis: the snapshot's `SimState`
(or `RouterState`) broadcasts to [N, ...] leaves, per-replica edit
batches scatter the perturbations in (update_links semantics), and one
`lax.scan` over T per-step PRNG keys advances a vmapped
`sim._step_parts` / `router_step` body with on-device metric
reductions — delivery-latency histogram against the reference
Prometheus buckets, delivered/dropped counters, queue occupancy. Only
[N]-sized reductions ever cross to the host.

Determinism contract (pinned by tests/test_twin.py):
- The per-step keys are `jax.random.split(jax.random.key(seed), steps)`
  — exactly `sim.run`'s schedule — and are SHARED across replicas
  (vmapped with in_axes=None). Every random draw inside the step
  depends only on (key, spec, shapes), so the draws hoist out of the
  replica batch: replica 0 of an unperturbed sweep is bit-identical to
  the unbatched `sim.run`/`run_routed` on the same snapshot and seed,
  and padding replicas cannot perturb any real replica's streams —
  the same sweep at N=4 and N=64 returns identical per-scenario
  results.
- Compilation is cached per (N, T, capacity, k_slots, ...) signature
  via an AOT executable cache, so the compile cost is paid once per
  shape and the compile/run split is measured exactly (the
  `kubedtn_whatif_*` metrics).

Sharding: pass `mesh=` (see parallel.mesh.make_replica_mesh) to shard
the replica axis across devices — replicas are embarrassingly
parallel, so GSPMD partitions the whole scan with zero communication.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from kubedtn_tpu.models.traffic import TrafficSpec
from kubedtn_tpu.ops import edge_state as es
# The latency reduction is the LINK TELEMETRY plane's: the same bucket
# ladder and histogram_quantile the live plane's per-edge window ring
# uses (kubedtn_tpu/telemetry.py), so a sweep's p99 and `cli top`'s p99
# are the same statistic by construction.
from kubedtn_tpu.telemetry import (BUCKET_EDGES_US, N_BINS,
                                   percentiles_from_hist)
from kubedtn_tpu.twin.snapshot import TwinSnapshot
from kubedtn_tpu.twin.spec import ReplicaEdits, compile_scenarios

_COUNTER_KEYS = ("tx_packets", "tx_bytes", "rx_packets", "rx_bytes",
                 "dropped_loss", "dropped_queue", "dropped_ring",
                 "rx_corrupted", "duplicated", "reordered")


@dataclasses.dataclass
class SweepResult:
    """One sweep's outcome: per-scenario metrics + provenance."""

    names: list
    metrics: list           # dict per scenario (see _replica_metrics)
    replicas: int           # total replica lanes incl. baseline/padding
    ticks: int
    sim_seconds: float
    compile_s: float        # 0.0 on a warm executable cache
    run_s: float
    replicas_steps_per_s: float
    final: object = None    # batched final state (tests/forks); [N,...]


def _broadcast(tree, n: int):
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), tree)


@jax.jit
def _apply_edits(bedges, rows, props, valid, drows, dvalid):
    """Vmapped perturbation application: one update_links scatter (row
    state reset, qdisc-reinstall semantics) plus one active-mask clear
    per replica. All-invalid lanes drop — a no-edit replica's arrays
    keep the base state's exact bits."""

    def one(edges, r, p, v, dr, dv):
        edges = es.update_links.__wrapped__(edges, r, p, v, False)
        t = jnp.where(dv, dr, edges.capacity)
        return dataclasses.replace(
            edges, active=edges.active.at[t].set(False, mode="drop"))

    return jax.vmap(one)(bedges, rows, props, valid, drows, dvalid)


# -- the compiled sweep ------------------------------------------------

def _spec_fingerprint(spec) -> tuple:
    """Hashable identity of a TrafficSpec's exact contents — the sweep
    closes over the spec as jaxpr CONSTANTS (below), so the compiled-fn
    cache must key on the values, not the object."""
    out = []
    for f in dataclasses.fields(spec):
        a = np.asarray(getattr(spec, f.name))
        out.append((f.name, a.shape, str(a.dtype), a.tobytes()))
    return tuple(out)


def _spec_from_fingerprint(fp) -> TrafficSpec:
    return TrafficSpec(**{
        name: jnp.asarray(np.frombuffer(buf, dtype=dtype).reshape(shape))
        for name, shape, dtype, buf in fp})


@functools.lru_cache(maxsize=64)
def _sweep_fn(k_slots: int, dt_us_f: float, spec_fp: tuple):
    edges_us = jnp.asarray(BUCKET_EDGES_US, jnp.float32)
    # dt AND the traffic spec are closure CONSTANTS, exactly as sim.run's
    # scan closes over them: passed traced instead, XLA keeps
    # `rate_b_us * dt` as a runtime multiply and contracts the following
    # `credit + rate*dt` into an FMA — one rounding the constant-folded
    # reference program doesn't take (measured ~2e-4 drift on
    # traffic.credit). Bit-exact replica 0 is the contract, so the
    # constant treatment must match; the lru key carries the spec's
    # exact bytes.
    dt_us = jnp.float32(dt_us_f)
    spec = _spec_from_fingerprint(spec_fp)

    def fn(bsim, keys, scale):
        from kubedtn_tpu.models.traffic import generate
        from kubedtn_tpu.sim import _finish_step

        n = bsim.clock_us.shape[0]

        def one(sim, s, tstate, sizes, valid, t_arr, ks):
            sim2, due, res, sizes2, t_arr2 = _finish_step(
                sim, tstate, sizes, valid, t_arr, ks, dt_us,
                size_scale=s)
            deliv = res.delivered
            # one-hop delivery latency of every shaped-and-delivered
            # packet this step (netem delay incl. rate backlog), binned
            # against the reference bucket ladder on device
            lat = (res.depart_us - t_arr2).ravel()
            idx = jnp.searchsorted(edges_us, lat, side="left")
            hist = jnp.zeros((N_BINS,), jnp.float32).at[idx].add(
                deliv.ravel().astype(jnp.float32))
            occ = jnp.isfinite(sim2.inflight.t).sum().astype(jnp.float32)
            return sim2, hist, occ

        def body(carry, key):
            bsim, ts, hist, occ = carry
            # traffic generation is replica-INDEPENDENT (the active mask
            # applies downstream and nothing feeds back into the
            # sources), so ONE unbatched call serves every replica: the
            # credit/PRNG chain stays the exact program sim.run traces —
            # a vmapped chain let XLA contract `credit + rate*dt` into
            # an FMA the reference program doesn't use, drifting replica
            # 0 by one rounding
            kg, ks = jax.random.split(key)
            ts2, sizes, valid, t_arr = generate(spec, ts, dt_us, k_slots,
                                                kg)
            bsim2, h, o = jax.vmap(
                one, in_axes=(0, 0, None, None, None, None, None))(
                bsim, scale, ts2, sizes, valid, t_arr, ks)
            return (bsim2, ts2, hist + h, occ + o), None

        # all replicas share one traffic chain; lane 0's state IS it
        ts0 = jax.tree.map(lambda x: x[0], bsim.traffic)
        init = (bsim, ts0, jnp.zeros((n, N_BINS), jnp.float32),
                jnp.zeros((n,), jnp.float32))
        (bsim, _ts, hist, occ), _ = jax.lax.scan(body, init, keys)
        # per-replica counter totals reduced on device: [N] each
        totals = {k: getattr(bsim.counters, k).sum(axis=1)
                  for k in _COUNTER_KEYS}
        return bsim, hist, occ / keys.shape[0], totals

    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _routed_sweep_fn(k_slots: int, k_fwd: int):
    from kubedtn_tpu.models.traffic import generate
    from kubedtn_tpu.router import _finish_router_step

    def fn(brs, spec, flow_dst, keys, dt_us):
        def body(carry, key):
            brs, ts = carry
            # same hoisted-generate treatment as the unrouted sweep:
            # one unbatched traffic chain keeps replica 0 bit-identical
            # to run_routed (see _sweep_fn)
            kg, ks = jax.random.split(key)
            ts2, sizes_t, valid_t, t_arr_t = generate(spec, ts, dt_us,
                                                      k_slots, kg)
            brs2 = jax.vmap(
                lambda rs: _finish_router_step(
                    rs, spec, flow_dst, ts2, sizes_t, valid_t, t_arr_t,
                    ks, k_fwd, dt_us))(brs)
            return (brs2, ts2), None

        ts0 = jax.tree.map(lambda x: x[0], brs.sim.traffic)
        (brs, _ts), _ = jax.lax.scan(body, (brs, ts0), keys)
        totals = {k: getattr(brs.sim.counters, k).sum(axis=1)
                  for k in _COUNTER_KEYS}
        totals["node_rx_packets"] = brs.node_rx_packets.sum(axis=1)
        totals["node_rx_bytes"] = brs.node_rx_bytes.sum(axis=1)
        totals["fwd_dropped"] = brs.fwd_dropped
        totals["no_route_dropped"] = brs.no_route_dropped
        return brs, totals

    return jax.jit(fn)


# AOT executable cache: exactly ONE compile per (program, input-shape)
# signature, and an exact compile-vs-run split for the whatif metrics.
# LRU-bounded: the signature includes CLIENT-controlled parameters
# (ticks, scenario count on the daemon's WhatIf surface), so an
# unbounded dict would let varied queries grow a long-lived daemon's
# memory monotonically — one compiled 10k-step scan per distinct shape.
_EXEC_MAX = 32
_EXEC_LOCK = threading.Lock()
_EXEC_CACHE: collections.OrderedDict = collections.OrderedDict()


def _compile_cached(jitted, sig, *args):
    """(executable, compile_seconds) — compile_seconds is 0.0 on a hit."""
    with _EXEC_LOCK:
        hit = _EXEC_CACHE.get(sig)
        if hit is not None:
            _EXEC_CACHE.move_to_end(sig)
            return hit, 0.0
    t0 = time.perf_counter()
    compiled = jitted.lower(*args).compile()
    compile_s = time.perf_counter() - t0
    with _EXEC_LOCK:
        # a racer may have compiled too; either executable is valid
        compiled = _EXEC_CACHE.setdefault(sig, compiled)
        _EXEC_CACHE.move_to_end(sig)
        while len(_EXEC_CACHE) > _EXEC_MAX:
            _EXEC_CACHE.popitem(last=False)
    return compiled, compile_s


def _abstract_sig(tree):
    return tuple((x.shape, str(x.dtype))
                 for x in jax.tree.leaves(tree))


def _mesh_sig(mesh):
    """Value identity of a mesh for the executable cache: axis names +
    device ids. id(mesh) would recompile for every equal-but-distinct
    Mesh object (a caller building make_replica_mesh() per sweep) and,
    worse, a GC'd mesh's reused id could alias a stale executable."""
    if mesh is None:
        return None
    return (tuple(mesh.axis_names),
            tuple(d.id for d in mesh.devices.flat))


# -- percentiles from bucket counts: telemetry.percentiles_from_hist --

_percentiles = percentiles_from_hist


def _replica_metrics(i: int, totals_np: dict, start: dict,
                     hist: np.ndarray, occ: np.ndarray,
                     sim_seconds: float) -> dict:
    """One replica's report row. Two populations, deliberately:
    `latency_hist`/percentiles measure the SHAPING latency of every
    packet that left the qdisc chain (scheduled delivery, at shaping
    time — including packets whose pop falls past the horizon), while
    `delivered_packets`/`delivery_ratio` count pops WITHIN the horizon.
    A latency perturbation comparable to the sweep horizon therefore
    shows both a high p99 and a depressed delivery ratio — read
    together, they say "slow AND not yet arrived", not a contradiction
    (documented in ARCHITECTURE.md "What-if plane")."""
    delta = {k: float(totals_np[k][i]) - start.get(k, 0.0)
             for k in _COUNTER_KEYS}
    m = {
        "tx_packets": delta["tx_packets"],
        "delivered_packets": delta["rx_packets"],
        "delivered_bytes": delta["rx_bytes"],
        "dropped_loss": delta["dropped_loss"],
        "dropped_queue": delta["dropped_queue"],
        "dropped_ring": delta["dropped_ring"],
        "corrupted": delta["rx_corrupted"],
        "throughput_bps": (delta["rx_bytes"] * 8.0 / sim_seconds
                           if sim_seconds > 0 else 0.0),
        "delivery_ratio": (delta["rx_packets"] / delta["tx_packets"]
                           if delta["tx_packets"] > 0 else None),
        "mean_queue_occupancy": float(occ[i]),
        "latency_hist": [float(x) for x in hist[i]],
    }
    m.update(_percentiles(hist[i]))
    for extra in ("node_rx_packets", "node_rx_bytes", "fwd_dropped",
                  "no_route_dropped"):
        if extra in totals_np:
            m[extra] = float(totals_np[extra][i]) - start.get(extra, 0.0)
    return m


def _start_totals(counters) -> dict:
    return {k: float(np.asarray(getattr(counters, k)).sum())
            for k in _COUNTER_KEYS}


def _shard_replicas(tree, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubedtn_tpu.parallel.mesh import REPLICA_AXIS, replica_sharding

    # the canonical replica sharding when the mesh uses the standard
    # axis name; a caller-supplied custom mesh shards its first axis
    if mesh.axis_names and mesh.axis_names[0] == REPLICA_AXIS:
        sh = replica_sharding(mesh)
    else:
        sh = NamedSharding(mesh, P(mesh.axis_names[0]))
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


def prepare_sweep(snapshot: TwinSnapshot, scenarios, *, steps: int,
                  dt_us: float, spec: TrafficSpec | None = None,
                  k_slots: int = 4, seed: int = 0, mesh=None,
                  edits: ReplicaEdits | None = None, pod_ids=None):
    """Build the compiled-sweep inputs without running anything:
    ``(jitted, args, sig, n_replicas)`` with ``args = (bsim, keys,
    scale)``. This is the ONE place the sweep's program and argument
    layout are assembled — `run_sweep` executes it, and dtnverify
    (kubedtn_tpu.analysis.verify) traces the identical `jitted`/`args`
    pair into a jaxpr for contract verification, so the verified
    program cannot drift from the served one."""
    names = [sc.name for sc in scenarios]
    if len(set(names)) != len(names):
        # reports and the wire surface key ranks by name — a duplicate
        # would silently collapse two lanes' results
        raise ValueError("scenario names must be unique")
    base = snapshot.sim
    cap = base.edges.capacity
    if spec is None:
        from kubedtn_tpu.twin.query import build_cbr_spec

        spec = build_cbr_spec(base.edges)
    pad_to = None
    if mesh is not None:
        size = int(mesh.devices.size)
        pad_to = -(-max(len(scenarios), 1) // size) * size
    if edits is None:
        edits = compile_scenarios(scenarios, base.edges, pod_ids=pod_ids,
                                  pad_replicas_to=pad_to)
    n = edits.n_replicas
    if n < len(scenarios):
        raise ValueError("edits cover fewer replicas than scenarios")

    bsim = _broadcast(base, n)
    bedges = _apply_edits(bsim.edges, jnp.asarray(edits.rows),
                          jnp.asarray(edits.props),
                          jnp.asarray(edits.valid),
                          jnp.asarray(edits.drows),
                          jnp.asarray(edits.dvalid))
    bsim = dataclasses.replace(bsim, edges=bedges)
    scale = jnp.asarray(edits.scale)
    keys = jax.random.split(jax.random.key(seed), steps)
    if mesh is not None:
        bsim = _shard_replicas(bsim, mesh)
        scale = _shard_replicas(scale, mesh)

    spec_fp = _spec_fingerprint(spec)
    jitted = _sweep_fn(k_slots, float(dt_us), spec_fp)
    # spec_fp itself (not its hash): the spec is a closure constant,
    # invisible to _abstract_sig — a 64-bit hash collision between two
    # same-shaped specs would silently reuse an executable baked with
    # the wrong traffic constants
    sig = ("sim", k_slots, float(dt_us), spec_fp, steps, n, cap,
           _abstract_sig((bsim, keys, scale)),
           _mesh_sig(mesh))
    return jitted, (bsim, keys, scale), sig, n


def run_sweep(snapshot: TwinSnapshot, scenarios, *, steps: int,
              dt_us: float, spec: TrafficSpec | None = None,
              k_slots: int = 4, seed: int = 0, mesh=None,
              edits: ReplicaEdits | None = None, pod_ids=None,
              keep_final: bool = False) -> SweepResult:
    """Run one what-if sweep: scenario replicas forked from `snapshot`,
    advanced `steps` × `dt_us` of virtual time under one compiled scan.

    Replica layout: lane i runs scenarios[i]; when `mesh` is given the
    lane count pads up to a multiple of the mesh size with unperturbed
    replicas (dropped from the results). `spec` defaults to the query
    surface's offered load (query.build_cbr_spec — the ONE default, so
    a library sweep and a `kdt whatif` sweep answer the same question).
    `edits` short-circuits compilation for callers that prebuilt the
    batches.
    """
    names = [sc.name for sc in scenarios]
    jitted, (bsim, keys, scale), sig, n = prepare_sweep(
        snapshot, scenarios, steps=steps, dt_us=dt_us, spec=spec,
        k_slots=k_slots, seed=seed, mesh=mesh, edits=edits,
        pod_ids=pod_ids)
    compiled, compile_s = _compile_cached(jitted, sig, bsim, keys, scale)
    t0 = time.perf_counter()
    bfinal, hist, occ, totals = compiled(bsim, keys, scale)
    hist_np = np.asarray(hist)
    occ_np = np.asarray(occ)
    totals_np = {k: np.asarray(v) for k, v in totals.items()}
    run_s = time.perf_counter() - t0

    sim_seconds = steps * dt_us / 1e6
    start = _start_totals(snapshot.sim.counters)
    metrics = [_replica_metrics(i, totals_np, start, hist_np, occ_np,
                                sim_seconds)
               for i in range(len(scenarios))]
    return SweepResult(
        names=names, metrics=metrics, replicas=n, ticks=steps,
        sim_seconds=sim_seconds, compile_s=round(compile_s, 3),
        run_s=round(run_s, 3),
        replicas_steps_per_s=round(n * steps / max(run_s, 1e-9), 1),
        final=bfinal if keep_final else None)


def run_sweep_routed(snapshot: TwinSnapshot, scenarios, *, steps: int,
                     dt_us: float, spec: TrafficSpec, flow_dst,
                     k_slots: int = 4, k_fwd: int = 8, seed: int = 0,
                     mesh=None, pod_ids=None,
                     keep_final: bool = False) -> SweepResult:
    """run_sweep over the multi-hop forwarding plane: vmapped
    `router_step` with the snapshot's routing table shared across
    replicas. Link perturbations apply per replica; offered-load
    scaling needs the unrouted engine (`router_step` has no size dial),
    so a scaled scenario is rejected here."""
    rs = snapshot.router
    if rs is None:
        raise ValueError("snapshot carries no RouterState; capture with "
                         "snapshot_from_router")
    for sc in scenarios:
        if sc.traffic_scale != 1.0:
            raise ValueError(
                f"scenario {sc.name!r}: traffic scale is only supported "
                f"by the unrouted sweep (run_sweep)")
    cap = rs.sim.edges.capacity
    pad_to = None
    if mesh is not None:
        size = int(mesh.devices.size)
        pad_to = -(-max(len(scenarios), 1) // size) * size
    edits = compile_scenarios(scenarios, rs.sim.edges, pod_ids=pod_ids,
                              pad_replicas_to=pad_to)
    n = edits.n_replicas

    brs = _broadcast(rs, n)
    bedges = _apply_edits(brs.sim.edges, jnp.asarray(edits.rows),
                          jnp.asarray(edits.props),
                          jnp.asarray(edits.valid),
                          jnp.asarray(edits.drows),
                          jnp.asarray(edits.dvalid))
    brs = dataclasses.replace(
        brs, sim=dataclasses.replace(brs.sim, edges=bedges))
    keys = jax.random.split(jax.random.key(seed), steps)
    dt = jnp.float32(dt_us)
    if mesh is not None:
        brs = _shard_replicas(brs, mesh)

    jitted = _routed_sweep_fn(k_slots, k_fwd)
    sig = ("routed", k_slots, k_fwd, steps, n, cap,
           _abstract_sig((brs, spec, flow_dst, keys, dt)),
           _mesh_sig(mesh))
    compiled, compile_s = _compile_cached(jitted, sig, brs, spec,
                                          flow_dst, keys, dt)
    t0 = time.perf_counter()
    bfinal, totals = compiled(brs, spec, flow_dst, keys, dt)
    totals_np = {k: np.asarray(v) for k, v in totals.items()}
    run_s = time.perf_counter() - t0

    sim_seconds = steps * dt_us / 1e6
    start = _start_totals(rs.sim.counters)
    start["node_rx_packets"] = float(np.asarray(rs.node_rx_packets).sum())
    start["node_rx_bytes"] = float(np.asarray(rs.node_rx_bytes).sum())
    start["fwd_dropped"] = float(np.asarray(rs.fwd_dropped))
    start["no_route_dropped"] = float(np.asarray(rs.no_route_dropped))
    zeros = np.zeros((n, N_BINS), np.float32)
    occ = np.zeros((n,), np.float32)
    metrics = [_replica_metrics(i, totals_np, start, zeros, occ,
                                sim_seconds)
               for i in range(len(scenarios))]
    for m in metrics:
        m.pop("latency_hist", None)
        for k in ("p50_us", "p90_us", "p99_us"):
            m[k] = None
    return SweepResult(
        names=[sc.name for sc in scenarios], metrics=metrics,
        replicas=n, ticks=steps, sim_seconds=sim_seconds,
        compile_s=round(compile_s, 3), run_s=round(run_s, 3),
        replicas_steps_per_s=round(n * steps / max(run_s, 1e-9), 1),
        final=bfinal if keep_final else None)
