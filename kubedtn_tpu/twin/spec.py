"""Perturbation spec — the what-if vocabulary and its compiler.

A `Scenario` is a named list of `Perturbation`s; a sweep runs one
replica per scenario (plus an implicit unperturbed baseline and any
padding replicas). Perturbation kinds:

- "degrade": replace a link's properties (both directed rows) with new
  `LinkProperties` — UpdateLinks semantics, i.e. the qdisc chain is
  reinstalled so the row's mutable shaping state resets, exactly like
  the live control plane's `update_links` batches (topology deltas are
  expressed the same way: any uid → any property set).
- "fail": deactivate a link's rows (both directions) — the hard-down
  case property emulation can't express.
- "blackhole": deactivate EVERY row touching a node (src or dst) — the
  node-death case.
- "scale": multiply the scenario's offered load (generated packet
  bytes) by `factor`; factor 1.0 is a bitwise no-op, so the baseline
  replica stays bit-identical to an unbatched run.

Compilation is host-side: each scenario's property edits and
deactivations become rows in padded [N, B]-shaped batches, applied on
device by one vmapped scatter per sweep (kubedtn_tpu.twin.engine).
Padding lanes scatter out of bounds with mode="drop" — an empty
scenario's replica is bit-identical to the unedited base state.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from kubedtn_tpu.ops import edge_state as es

KINDS = ("degrade", "fail", "blackhole", "scale")


@dataclasses.dataclass(frozen=True)
class Perturbation:
    """One edit to a replica's universe."""

    kind: str                    # one of KINDS
    uid: int | None = None       # degrade / fail target link
    props: object | None = None  # LinkProperties for degrade
    node: object | None = None   # blackhole target: node id or pod name
    factor: float = 1.0          # scale multiplier
    # degrade only: restrict the edit to the directed row(s) whose
    # SOURCE is this node id — `update_links` semantics, which rebuild
    # only the local end's qdiscs. None (the default, and the only form
    # the wire surface emits) degrades every active row of the uid,
    # the historical both-directions behavior.
    src_node: int | None = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown perturbation kind {self.kind!r}; "
                             f"choices: {', '.join(KINDS)}")
        if self.kind in ("degrade", "fail") and self.uid is None:
            raise ValueError(f"{self.kind} perturbation needs a link uid")
        if self.kind == "degrade" and self.props is None:
            raise ValueError("degrade perturbation needs LinkProperties")
        if self.kind == "blackhole" and self.node is None:
            raise ValueError("blackhole perturbation needs a node")


@dataclasses.dataclass(frozen=True)
class Scenario:
    """A named bundle of perturbations — one replica of the sweep."""

    name: str
    perturbations: tuple = ()

    @property
    def traffic_scale(self) -> float:
        s = 1.0
        for p in self.perturbations:
            if p.kind == "scale":
                s *= float(p.factor)
        return s


@dataclasses.dataclass(frozen=True)
class ReplicaEdits:
    """Compiled per-replica edit batches (host numpy, padded).

    rows/props/valid drive a vmapped `update_links` scatter; drows/
    dvalid a vmapped `active`-mask clear; scale is the per-replica
    offered-load multiplier. Row 0 lanes of a scenario with no edits
    are all-invalid, which the scatters drop — a bitwise no-op.
    """

    rows: np.ndarray    # i32[N, B]
    props: np.ndarray   # f32[N, B, NPROP]
    valid: np.ndarray   # bool[N, B]
    drows: np.ndarray   # i32[N, Bd]
    dvalid: np.ndarray  # bool[N, Bd]
    scale: np.ndarray   # f32[N]

    @property
    def n_replicas(self) -> int:
        return self.rows.shape[0]


def _pad(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _resolve_node(node, pod_ids: dict | None) -> int:
    """Node id from an int, a pod name (engine registry lookup), or a
    digit string (the wire protocol's node field is a string, so a
    numeric id sent via `kdt whatif --daemon` arrives as "3" — the two
    query modes must resolve the same spec identically). A pod NAMED
    like a number wins over the numeric reading."""
    if isinstance(node, (int, np.integer)):
        return int(node)
    if pod_ids is not None:
        if node in pod_ids:
            return int(pod_ids[node])
        # pod keys are "ns/name": accept a bare name matching exactly one
        hits = [v for k, v in pod_ids.items()
                if k == node or k.endswith(f"/{node}")]
        if len(hits) == 1:
            return int(hits[0])
        if len(hits) > 1:
            raise ValueError(
                f"blackhole node {node!r}: ambiguous in pod registry")
    try:
        return int(str(node))
    except ValueError:
        pass
    if pod_ids is None:
        raise ValueError(
            f"blackhole node {node!r} is a name but no pod-id registry "
            f"was provided (pass ints, or compile with pod_ids=)")
    raise ValueError(f"blackhole node {node!r}: not found in pod registry")


def compile_scenarios(scenarios, edges, pod_ids: dict | None = None,
                      pad_replicas_to: int | None = None) -> ReplicaEdits:
    """Compile scenarios into padded per-replica edit batches.

    `edges` is the snapshot's EdgeState (host reads of uid/src/dst
    resolve targets); `pod_ids` the engine's endpoint→node registry for
    blackhole-by-name. `pad_replicas_to` rounds the replica count up
    with unperturbed padding replicas (sharding wants N divisible by
    the mesh size); padding replicas share the sweep's PRNG keys, so
    they cannot perturb any real replica's streams.
    """
    uid_arr = np.asarray(edges.uid)
    active = np.asarray(edges.active)
    src = np.asarray(edges.src)
    dst = np.asarray(edges.dst)
    cap = int(uid_arr.shape[0])

    per_rows: list[list[int]] = []
    per_props: list[list[np.ndarray]] = []
    per_drows: list[list[int]] = []
    scales: list[float] = []
    for sc in scenarios:
        rows_i: list[int] = []
        props_i: list[np.ndarray] = []
        drows_i: list[int] = []
        for p in sc.perturbations:
            if p.kind == "scale":
                continue
            if p.kind == "blackhole":
                nid = _resolve_node(p.node, pod_ids)
                hit = np.flatnonzero(active & ((src == nid) | (dst == nid)))
                if hit.size == 0:
                    # same contract as an unknown uid below: a silent
                    # no-op replica would rank the node's death as
                    # harmless — a wrong answer, not an empty one
                    raise ValueError(
                        f"scenario {sc.name!r}: blackhole node "
                        f"{p.node!r} (id {nid}) touches no active rows")
                drows_i.extend(int(r) for r in hit)
                continue
            mask = active & (uid_arr == int(p.uid))
            if p.kind == "degrade" and p.src_node is not None:
                mask &= src == int(p.src_node)
            hit = np.flatnonzero(mask)
            if hit.size == 0:
                raise ValueError(
                    f"scenario {sc.name!r}: no active rows for link uid "
                    f"{p.uid}"
                    + (f" with src node {p.src_node}"
                       if p.src_node is not None else ""))
            if p.kind == "fail":
                drows_i.extend(int(r) for r in hit)
            else:  # degrade
                prow, _shaped = es.props_row_and_shaped(p.props)
                for r in hit:
                    rows_i.append(int(r))
                    props_i.append(prow)
        per_rows.append(rows_i)
        per_props.append(props_i)
        per_drows.append(drows_i)
        scales.append(sc.traffic_scale)

    n = len(scenarios)
    n_pad = max(n, 1)
    if pad_replicas_to is not None:
        n_pad = max(n_pad, int(pad_replicas_to))
    b = _pad(max((len(r) for r in per_rows), default=1) or 1)
    bd = _pad(max((len(r) for r in per_drows), default=1) or 1)

    rows = np.full((n_pad, b), cap, np.int32)      # cap = dropped lane
    props = np.zeros((n_pad, b, es.NPROP), np.float32)
    valid = np.zeros((n_pad, b), bool)
    drows = np.full((n_pad, bd), cap, np.int32)
    dvalid = np.zeros((n_pad, bd), bool)
    scale = np.ones((n_pad,), np.float32)
    for i in range(n):
        m = len(per_rows[i])
        if m:
            rows[i, :m] = per_rows[i]
            props[i, :m] = np.stack(per_props[i])
            valid[i, :m] = True
        md = len(per_drows[i])
        if md:
            drows[i, :md] = per_drows[i]
            dvalid[i, :md] = True
        scale[i] = scales[i]
    return ReplicaEdits(rows=rows, props=props, valid=valid,
                       drows=drows, dvalid=dvalid, scale=scale)
