"""Snapshot-fork: consistent capture of a running twin.

The consistency contract (see ARCHITECTURE.md "What-if plane"):

- From a LIVE data plane, the capture happens under the plane's tick
  lock AFTER a pipeline `flush()` — every in-flight shaping dispatch
  lands its edge-state write-back first, so the captured token buckets,
  correlation memory and backlog clocks are exactly the state the next
  live tick would shape against. The runner is paused for one barrier
  (microseconds to a few ms), never stopped: the real-time plane loses
  zero frames while a sweep runs.
- EdgeState arrays are immutable jax arrays; holding references IS the
  snapshot — no copy, no torn reads after the barrier.
- From a pure `SimState`/`RouterState`, the snapshot is the state
  itself: forking replicas from step t of a virtual run continues it
  bit-exactly (replica 0 of an unperturbed sweep equals the unforked
  run — pinned by tests/test_twin.py).

Snapshots serialize through the checkpoint machinery's npz layout
(`save_snapshot`/`load_snapshot`) so a sweep can be re-run offline
against the exact captured state.
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np

from kubedtn_tpu.sim import SimState, init_sim


@dataclasses.dataclass(frozen=True)
class TwinSnapshot:
    """A consistent point-in-time fork base for the replica engine."""

    sim: SimState            # edges + inflight + counters + traffic + clock
    router: object | None    # RouterState when captured from a routed run
    n_nodes: int             # node-id space (blackhole resolution)
    captured_at_s: float     # wall clock of the capture
    source: str              # "plane" | "sim" | "router" | "engine"
    # live-plane virtual clock at capture (None unless source=="plane"),
    # kept as HOST float64 — sim.clock_us is f32 and a monotonic-clock
    # anchor (hours of µs) exceeds f32 spacing, so anchoring the device
    # clock would both mis-place it and freeze `clock_us + dt_us`
    plane_clock_s: float | None = None


def snapshot_from_sim(sim: SimState, n_nodes: int = 0) -> TwinSnapshot:
    """Fork base from a virtual-time run's SimState (bit-exact resume)."""
    return TwinSnapshot(sim=sim, router=None, n_nodes=int(n_nodes),
                        captured_at_s=time.time(), source="sim")


def snapshot_from_router(rs, n_nodes: int | None = None) -> TwinSnapshot:
    """Fork base from a routed run's RouterState (bit-exact resume)."""
    if n_nodes is None:
        n_nodes = int(rs.node_rx_packets.shape[0])
    return TwinSnapshot(sim=rs.sim, router=rs, n_nodes=int(n_nodes),
                        captured_at_s=time.time(), source="router")


def snapshot_from_engine(engine, q: int = 32) -> TwinSnapshot:
    """Fork base from an engine with no data plane attached: the edge
    state (including pending control-plane ops, flushed by the `state`
    property) with a fresh delay line / traffic state."""
    with engine._lock:
        state = engine.state  # flushes pending control-plane batches
        n_nodes = len(engine._pod_ids)
    return TwinSnapshot(sim=init_sim(state, q=q), router=None,
                        n_nodes=max(n_nodes, 1),
                        captured_at_s=time.time(), source="engine")


def snapshot_from_plane(plane, q: int = 32) -> TwinSnapshot:
    """Consistent capture from a LIVE WireDataPlane without stopping it.

    Takes the tick lock (the runner finishes its current tick and then
    waits one barrier), crosses `flush()` so every in-flight pipelined
    dispatch has written its dynamic edge-state columns back, snapshots
    the engine state + cumulative counters, and releases — the runner's
    next tick proceeds normally. The live wheel-held frames are process
    state, not simulation state: replicas synthesize their own traffic
    from the captured shaping state (the same boundary the pending-frame
    checkpoint draws — see checkpoint.save_pending).
    """
    engine = plane.engine
    with plane._tick_lock:
        plane.flush()
        with engine._lock:
            state = engine.state  # flushes pending control-plane ops
            n_nodes = len(engine._pod_ids)
        clock_s = plane.last_now_s
    # fresh delay line + counters (the sweep measures the what-if
    # horizon); the virtual clock starts at 0 — the plane's own clock is
    # carried host-side in plane_clock_s (see the field note)
    sim = init_sim(state, q=q)
    return TwinSnapshot(sim=sim, router=None, n_nodes=max(n_nodes, 1),
                        captured_at_s=time.time(), source="plane",
                        plane_clock_s=clock_s)


# -- offline persistence (checkpoint-machinery npz codec) --------------

def save_snapshot(path: str, snap: TwinSnapshot) -> None:
    """Persist a snapshot's SimState as one npz via the checkpoint
    module's shared flatten (the `<field>.<leaf>` layout, edges
    inlined — one codec for both formats)."""
    from kubedtn_tpu.checkpoint import flatten_sim_arrays

    flat = flatten_sim_arrays(snap.sim, include_edges=True)
    flat["n_nodes"] = np.asarray(snap.n_nodes, np.int64)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    np.savez_compressed(path, **flat)


def load_snapshot(path: str) -> TwinSnapshot:
    from kubedtn_tpu.checkpoint import unflatten_sim_arrays

    with np.load(path) as z:
        sim = unflatten_sim_arrays(z)
        n_nodes = int(z["n_nodes"])
    return TwinSnapshot(sim=sim, router=None, n_nodes=n_nodes,
                        captured_at_s=time.time(), source="sim")
