"""Daemon-side what-if query surface.

`Local.WhatIf` (a framework extension of the reference IDL, like
InjectBulk) lets any client ask a LIVE daemon "what would your network
do under these futures": the handler forks a consistent snapshot of
the running data plane (snapshot_from_plane's flush barrier — the
real-time runner keeps ticking, zero frame loss), compiles the
request's scenarios, runs the batched replica sweep on device, and
returns ranked per-scenario metrics. Sweep counts, replica volume and
the compile/run split are exported as `kubedtn_whatif_*` through the
existing metrics registry (metrics.WhatIfStatsCollector).
"""

from __future__ import annotations

import threading

from kubedtn_tpu.twin.report import rank_results
from kubedtn_tpu.twin.snapshot import snapshot_from_engine, \
    snapshot_from_plane
from kubedtn_tpu.twin.spec import Perturbation, Scenario

DEFAULT_TICKS = 1000
DEFAULT_DT_US = 1000.0
DEFAULT_RATE_BPS = 1e6
DEFAULT_PKT_BYTES = 200.0
MAX_TICKS = 200_000
MAX_SCENARIOS = 1024
# k_slots is a STATIC compile parameter sizing the [E, K] slot arrays
# and the K-sequential qdisc scan — unbounded it defeats every other
# ceiling here via one enormous trace/compile
MAX_K_SLOTS = 64
# per-request work and memory ceilings: ticks and scenario count are
# each bounded above, but their PRODUCT (and the replica-broadcast
# footprint replicas × edge capacity) is what a gRPC worker actually
# pays — one in-limit 1024×200k request would otherwise pin a worker
# for hours (CPU) or OOM the daemon serving the live plane
MAX_REPLICA_STEPS = 2_000_000
MAX_REPLICA_CELLS = 4_000_000
# concurrent sweeps allowed per daemon: a sweep can legitimately run
# for minutes on a slow host, and the gRPC pool has 16 workers shared
# with the LIVE data plane's peer RPCs — unbounded concurrent sweeps
# would starve those (breakers open, outage buffers fill). One sweep
# computes at a time; a second request waits briefly, then is refused
# loudly instead of parking a worker.
#
# TENANT SCOPING (round 10): the single-sweep slot is per TENANT — one
# tenant's long sweep can no longer park every other tenant's
# Local.WhatIf behind a global lock. Untenanted requests share the ""
# pool. A small PROCESS-WIDE cap still bounds total concurrency so N
# tenants cannot occupy the whole gRPC worker pool with sweeps.
MAX_CONCURRENT_SWEEPS = 1
MAX_PROCESS_SWEEPS = 4
SWEEP_WAIT_S = 10.0


class WhatIfStats:
    """Cumulative counters behind the kubedtn_whatif_* series."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.sweeps = 0
        self.scenarios = 0
        self.replicas = 0
        self.replica_steps = 0
        self.compile_s = 0.0
        self.run_s = 0.0
        self.errors = 0

    def record(self, result, n_scenarios: int) -> None:
        with self._lock:
            self.sweeps += 1
            self.scenarios += n_scenarios
            self.replicas += result.replicas
            self.replica_steps += result.replicas * result.ticks
            self.compile_s += result.compile_s
            self.run_s += result.run_s

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "sweeps_served": self.sweeps,
                "scenarios_served": self.scenarios,
                "replicas_run": self.replicas,
                "replica_steps_run": self.replica_steps,
                "compile_seconds": self.compile_s,
                "run_seconds": self.run_s,
                "errors": self.errors,
            }


_ATTACH_LOCK = threading.Lock()  # guards first-use attachment races


def stats_for(daemon) -> WhatIfStats:
    """The daemon's WhatIfStats, created on first use."""
    with _ATTACH_LOCK:
        st = getattr(daemon, "whatif_stats", None)
        if st is None:
            st = daemon.whatif_stats = WhatIfStats()
        return st


def _sweep_slots(daemon, tenant: str = "") -> threading.BoundedSemaphore:
    """The sweep-concurrency slot for one tenant ("" = the untenanted
    shared pool): a bounded per-tenant pool, created on first use, so
    tenants queue behind THEIR OWN sweeps only."""
    with _ATTACH_LOCK:
        slots = getattr(daemon, "_whatif_slots", None)
        if slots is None or not isinstance(slots, dict):
            slots = daemon._whatif_slots = {}
        sem = slots.get(tenant)
        if sem is None:
            sem = slots[tenant] = threading.BoundedSemaphore(
                MAX_CONCURRENT_SWEEPS)
        return sem


def _process_slots(daemon) -> threading.BoundedSemaphore:
    """Process-wide sweep cap across ALL tenants (gRPC-pool guard)."""
    with _ATTACH_LOCK:
        sem = getattr(daemon, "_whatif_process_slots", None)
        if sem is None:
            sem = daemon._whatif_process_slots = \
                threading.BoundedSemaphore(MAX_PROCESS_SWEEPS)
        return sem


def build_cbr_spec(edges, rate_bps: float = DEFAULT_RATE_BPS,
                   pkt_bytes: float = DEFAULT_PKT_BYTES):
    """The sweep's default offered load: CBR on every ACTIVE edge. The
    ONE construction both query modes use — `kdt whatif --daemon` and
    `--file` must answer the same question for the same flags, so the
    defaults live here, not in copies."""
    import dataclasses

    import jax.numpy as jnp

    from kubedtn_tpu.models.traffic import cbr_everywhere

    cap = edges.capacity
    spec = cbr_everywhere(cap, cap, rate_bps=rate_bps,
                          pkt_bytes=pkt_bytes)
    return dataclasses.replace(
        spec, mode=jnp.where(edges.active, spec.mode, 0))


def scenarios_from_request(request, props_from_proto) -> list:
    """Wire → spec translation. Proto3 scalars carry no presence, so
    fields are interpreted BY KIND rather than by truthiness — a scale
    factor of 0 ("this source stops") and a link uid of 0 are both
    legal values and must not silently coerce to defaults."""
    out = []
    for sc in request.scenarios:
        perts = []
        for p in sc.perturbations:
            kind = p.kind or "degrade"
            perts.append(Perturbation(
                kind=kind,
                uid=(int(p.uid) if kind in ("degrade", "fail")
                     else None),
                props=(props_from_proto(p.properties)
                       if kind == "degrade" else None),
                node=p.node or None,
                factor=p.factor if kind == "scale" else 1.0,
            ))
        out.append(Scenario(name=sc.name or f"scenario{len(out)}",
                            perturbations=tuple(perts)))
    return out


def serve_whatif(daemon, request):
    """The Local.WhatIf handler body (imported lazily by the daemon so
    the twin engine costs nothing until the first query)."""
    from kubedtn_tpu.utils import tracing

    with tracing.span("whatif-sweep",
                      scenarios=len(request.scenarios)):
        return _serve_whatif_traced(daemon, request)


def _serve_whatif_traced(daemon, request):
    from kubedtn_tpu.twin.engine import run_sweep
    from kubedtn_tpu.wire import proto as pb

    stats = stats_for(daemon)
    try:
        ticks = int(request.ticks) or DEFAULT_TICKS
        if not 0 < ticks <= MAX_TICKS:
            raise ValueError(f"ticks must be in (0, {MAX_TICKS}]")
        dt_us = float(request.dt_us) or DEFAULT_DT_US
        if dt_us <= 0:
            raise ValueError("dt_us must be positive")
        if len(request.scenarios) > MAX_SCENARIOS:
            raise ValueError(f"at most {MAX_SCENARIOS} scenarios per "
                             f"sweep")
        k_slots = int(request.k_slots) or 4
        if not 0 < k_slots <= MAX_K_SLOTS:
            raise ValueError(f"k_slots must be in (0, {MAX_K_SLOTS}]")
        scenarios = scenarios_from_request(request, pb.props_from_proto)
        if request.include_baseline or not scenarios:
            scenarios = [Scenario(name="baseline"), *scenarios]
        names = [sc.name for sc in scenarios]
        if len(set(names)) != len(names):
            # ranks (server AND client side) key by name: a duplicate —
            # including a user scenario named "baseline" next to the
            # injected one — would collapse two lanes' ranks silently
            raise ValueError(
                "scenario names must be unique ('baseline' is reserved "
                "when include_baseline is set)")

        n_replicas = len(scenarios)
        if n_replicas * ticks > MAX_REPLICA_STEPS:
            raise ValueError(
                f"scenarios × ticks = {n_replicas * ticks} exceeds the "
                f"per-request budget {MAX_REPLICA_STEPS}")

        # sweeps compute for seconds-to-minutes: bound how many run at
        # once so they can never occupy the gRPC pool the live data
        # plane's peer RPCs share — refuse loudly rather than park.
        # The slot is PER TENANT (plus a process-wide cap): tenant A's
        # sweep never parks tenant B's Local.WhatIf.
        tenant = getattr(request, "tenant", "") or ""
        registry = getattr(daemon, "tenancy", None)
        if tenant and (registry is None
                       or registry.get(tenant) is None):
            raise ValueError(f"unknown tenant {tenant!r}")
        slots = _sweep_slots(daemon, tenant)
        if not slots.acquire(timeout=SWEEP_WAIT_S):
            raise RuntimeError(
                f"another what-if sweep is in progress for "
                f"{'tenant ' + tenant if tenant else 'this daemon'}; "
                f"retry later")
        proc = _process_slots(daemon)
        if not proc.acquire(timeout=SWEEP_WAIT_S):
            slots.release()
            raise RuntimeError(
                "the daemon-wide what-if concurrency cap is occupied; "
                "retry later")
        try:
            plane = getattr(daemon, "dataplane", None)
            if tenant:
                # tenant-scoped fork: only this tenant's edge slice is
                # active in the replicas (tenancy.tenant_snapshot)
                snap = registry.tenant_snapshot(
                    plane if plane is not None else daemon.engine,
                    tenant)
            elif plane is not None:
                snap = snapshot_from_plane(plane)
            else:
                snap = snapshot_from_engine(daemon.engine)
            if n_replicas * snap.sim.edges.capacity > MAX_REPLICA_CELLS:
                raise ValueError(
                    f"scenarios × edge capacity = "
                    f"{n_replicas * snap.sim.edges.capacity} exceeds the "
                    f"replica-broadcast budget {MAX_REPLICA_CELLS}")
            with daemon.engine._lock:
                pod_ids = dict(daemon.engine._pod_ids)

            # proto3 presence convention (as for ticks/dt_us/k_slots):
            # 0 means UNSET → default. Zero offered load is expressed
            # with a scale-0 scenario, never a zero rate; negatives are
            # rejected rather than fed to the generator.
            rate = float(request.traffic_rate_bps) or DEFAULT_RATE_BPS
            pkt = float(request.traffic_pkt_bytes) or DEFAULT_PKT_BYTES
            if rate < 0 or pkt < 0:
                raise ValueError(
                    "traffic_rate_bps/traffic_pkt_bytes must be "
                    "positive (0 = default; use a scale-0 scenario "
                    "for zero offered load)")
            spec = build_cbr_spec(snap.sim.edges, rate_bps=rate,
                                  pkt_bytes=pkt)

            result = run_sweep(
                snap, scenarios, steps=ticks, dt_us=dt_us, spec=spec,
                k_slots=k_slots, seed=int(request.seed),
                pod_ids=pod_ids)
        finally:
            proc.release()
            slots.release()
    except Exception as e:  # a bad query must not kill the worker
        stats.record_error()
        from kubedtn_tpu.utils.logging import fields, get_logger

        get_logger("whatif").warning(
            "whatif sweep failed %s",
            fields(error=f"{type(e).__name__}: {e}"))
        return pb.WhatIfResponse(ok=False,
                                 error=f"{type(e).__name__}: {e}")

    stats.record(result, len(scenarios))
    ranks = {name: r for name, _m, r in rank_results(result)}
    msgs = []
    for name, m in zip(result.names, result.metrics):
        msgs.append(pb.WhatIfMetrics(
            name=name,
            tx_packets=m["tx_packets"],
            delivered_packets=m["delivered_packets"],
            delivered_bytes=m["delivered_bytes"],
            dropped_loss=m["dropped_loss"],
            dropped_queue=m["dropped_queue"],
            dropped_ring=m["dropped_ring"],
            throughput_bps=m["throughput_bps"],
            delivery_ratio=(m["delivery_ratio"]
                            if m["delivery_ratio"] is not None else -1.0),
            p50_us=m["p50_us"] if m["p50_us"] is not None else -1.0,
            p90_us=m["p90_us"] if m["p90_us"] is not None else -1.0,
            p99_us=m["p99_us"] if m["p99_us"] is not None else -1.0,
            p99_censored=bool(m.get("p99_censored", False)),
            mean_queue_occupancy=m["mean_queue_occupancy"],
            latency_hist=m["latency_hist"],
            rank=ranks[name],
        ))
    return pb.WhatIfResponse(
        ok=True, results=msgs, replicas=result.replicas,
        ticks=result.ticks, sim_seconds=result.sim_seconds,
        compile_s=result.compile_s, run_s=result.run_s,
        replicas_steps_per_s=result.replicas_steps_per_s)
