"""Sweep reporting: rank scenarios and render the comparison table.

Used by the `kdt whatif` CLI (local and daemon-served sweeps) and by
anything that wants a human-readable answer out of a SweepResult. The
ranking is impact-ordered: scenarios that hurt the network most rank
first — the operator's question is "which of these futures do I need
to worry about", so the sort key is (delivery ratio ascending, p99
latency descending, throughput ascending).
"""

from __future__ import annotations


def _key(name: str, m: dict):
    dr = m.get("delivery_ratio")
    p99 = m.get("p99_us")
    return (
        dr if dr is not None else 2.0,      # unknown ranks after real
        -(p99 if p99 is not None else -1.0),
        m.get("throughput_bps", 0.0),
        name,
    )


def rank_results(result, ranks: dict | None = None) -> list:
    """(name, metrics, rank) triples, worst-impact first. `ranks`
    (name → rank) overrides the local scoring — a daemon-served sweep
    already ranked server-side, and re-deriving here could silently
    disagree if the scoring ever changes on one side only."""
    if ranks is not None:
        rows = sorted(zip(result.names, result.metrics),
                      key=lambda nm: ranks[nm[0]])
        return [(name, m, ranks[name]) for name, m in rows]
    rows = sorted(zip(result.names, result.metrics),
                  key=lambda nm: _key(*nm))
    return [(name, m, i + 1) for i, (name, m) in enumerate(rows)]


def _fmt(v, unit: str = "") -> str:
    if v is None:
        return "-"
    if unit == "us":
        return f"{v / 1000.0:.2f}ms" if v >= 1000 else f"{v:.0f}us"
    if unit == "bps":
        for div, suf in ((1e9, "G"), (1e6, "M"), (1e3, "K")):
            if abs(v) >= div:
                return f"{v / div:.2f}{suf}bit/s"
        return f"{v:.0f}bit/s"
    if unit == "ratio":
        return f"{100.0 * v:.2f}%"
    if isinstance(v, float):
        return f"{v:,.0f}"
    return str(v)


def render_report(result, title: str = "what-if sweep",
                  ranks: dict | None = None) -> str:
    """Fixed-width ranked comparison — the `kdt whatif` output."""
    cols = [
        ("#", lambda n, m, r: str(r)),
        ("scenario", lambda n, m, r: n),
        ("delivery", lambda n, m, r: _fmt(m.get("delivery_ratio"),
                                          "ratio")),
        ("p50", lambda n, m, r: _fmt(m.get("p50_us"), "us")),
        # a censored p99 clamped at the ladder's open top bucket reads
        # ">5000ms", never "=5000ms" (telemetry.percentiles_from_hist)
        ("p99", lambda n, m, r: (">" if m.get("p99_censored") else "")
            + _fmt(m.get("p99_us"), "us")),
        ("throughput", lambda n, m, r: _fmt(m.get("throughput_bps"),
                                            "bps")),
        ("lost", lambda n, m, r: _fmt(
            m.get("dropped_loss", 0.0) + m.get("dropped_queue", 0.0)
            + m.get("dropped_ring", 0.0))),
        ("queue", lambda n, m, r: _fmt(m.get("mean_queue_occupancy"))),
    ]
    ranked = rank_results(result, ranks=ranks)
    table = [[fn(n, m, r) for _h, fn in cols] for n, m, r in ranked]
    widths = [max(len(h), *(len(row[i]) for row in table))
              if table else len(h)
              for i, (h, _fn) in enumerate(cols)]
    lines = [
        f"{title}: {result.replicas} replicas x {result.ticks} ticks "
        f"({result.sim_seconds:g}s virtual) in {result.run_s:.3f}s wall"
        + (f" (+{result.compile_s:.2f}s compile)" if result.compile_s
           else "")
        + f", {result.replicas_steps_per_s:,.0f} replica-steps/s",
        "  ".join(h.ljust(w) for (h, _fn), w in zip(cols, widths)),
    ]
    lines.extend("  ".join(c.ljust(w) for c, w in zip(row, widths))
                 for row in table)
    return "\n".join(lines)
