"""What-if plane — batched snapshot-fork replica engine.

The live data plane answers "what IS the network doing"; this package
answers "what WOULD it do" — fork a consistent snapshot of the running
twin, apply N perturbed futures (link degrades/failures, node
blackholes, offered-load scaling, property deltas), and advance all N
replicas × T virtual ticks in ONE jitted scan with the replica axis as
just another array dimension. The reference, bound to kernel qdisc
clocks, can never run one topology faster than real time, let alone
hundreds of perturbed copies at once.

Layers:
- snapshot: consistent capture from a live plane / sim / router state
  (crossing the pipeline flush() barrier — the runner never stops).
- spec: the perturbation vocabulary and its compilation into padded
  per-replica edit batches (device scatters, update_links semantics).
- engine: the batched replica engine — vmapped `sim_step`/`router_step`
  under one lax.scan, on-device metric reductions (latency histogram
  against the reference Prometheus buckets, loss, throughput, queue
  occupancy), optional sharding over the parallel/mesh replica axis.
- report: ranking + rendering of a sweep (the `kdt whatif` output).
- query: the daemon-side WhatIfRequest service surface.
"""

from kubedtn_tpu.twin.engine import SweepResult, run_sweep, run_sweep_routed
from kubedtn_tpu.twin.report import rank_results, render_report
from kubedtn_tpu.twin.snapshot import (
    TwinSnapshot,
    snapshot_from_plane,
    snapshot_from_router,
    snapshot_from_sim,
)
from kubedtn_tpu.twin.spec import Perturbation, Scenario, compile_scenarios

__all__ = [
    "Perturbation",
    "Scenario",
    "SweepResult",
    "TwinSnapshot",
    "compile_scenarios",
    "rank_results",
    "render_report",
    "run_sweep",
    "run_sweep_routed",
    "snapshot_from_plane",
    "snapshot_from_router",
    "snapshot_from_sim",
]
