"""tpudtn CLI — operator entry point.

Subsumes the reference's operator tooling: the controller+daemon runtime
(`daemon`), scenario loading (`apply`, like kubectl apply of the sample
YAMLs), the ping smoke test (reference hack/test-3node.sh), the physical
-host join CLI (reference cmd/main.go) as `physical-join`, plus the
BASELINE scenario ladder and the headline bench.

Usage:
  python -m kubedtn_tpu.cli apply config/samples/3node.yml
  python -m kubedtn_tpu.cli ping r1 r2 --uid 1 --file 3node.yml
  python -m kubedtn_tpu.cli scenario clos_100k
  python -m kubedtn_tpu.cli daemon --port 51111 --metrics-port 51112
  python -m kubedtn_tpu.cli physical-join link.yml --daemon 127.0.0.1:51111
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time


def _rpc_code(e) -> str:
    """Short status-code name for a grpc.RpcError (shared by every verb
    that dials a daemon)."""
    try:
        return e.code().name
    except Exception:
        return type(e).__name__


def _json_safe(obj):
    """inf/nan are not valid JSON — emit null for unreachable values."""
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    return obj


def cmd_apply(args) -> int:
    if getattr(args, "plan", None):
        return _cmd_apply_plan(args)
    if not args.file:
        print("apply needs a topology YAML file, or --plan ID --daemon "
              "HOST:PORT to apply a staged plan", file=sys.stderr)
        return 1
    from kubedtn_tpu.api.types import load_yaml
    from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore

    store = TopologyStore()
    engine = SimEngine(store)
    topos = load_yaml(args.file)
    for t in topos:
        t.validate()
        store.create(t)
    for t in topos:
        engine.setup_pod(t.name, t.namespace)
    rec = Reconciler(store, engine)
    results = rec.drain()
    print(json.dumps({
        "topologies": len(topos),
        "links_realized": engine.num_active,
        "reconciles": len(results),
    }))
    return 0


def _cmd_apply_plan(args) -> int:
    """`kdt apply --plan ID --daemon HOST:PORT`: stage a previously
    verified plan through the daemon's live plane (watch windows +
    automatic rollback — Local.ApplyPlan)."""
    import grpc

    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient

    client = DaemonClient(args.daemon)
    try:
        resp = client.ApplyPlan(
            pb.ApplyPlanRequest(plan_id=int(args.plan),
                                observe_ticks=args.observe_ticks),
            timeout=args.timeout)
    except grpc.RpcError as e:
        print(f"apply: daemon {args.daemon} RPC failed: {_rpc_code(e)}",
              file=sys.stderr)
        return 1
    finally:
        client.close()
    out = {"ok": bool(resp.ok), "rounds_applied": resp.rounds_applied,
           "rolled_back": bool(resp.rolled_back),
           "reason": resp.reason or resp.error,
           "stage_s": resp.stage_s}
    print(json.dumps(_json_safe(out)))
    return 0 if resp.ok else 1


def cmd_plan(args) -> int:
    """`kdt plan topo.yml --daemon HOST:PORT`: declare each topology's
    desired links, get back the ordered schedule + the twin gate's
    verdict, and (when verified) a plan id for `kdt apply --plan`
    (Local.PlanUpdate)."""
    import grpc

    from kubedtn_tpu.api.types import load_yaml
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient

    try:
        topos = load_yaml(args.file)
    except (OSError, ValueError) as e:
        print(f"plan: {e}", file=sys.stderr)
        return 1
    none_if = lambda v: None if v < 0 else v  # noqa: E731
    client = DaemonClient(args.daemon)
    results = []
    rc = 0
    try:
        for t in topos:
            req = pb.PlanUpdateRequest(
                name=t.name, kube_ns=t.namespace,
                links=[pb.link_to_proto(l) for l in t.spec.links],
                ticks=args.ticks, dt_us=args.dt_us,
                max_delivery_drop=args.max_delivery_drop,
                max_p99_factor=args.max_p99_factor,
                max_round_edits=args.max_round_edits, seed=args.seed)
            try:
                resp = client.PlanUpdate(req, timeout=args.timeout)
            except grpc.RpcError as e:
                print(f"plan: daemon {args.daemon} RPC failed: "
                      f"{_rpc_code(e)}", file=sys.stderr)
                return 1
            key = f"{t.namespace}/{t.name}"
            if not resp.ok:
                results.append({"topology": key, "ok": False,
                                "error": resp.error})
                rc = 1
                continue
            results.append({
                "topology": key,
                "ok": True,
                "verified": bool(resp.verified),
                "plan_id": int(resp.plan_id),
                "reject_reason": resp.reject_reason,
                "rounds": [{
                    "index": r.index, "adds": r.adds,
                    "changes": r.changes, "dels": r.dels,
                    "delivery_ratio": none_if(r.delivery_ratio),
                    "p99_us": none_if(r.p99_us),
                } for r in resp.rounds],
                "baseline_delivery_ratio": none_if(
                    resp.baseline_delivery_ratio),
                "baseline_p99_us": none_if(resp.baseline_p99_us),
                "gate_s": resp.gate_s,
                "skipped_adds": resp.skipped_adds,
            })
            if not resp.verified:
                rc = 1
    finally:
        client.close()
    if args.json:
        print(json.dumps(_json_safe({"plans": results})))
        return rc
    for r in results:
        if not r["ok"]:
            print(f"{r['topology']}: ERROR {r['error']}")
            continue
        if not r["rounds"]:
            print(f"{r['topology']}: no changes (empty diff)")
            continue
        verdict = ("VERIFIED" if r["verified"]
                   else f"REJECTED ({r['reject_reason']})")
        base = r["baseline_delivery_ratio"]
        base_s = f"{100 * base:.2f}%" if base is not None else "-"
        print(f"{r['topology']}: {verdict}  plan_id={r['plan_id']}  "
              f"rounds={len(r['rounds'])}  baseline_delivery={base_s}  "
              f"gate={r['gate_s']:.2f}s"
              + (f"  skipped_adds={r['skipped_adds']}"
                 if r["skipped_adds"] else ""))
        for rd in r["rounds"]:
            dr = rd["delivery_ratio"]
            dr_s = f"{100 * dr:.2f}%" if dr is not None else "-"
            p99 = rd["p99_us"]
            p99_s = f"{p99:.0f}us" if p99 is not None else "-"
            print(f"  round {rd['index'] + 1}: +{rd['adds']} "
                  f"~{rd['changes']} -{rd['dels']}  delivery={dr_s}  "
                  f"p99={p99_s}")
        if r["verified"]:
            print(f"  apply with: kdt apply --plan {r['plan_id']} "
                  f"--daemon {args.daemon}")
    return rc


def _engine_from_yaml(path):
    from kubedtn_tpu.api.types import load_yaml
    from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore

    store = TopologyStore()
    engine = SimEngine(store)
    topos = load_yaml(path)
    for t in topos:
        store.create(t)
    for t in topos:
        engine.setup_pod(t.name, t.namespace)
    Reconciler(store, engine).drain()
    return engine, topos


def cmd_ping(args) -> int:
    engine, topos = _engine_from_yaml(args.file)
    uid = args.uid
    if uid is None:
        for t in topos:
            if t.name != args.a:
                continue
            for l in t.spec.links:
                if l.peer_pod == args.b:
                    uid = l.uid
    if uid is None:
        print(f"no link between {args.a} and {args.b}", file=sys.stderr)
        return 1
    out = engine.ping(args.a, args.b, uid)
    print(json.dumps(_json_safe(out)))
    return 0 if out["reachable"] else 1


def cmd_trace(args) -> int:
    """Two modes sharing one verb:

    - path mode (`kdt trace a b --file topo.yml`): multi-hop route
      query across the fabric (ping's traceroute sibling);
    - flight-recorder mode (`kdt trace <trace-id|latest> --daemon A
      [--daemon B ...]`): reconstruct a SAMPLED FRAME's hop-by-hop
      lifecycle — ingress → bypass/shaped → delivered/dropped(cause) →
      staged-peer → outage-buffered/retried → peer-sent → received —
      by merging the flight-recorder events of every named daemon
      (cross-node trace correlation, Local.ObserveTrace)."""
    if args.daemon:
        return _cmd_trace_flight(args)
    if not args.file or args.b is None:
        print("trace needs `a b --file topo.yml` (path mode) or "
              "`<trace-id|latest> --daemon HOST:PORT` (flight-recorder "
              "mode)", file=sys.stderr)
        return 1
    engine, _ = _engine_from_yaml(args.file)
    out = engine.trace(args.a, args.b, max_hops=args.max_hops)
    print(json.dumps(_json_safe(out)))
    return 0 if out["reachable"] else 1


def _cmd_trace_flight(args) -> int:
    import grpc

    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient

    clients = []
    try:
        for addr in args.daemon:
            clients.append((addr, DaemonClient(addr)))

        def observe(client, tid):
            return client.ObserveTrace(
                pb.ObserveTraceRequest(trace_id=tid,
                                       limit=args.max_hops * 64),
                timeout=10.0)

        tid = 0
        if args.a != "latest":
            try:
                tid = int(args.a, 0)  # decimal or 0x-hex
            except ValueError:
                print(f"trace: {args.a!r} is not a trace id (use a "
                      f"decimal/hex id or 'latest')", file=sys.stderr)
                return 1
        events = []
        recents: list[int] = []
        for addr, client in clients:
            try:
                resp = observe(client, tid)
            except grpc.RpcError as e:
                print(f"trace: daemon {addr} RPC failed: "
                      f"{_rpc_code(e)}", file=sys.stderr)
                return 1
            if not resp.ok:
                print(f"trace: {addr}: {resp.error}", file=sys.stderr)
                return 1
            recents.extend(int(t) for t in resp.recent_traces)
            events.extend(
                {"trace_id": int(e.trace_id), "t": e.t, "node": e.node,
                 "stage": e.stage, "detail": e.detail}
                for e in resp.events)
        if tid == 0:
            # newest sampled trace across the daemons, preferring one
            # with a complete local story (an ingress event)
            have_ingress = {e["trace_id"] for e in events
                            if e["stage"] == "ingress"}
            pick = next((t for t in recents if t in have_ingress),
                        recents[0] if recents else 0)
            if not pick:
                print("trace: no sampled traces recorded yet",
                      file=sys.stderr)
                return 1
            tid = pick
        path = sorted((e for e in events if e["trace_id"] == tid),
                      key=lambda e: e["t"])
        if args.json:
            print(json.dumps(_json_safe({"trace_id": tid,
                                         "events": path})))
            return 0
        if not path:
            print(f"trace: no events for {tid:#x}", file=sys.stderr)
            return 1
        from kubedtn_tpu.telemetry import render_trace

        print(render_trace(
            path, header=f"trace {tid:#018x} ({len(path)} events, "
                         f"{len(set(e['node'] for e in path))} "
                         f"node(s))"))
        return 0
    finally:
        for _addr, client in clients:
            client.close()


def cmd_top(args) -> int:
    """Live ranked per-link table from a daemon's link telemetry plane
    (Local.ObserveLinks): delivery rate, p50/p99 shaping latency, and
    drops BY CAUSE per link — the per-edge time-series view the
    reference daemon never had."""
    import grpc

    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient

    client = DaemonClient(args.daemon)
    try:
        for it in range(args.count):
            if it:
                time.sleep(args.interval)
            try:
                resp = client.ObserveLinks(
                    pb.ObserveLinksRequest(top_n=args.top,
                                           windows=args.windows),
                    timeout=10.0)
            except grpc.RpcError as e:
                print(f"top: daemon {args.daemon} RPC failed: "
                      f"{_rpc_code(e)}", file=sys.stderr)
                return 1
            if not resp.ok:
                print(f"top: {resp.error}", file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(_json_safe({
                    "covered_seconds": resp.covered_seconds,
                    "windows_closed": resp.windows_closed,
                    "truncated": resp.truncated,
                    "links": [{
                        "pod": l.pod, "namespace": l.namespace,
                        "uid": l.uid, "delivered_pps": l.delivered_pps,
                        "bytes_ps": l.bytes_ps, "tx": l.tx,
                        "delivered": l.delivered,
                        "dropped_loss": l.dropped_loss,
                        "dropped_queue": l.dropped_queue,
                        "corrupted": l.corrupted,
                        "queue_depth": l.queue_depth,
                        "p50_us": None if l.p50_us < 0 else l.p50_us,
                        "p99_us": None if l.p99_us < 0 else l.p99_us,
                        "p99_censored": l.p99_censored,
                    } for l in resp.links]})))
                continue
            # censored quantile = clamped at the ladder's open top
            # bucket: the real value is >= it, so render ">5000.00ms"
            # — never an "=" that silently understates the tail
            fmt_us = lambda v, c=False: (  # noqa: E731
                "-" if v < 0 else f"{'>' if c else ''}{v / 1000:.2f}ms")
            print(f"links via {args.daemon} — window "
                  f"{resp.covered_seconds:.1f}s "
                  f"({resp.windows_closed} closed"
                  + (f", {resp.truncated} truncated" if resp.truncated
                     else "") + ")")
            hdr = (f"{'link':<24}{'rate/s':>10}{'p50':>10}{'p99':>10}"
                   f"{'loss':>8}{'queue':>8}{'corrupt':>8}{'qdepth':>8}")
            print(hdr)
            for l in resp.links:
                name = f"{l.pod}/uid{l.uid}"
                print(f"{name:<24}{l.delivered_pps:>10.1f}"
                      f"{fmt_us(l.p50_us):>10}"
                      f"{fmt_us(l.p99_us, l.p99_censored):>10}"
                      f"{l.dropped_loss:>8.0f}{l.dropped_queue:>8.0f}"
                      f"{l.corrupted:>8.0f}{l.queue_depth:>8.0f}")
    finally:
        client.close()
    return 0


def _slo_row_dict(t) -> dict:
    """One wire SloTenant row as a JSON-safe dict (shared by the
    single-daemon and fleet-merge paths)."""
    none_if = lambda v: None if v < 0 else v  # noqa: E731
    return {
        "tenant": t.tenant, "qos": t.qos,
        "spec": {
            "delivery_ratio_floor": t.delivery_ratio_floor,
            "p99_bound_us": t.p99_bound_us,
            "p999_bound_us": t.p999_bound_us,
            # burn-alerting half — omitted (0) fields fall back to
            # the SloSpec defaults in the client-side merge
            **({"fast_windows": t.fast_windows}
               if t.fast_windows else {}),
            **({"slow_windows": t.slow_windows}
               if t.slow_windows else {}),
            **({"warn_burn": t.warn_burn} if t.warn_burn else {}),
            **({"page_burn": t.page_burn} if t.page_burn else {}),
        },
        "window_seconds": t.window_seconds,
        "tx": t.tx, "delivered": t.delivered,
        "delivery_ratio": none_if(t.delivery_ratio),
        "p50_us": none_if(t.p50_us),
        "p99_us": none_if(t.p99_us),
        "p99_censored": t.p99_censored,
        "p999_us": none_if(t.p999_us),
        "tail_method": t.tail_method,
        "fast_burn": t.fast_burn, "slow_burn": t.slow_burn,
        "budget_remaining": t.budget_remaining,
        "throttle_backlog": t.throttle_backlog,
        "attainment_ok": t.attainment_ok,
        "latency_ok": t.latency_ok,
        "severity": t.severity,
        "hist": list(t.hist),
        "frozen": t.frozen, "plane": t.plane,
        "planes": list(t.planes),
        "frozen_planes": list(t.frozen_planes),
        "frozen_tx": t.frozen_tx,
        "frozen_delivered": t.frozen_delivered,
    }


def _render_slo_table(rows: list[dict], title: str) -> None:
    """Fixed-width per-tenant SLO table: attainment vs floor,
    estimated tails (a censored p99 renders `>Xms`), burn rates,
    remaining budget, severity."""
    fmt_ms = lambda v, c=False: (  # noqa: E731
        "-" if v is None else f"{'>' if c else ''}{v / 1000:.2f}ms")
    fmt_pct = lambda v: "-" if v is None else f"{100 * v:.3f}%"  # noqa: E731
    print(title)
    hdr = (f"{'tenant':<14}{'qos':<8}{'attain':>9}{'floor':>9}"
           f"{'p99(est)':>11}{'p99.9(est)':>12}{'fast':>7}{'slow':>7}"
           f"{'budget':>8}  status")
    print(hdr)
    for r in rows:
        status = r["severity"]
        if r.get("frozen"):
            status = "frozen"
        elif not (r["attainment_ok"] and r["latency_ok"]):
            status += " MISS"
        extra = ""
        if r.get("planes") or r.get("frozen_planes"):
            parts = list(r.get("planes") or ())
            parts += [f"{p}(frozen)" for p in
                      r.get("frozen_planes") or ()]
            extra = "  [" + ", ".join(parts) + "]"
        elif r.get("plane"):
            extra = f"  [{r['plane']}]"
        tail = fmt_ms(r["p999_us"],
                      r["tail_method"] == "censored-clamp")
        print(f"{r['tenant']:<14}{r['qos'] or '-':<8}"
              f"{fmt_pct(r['delivery_ratio']):>9}"
              f"{fmt_pct(r['spec']['delivery_ratio_floor']):>9}"
              f"{fmt_ms(r['p99_us'], r['p99_censored']):>11}"
              f"{tail:>12}"
              f"{r['fast_burn']:>7.2f}{r['slow_burn']:>7.2f}"
              f"{100 * r['budget_remaining']:>7.1f}%"
              f"  {status}{extra}")


def _autopilot_action_dict(a) -> dict:
    """One wire AutopilotAction as a JSON-safe dict."""
    return {
        "id": a.id, "t": a.t, "tenant": a.tenant, "kind": a.kind,
        "candidate": a.candidate, "verdict": a.verdict,
        "reason": a.reason, "staged": a.staged,
        "rejected": a.rejected, "rolled_back": a.rolled_back,
        "dry_run": a.dry_run, "candidates": a.candidates,
        "plans": a.plans, "baseline_burn": a.baseline_burn,
        "projected_burn": a.projected_burn, "compile_s": a.compile_s,
        "run_s": a.run_s, "gate_s": a.gate_s, "stage_s": a.stage_s,
        "time_to_green_s": a.time_to_green_s,
    }


def _autopilot_last_actions(addr: str, tenant: str,
                            timeout: float) -> list[dict] | None:
    """Each tenant's last autopilot action from one daemon, or None
    when the daemon has no autopilot attached / the RPC fails — the
    `kdt slo` audit column must never break the burn view."""
    import grpc

    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient

    client = DaemonClient(addr)
    try:
        resp = client.AutopilotStatus(
            pb.AutopilotStatusRequest(tenant=tenant or ""),
            timeout=timeout)
    except grpc.RpcError:
        return None
    finally:
        client.close()
    if not resp.ok:
        return None
    out = []
    for s in resp.states:
        if not s.HasField("last_action"):
            continue
        d = _autopilot_action_dict(s.last_action)
        d["tenant"] = d["tenant"] or s.tenant
        d["state"] = s.state
        out.append(d)
    return out


def _render_autopilot_actions(acts: list[dict],
                              title: str = "") -> None:
    if title:
        print(title)
    print(f"{'tenant':<14}{'id':>5}  {'candidate':<24}"
          f"{'verdict':<12}{'proj.burn':>10}{'ttg':>8}  reason")
    for a in acts:
        ttg = (f"{a['time_to_green_s']:.1f}s"
               if a.get("time_to_green_s") else "-")
        print(f"{a['tenant'] or '(fleet)':<14}{a['id']:>5}  "
              f"{a['candidate'] or '-':<24}{a['verdict'] or '-':<12}"
              f"{a['projected_burn']:>10.3f}{ttg:>8}"
              f"  {a['reason'][:60]}")


def cmd_autopilot(args) -> int:
    """`kdt autopilot status|enable|disable|dry-run|history` — the
    SLO autopilot's operator surface (Local.Autopilot* framework
    extensions): switch the remediation loop, audit the per-tenant
    state machine and every action it took (delta id, gate verdict,
    time-to-green)."""
    import grpc

    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient

    client = DaemonClient(args.daemon)
    try:
        if args.action in ("enable", "disable", "dry-run"):
            wire_action = args.action
            if args.action == "dry-run":
                wire_action = ("dry-run-on" if args.value != "off"
                               else "dry-run-off")
            resp = client.AutopilotCtl(
                pb.AutopilotCtlRequest(action=wire_action),
                timeout=args.timeout)
            if not resp.ok:
                print(f"autopilot: {resp.error}", file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps({"enabled": resp.enabled,
                                  "dry_run": resp.dry_run}))
            else:
                print(f"autopilot enabled={resp.enabled} "
                      f"dry_run={resp.dry_run}")
            return 0
        history = (int(args.limit) if args.action == "history"
                   else 0)
        resp = client.AutopilotStatus(
            pb.AutopilotStatusRequest(tenant=args.tenant or "",
                                      history=history),
            timeout=args.timeout)
    except grpc.RpcError as e:
        print(f"autopilot: daemon RPC failed: {_rpc_code(e)}",
              file=sys.stderr)
        return 1
    finally:
        client.close()
    if not resp.ok:
        print(f"autopilot: {resp.error}", file=sys.stderr)
        return 1
    states = [{
        "tenant": s.tenant, "state": s.state, "pages": s.pages,
        "fails": s.fails, "hold_remaining_s": s.hold_remaining_s,
        **({"last_action": _autopilot_action_dict(s.last_action)}
           if s.HasField("last_action") else {}),
    } for s in resp.states]
    actions = [_autopilot_action_dict(a) for a in resp.actions]
    if args.json:
        print(json.dumps(_json_safe({
            "enabled": resp.enabled, "dry_run": resp.dry_run,
            "running": resp.running, "states": states,
            "actions": actions,
            "pages_seen": resp.pages_seen,
            "searches_run": resp.searches_run,
            "deltas_staged": resp.deltas_staged,
            "deltas_rejected": resp.deltas_rejected,
            "deltas_rolled_back": resp.deltas_rolled_back,
            "escalations": resp.escalations})))
        return 0
    if args.action == "history":
        if not actions:
            print("autopilot: no actions recorded yet")
            return 0
        _render_autopilot_actions(
            actions, title=f"autopilot history via {args.daemon} "
                           f"({len(actions)} action(s))")
        return 0
    print(f"autopilot via {args.daemon} — "
          f"enabled={resp.enabled} dry_run={resp.dry_run} "
          f"running={resp.running}")
    print(f"pages={resp.pages_seen} searches={resp.searches_run} "
          f"staged={resp.deltas_staged} "
          f"rejected={resp.deltas_rejected} "
          f"rolled_back={resp.deltas_rolled_back} "
          f"escalations={resp.escalations}")
    if not states:
        print("no tenants observed yet")
        return 0
    print(f"{'tenant':<14}{'state':<10}{'pages':>6}{'fails':>6}"
          f"{'hold':>8}  last action")
    for s in states:
        hold = (f"{s['hold_remaining_s']:.1f}s"
                if s["hold_remaining_s"] else "-")
        la = s.get("last_action")
        last = (f"#{la['id']} {la['candidate'] or la['kind']} "
                f"-> {la['verdict']}" if la else "-")
        print(f"{s['tenant']:<14}{s['state']:<10}{s['pages']:>6}"
              f"{s['fails']:>6}{hold:>8}  {last}")
    return 0


def cmd_slo(args) -> int:
    """`kdt slo [--tenant T] [--fleet]` — the SLO observability plane's
    operator surface (Local.ObserveSLO): per-tenant attainment vs
    objective, censored-tail-estimated p99/p99.9, multi-window burn
    rates and remaining error budget. With --fleet and SEVERAL
    --daemon addresses the answers are merged CLIENT-side on the
    shared bucket ladder (exact), stitching a migrated tenant's frozen
    pre-move slice with its live post-move window — the continuous
    fleet view; a single daemon with a fleet supervisor serves its
    server-side merge instead."""
    import grpc

    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient

    daemons = args.daemon or ["127.0.0.1:51111"]
    if len(daemons) > 1 and not args.fleet:
        # without --fleet only one daemon's answer could be shown —
        # silently dropping the others would read as "tenant missing"
        print("slo: several --daemon addresses need --fleet (the "
              "cross-plane merge)", file=sys.stderr)
        return 1
    fleet_many = args.fleet and len(daemons) > 1
    fleet_local = args.fleet and len(daemons) == 1
    responses = []
    for addr in daemons:
        client = DaemonClient(addr)
        try:
            resp = client.ObserveSLO(
                pb.ObserveSLORequest(tenant=args.tenant or "",
                                     fleet=fleet_local),
                timeout=args.timeout)
        except grpc.RpcError as e:
            # a multi-daemon fleet merge TOLERATES a dead plane (the
            # supervisor's server-side merge does the same): the view
            # must stay available during exactly the outage the
            # operator is looking into — warn and merge the rest
            print(f"slo: daemon {addr} RPC failed: {_rpc_code(e)}"
                  + (" (merging the remaining planes)" if fleet_many
                     else ""), file=sys.stderr)
            if not fleet_many:
                return 1
            continue
        finally:
            client.close()
        if not resp.ok:
            print(f"slo: {addr}: {resp.error}", file=sys.stderr)
            if not fleet_many:
                return 1
            continue
        responses.append((addr, resp))
    if not responses:
        print("slo: no daemon answered", file=sys.stderr)
        return 1

    if args.fleet and len(daemons) > 1:
        # client-side merge over every daemon's answer: live rows per
        # plane + frozen journal slices, the same slo.fleet arithmetic
        # the supervisor runs server-side
        from kubedtn_tpu.slo.fleet import fleet_slo as _merge

        per_plane: dict = {}
        frozen = []
        for addr, resp in responses:
            plane = resp.plane or addr
            for t in resp.tenants:
                d = _slo_row_dict(t)
                if d["frozen"]:
                    frozen.append((d["plane"] or plane, d["tenant"],
                                   {"tx": d["tx"],
                                    "delivered": d["delivered"],
                                    "window_seconds":
                                        d["window_seconds"],
                                    "hist": d["hist"]}, d["qos"]))
                else:
                    per_plane.setdefault(plane, []).append(d)
        merged = _merge(per_plane, frozen, tenant=args.tenant or "")
        rows = [merged[k] for k in sorted(merged)]
        title = (f"fleet SLO via {', '.join(daemons)} "
                 f"({len(rows)} tenant(s))")
    else:
        _addr, resp = responses[0]
        rows = [_slo_row_dict(t) for t in resp.tenants]
        if fleet_local and not resp.fleet:
            # the daemon has no fleet supervisor: it answered with its
            # own plane only — say so instead of mislabeling the view
            print("slo: daemon has no fleet supervisor — showing its "
                  "single-plane view", file=sys.stderr)
        where = ("fleet view via" if fleet_local and resp.fleet
                 else "SLO via")
        title = (f"{where} {daemons[0]} — {resp.windows_closed} "
                 f"windows closed, {resp.evaluations} evaluations")
    if args.tenant:
        rows = [r for r in rows if r["tenant"] == args.tenant]
    # the autopilot's audit trail rides the same command the operator
    # uses to see the burn: each tenant's last action (single-daemon
    # views only — the fleet merge has no one autopilot to ask)
    autopilot = None
    if len(daemons) == 1:
        autopilot = _autopilot_last_actions(
            daemons[0], args.tenant or "", args.timeout)
    if args.json:
        out = {"tenants": rows}
        if autopilot is not None:
            out["autopilot"] = autopilot
        print(json.dumps(_json_safe(out)))
        return 0
    if not rows:
        print("slo: no tenants evaluated yet (no tenancy registry, "
              "or no telemetry windows closed)", file=sys.stderr)
        return 1
    _render_slo_table(rows, title)
    if autopilot:
        _render_autopilot_actions(
            autopilot, title="autopilot last actions:")
    return 0


def _pauses_payload(resp) -> dict:
    """One wire ObservePausesResponse as a JSON-safe dict."""
    return {
        "enabled": resp.enabled,
        "uptime_s": resp.uptime_s,
        "total_pause_s": resp.total_pause_s,
        "dropped_events": resp.dropped_events,
        "tick_edges_s": list(resp.tick_edges_s),
        "causes": [{
            "cause": c.cause, "count": c.count, "seconds": c.seconds,
            "max_s": c.max_s, "last_s": c.last_s,
            "last_t_s": c.last_t_s, "rows": c.rows, "bytes": c.bytes,
            "tick_buckets": list(c.tick_buckets),
            "tick_count": c.tick_count, "tick_sum_s": c.tick_sum_s,
        } for c in resp.causes],
        "events": [{
            "cause": e.cause, "dur_s": e.dur_s, "t_s": e.t_s,
            "detail": e.detail,
        } for e in resp.events],
    }


def _render_pauses(resp, addr: str) -> None:
    share = (100.0 * resp.total_pause_s / resp.uptime_s
             if resp.uptime_s > 0 else 0.0)
    print(f"pauses via {addr} — uptime {resp.uptime_s:.1f}s, "
          f"total pause {resp.total_pause_s:.3f}s "
          f"({share:.2f}% of wall), "
          f"ledger {'on' if resp.enabled else 'OFF'}"
          + (f", {resp.dropped_events} events dropped"
             if resp.dropped_events else ""))
    # ranked worst cause first: cumulative seconds is the availability
    # cost, which is what the savail budget ceilings
    ranked = sorted(resp.causes, key=lambda c: -c.seconds)
    print(f"{'cause':<20}{'count':>7}{'seconds':>10}{'max':>9}"
          f"{'last':>9}{'rows':>10}{'bytes':>12}{'ticks':>7}")
    for c in ranked:
        if c.cause == "none" and not c.count:
            # clean ticks carry only the histogram row below
            print(f"{'(clean ticks)':<20}{'-':>7}{'-':>10}{'-':>9}"
                  f"{'-':>9}{'-':>10}{'-':>12}{c.tick_count:>7}")
            continue
        print(f"{c.cause:<20}{c.count:>7}{c.seconds:>10.3f}"
              f"{c.max_s:>9.3f}{c.last_s:>9.3f}{c.rows:>10}"
              f"{c.bytes:>12}{c.tick_count:>7}")
    for e in resp.events:
        det = f"  {e.detail}" if e.detail else ""
        print(f"  [{e.t_s:>9.3f}s] {e.cause:<18} "
              f"{e.dur_s * 1000:.2f}ms{det}")


def cmd_pauses(args) -> int:
    """`kdt pauses [--json] [--watch] [--events N]` — the pause/stall
    observability plane's operator surface (Local.ObservePauses): a
    ranked worst-cause table of every tick-lock barrier the plane paid
    (checkpoint / compact / staged update / migration / flush / shm
    stall / jit compile / GC), each with count, cumulative and worst
    duration, and rows/bytes touched — the answer to "why did tick
    latency spike at 14:02"."""
    import grpc

    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient

    client = DaemonClient(args.daemon)
    try:
        while True:
            try:
                resp = client.ObservePauses(
                    pb.ObservePausesRequest(cause=args.cause or "",
                                            events=args.events),
                    timeout=args.timeout)
            except grpc.RpcError as e:
                print(f"pauses: daemon {args.daemon} RPC failed: "
                      f"{_rpc_code(e)}", file=sys.stderr)
                return 1
            if not resp.ok:
                print(f"pauses: {resp.error}", file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(_json_safe(_pauses_payload(resp))),
                      flush=True)
            else:
                _render_pauses(resp, args.daemon)
            if not args.watch:
                return 0
            try:
                time.sleep(args.interval)
            except KeyboardInterrupt:
                return 0
            if not args.json:
                print()
    finally:
        client.close()


def cmd_tenant(args) -> int:
    """`kdt tenant create|list|quota|stats` — the multi-tenant plane's
    operator surface (Local.Tenant* framework extensions): register a
    tenant with QoS class / admission budgets / an optional reserved
    edge block, inspect per-tenant quotas and live stats."""
    import grpc

    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient

    client = DaemonClient(args.daemon)

    def info_dict(t) -> dict:
        return {
            "name": t.name, "qos": t.qos,
            "namespaces": list(t.namespaces),
            "frame_budget_per_s": t.frame_budget_per_s,
            "byte_budget_per_s": t.byte_budget_per_s,
            "block": ([t.block_lo, t.block_hi]
                      if t.block_lo >= 0 else None),
            "links": t.links,
        }

    try:
        if args.action in ("create", "quota"):
            spec = pb.TenantSpec(
                name=args.name, qos=args.qos or "",
                frame_budget_per_s=args.frames_per_s,
                byte_budget_per_s=args.bytes_per_s,
                block_edges=args.block_edges,
                namespaces=args.namespace or [])
            rpc = (client.TenantCreate if args.action == "create"
                   else client.TenantQuota)
            resp = rpc(spec, timeout=args.timeout)
            if not resp.ok:
                print(f"tenant {args.action}: {resp.error}",
                      file=sys.stderr)
                return 1
            print(json.dumps(_json_safe(info_dict(resp.tenant))))
            return 0
        if args.action == "list":
            resp = client.TenantList(pb.TenantQuery(name=args.name
                                                    or ""),
                                     timeout=args.timeout)
            if not resp.ok:
                print(f"tenant list: {resp.error}", file=sys.stderr)
                return 1
            print(json.dumps(_json_safe(
                {"tenants": [info_dict(t) for t in resp.tenants]})))
            return 0
        if args.action == "delete":
            if not args.name:
                print("tenant delete needs a tenant name",
                      file=sys.stderr)
                return 1
            resp = client.TenantDelete(pb.TenantQuery(name=args.name),
                                       timeout=args.timeout)
            if not resp.ok:
                print(f"tenant delete: {resp.error}", file=sys.stderr)
                return 1
            print(json.dumps({"deleted": args.name}))
            return 0
        # stats
        if not args.name:
            print("tenant stats needs a tenant name", file=sys.stderr)
            return 1
        resp = client.TenantStats(pb.TenantQuery(name=args.name),
                                  timeout=args.timeout)
        if not resp.ok:
            print(f"tenant stats: {resp.error}", file=sys.stderr)
            return 1
        none_if = lambda v: None if v < 0 else v  # noqa: E731
        out = {
            **info_dict(resp.tenant),
            "admitted_frames": resp.admitted_frames,
            "admitted_bytes": resp.admitted_bytes,
            "throttle_events": resp.throttle_events,
            "throttled_frame_ticks": resp.throttled_frame_ticks,
            "tx_packets": resp.tx_packets,
            "delivered_packets": resp.delivered_packets,
            "delivered_bytes": resp.delivered_bytes,
            "dropped_loss": resp.dropped_loss,
            "dropped_queue": resp.dropped_queue,
            "dropped_ring": resp.dropped_ring,
            "window_seconds": resp.window_seconds,
            "delivered_pps": resp.delivered_pps,
            "bytes_ps": resp.bytes_ps,
            "p50_us": none_if(resp.p50_us),
            "p99_us": none_if(resp.p99_us),
        }
        print(json.dumps(_json_safe(out)))
        return 0
    except grpc.RpcError as e:
        print(f"tenant: daemon {args.daemon} RPC failed: "
              f"{_rpc_code(e)}", file=sys.stderr)
        return 1
    finally:
        client.close()


def cmd_migrate(args) -> int:
    """`kdt migrate` — live tenant migration between federation planes
    (Local.MigrateTenant), plus `--status` over the journaled records
    (Local.MigrationStatus). Zero-loss: the state machine throttles,
    forks, restores, cuts over make-before-break and reconciles
    byte-exact delivery accounting across the move."""
    import grpc

    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient

    client = DaemonClient(args.daemon)

    def info_dict(m) -> dict:
        return {
            "migration_id": m.migration_id, "tenant": m.tenant,
            "src": m.src, "dst": m.dst, "state": m.state,
            "steps_done": list(m.steps_done),
            "resumed": m.resumed, "rollbacks": m.rollbacks,
            "transferred_frames": m.transferred_frames,
            "delivered_src_frames": m.delivered_src_frames,
            "delivered_src_bytes": m.delivered_src_bytes,
        }

    try:
        if args.status:
            resp = client.MigrationStatus(pb.MigrationStatusRequest(
                migration_id=args.migration_id, tenant=args.tenant),
                timeout=args.timeout)
            if not resp.ok:
                print(f"migrate status: {resp.error}", file=sys.stderr)
                return 1
            print(json.dumps(_json_safe(
                {"migrations": [info_dict(m)
                                for m in resp.migrations]})))
            return 0
        if args.resume:
            if not args.migration_id:
                print("migrate --resume needs --id", file=sys.stderr)
                return 1
        elif not (args.tenant and args.dst):
            print("migrate needs a tenant and --dst", file=sys.stderr)
            return 1
        resp = client.MigrateTenant(pb.MigrateRequest(
            tenant=args.tenant, src=args.src, dst=args.dst,
            migration_id=args.migration_id, resume=args.resume,
            reconcile_timeout_s=max(1.0, args.timeout - 5.0)),
            timeout=args.timeout)
        if not resp.ok:
            print(f"migrate: {resp.error}", file=sys.stderr)
            return 1
        print(json.dumps(_json_safe(info_dict(resp.migration))))
        return 0
    except grpc.RpcError as e:
        print(f"migrate: daemon {args.daemon} RPC failed: "
              f"{_rpc_code(e)}", file=sys.stderr)
        return 1
    finally:
        client.close()


def cmd_fleet(args) -> int:
    """`kdt fleet status|upgrade` — the fleet supervisor's operator
    surface (Local.FleetStatus / Local.FleetUpgrade): per-plane health
    + suspicion state + the placement ledger, and the rolling-upgrade
    driver (cordon → drain via live migration → restart →
    health-verify → refill, zero frame loss for every live-migrated
    tenant)."""
    import grpc

    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient

    client = DaemonClient(args.daemon)
    try:
        if args.action == "status":
            resp = client.FleetStatus(pb.FleetStatusRequest(),
                                      timeout=args.timeout)
            if not resp.ok:
                print(f"fleet status: {resp.error}", file=sys.stderr)
                return 1
            none_if = lambda v: None if v < 0 else v  # noqa: E731
            out = {
                "planes": [{
                    "name": p.name, "state": p.state,
                    "consecutive_failures": p.consecutive_failures,
                    "last_error": p.last_error or None,
                    "tenants_placed": p.tenants_placed,
                    "health": {
                        "running": p.health.running,
                        "serving": p.health.serving,
                        "heartbeat_age_s": none_if(
                            p.health.heartbeat_age_s),
                        "degrade_level": p.health.degrade_level,
                        "tick_errors": p.health.tick_errors,
                        "backlog": p.health.backlog,
                        "tenants": p.health.tenants,
                        "headroom_rows": p.health.headroom_rows,
                    } if p.health.ok else None,
                } for p in resp.planes],
                "placements": {e.tenant: e.plane
                               for e in resp.placements},
                "sweeps": resp.sweeps,
                "evacuations": resp.evacuations,
            }
            print(json.dumps(_json_safe(out)))
            return 0
        # upgrade
        resp = client.FleetUpgrade(pb.FleetUpgradeRequest(
            planes=args.plane or [],
            verify_probes=args.verify_probes),
            timeout=args.timeout)
        if not resp.ok and not resp.reports:
            print(f"fleet upgrade: {resp.error}", file=sys.stderr)
            return 1
        out = {
            "reports": [{
                "plane": r.plane,
                "drained_tenants": list(r.drained_tenants),
                "refilled_tenants": list(r.refilled_tenants),
                "restarted": bool(r.restarted),
                "healthy": bool(r.healthy),
                "error": r.error or None,
            } for r in resp.reports],
            "migrations": resp.migrations,
        }
        print(json.dumps(_json_safe(out)))
        return 0 if resp.ok else 1
    except grpc.RpcError as e:
        print(f"fleet: daemon {args.daemon} RPC failed: "
              f"{_rpc_code(e)}", file=sys.stderr)
        return 1
    finally:
        client.close()


def cmd_scenario(args) -> int:
    from kubedtn_tpu.scenarios import LADDER

    if args.name == "all":
        if args.param:
            print("-p overrides are per-scenario; not supported with "
                  "'all'", file=sys.stderr)
            return 1
        for name, fn in LADDER.items():
            print(json.dumps(_json_safe(fn())))
        return 0
    if args.name not in LADDER:
        print(f"unknown scenario {args.name}; "
              f"choices: {', '.join(LADDER)} or all", file=sys.stderr)
        return 1
    fn = LADDER[args.name]
    import inspect

    try:
        kwargs = _coerce_params(fn, args.param)
        out = fn(**kwargs)
    except (TypeError, ValueError, AssertionError) as e:
        print(f"scenario {args.name}: {e}\nsignature: "
              f"{args.name}{inspect.signature(fn)}", file=sys.stderr)
        return 1
    print(json.dumps(_json_safe(out)))
    return 0


def _env_port(var: str, default: int) -> int:
    """Port from an env var that may be a bare port, ':port', or
    'host:port' (the reference's HTTP_ADDR forms, daemon/main.go:27-40)."""
    raw = os.environ.get(var, "")
    if not raw:
        return default
    try:
        return int(raw.rsplit(":", 1)[-1])
    except ValueError:
        raise SystemExit(f"{var}={raw!r}: not a port")


def cmd_daemon(args) -> int:
    from kubedtn_tpu.metrics.metrics import MetricsServer, make_registry
    from kubedtn_tpu.topology import SimEngine, TopologyStore
    from kubedtn_tpu.utils.logging import fields, get_logger, setup
    from kubedtn_tpu.wire.server import Daemon, make_server

    from kubedtn_tpu.runtime import WireDataPlane

    # structured logs for the whole daemon (level: KUBEDTN_LOG_LEVEL),
    # the zap/logrus setup of the reference (main.go:61-78)
    setup()
    log = get_logger("daemon")

    # persistent compilation cache: a restarted daemon skips the one-time
    # batch-kernel compiles (seconds each on first traffic) that would
    # otherwise show up as multi-second delivery latency right after boot
    try:
        import jax as _jax

        cache_dir = os.environ.get(
            "KUBEDTN_JAX_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "kubedtn-jax"))
        _jax.config.update("jax_compilation_cache_dir", cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                           1.0)
    except Exception as e:  # an optimization, never fatal
        log.info("compilation cache unavailable: %r", e)

    if args.port is None:
        args.port = _env_port("GRPC_PORT", 51111)
    if args.metrics_port is None:
        args.metrics_port = _env_port("HTTP_ADDR", 51112)

    ckpt_dir = getattr(args, "checkpoint_dir", None)
    store = engine = None
    if ckpt_dir:
        from kubedtn_tpu import checkpoint

        # warm restart: topologies, realized links, and (below) the
        # delay line's in-flight frames all come back. load() resolves
        # the .prev generation a mid-save crash may have left; a
        # CORRUPT checkpoint cold-starts with a warning (clients /
        # the k8s bridge re-apply the CRs — the reconstruction path)
        # instead of crash-looping the daemon.
        try:
            store, engine = checkpoint.load(ckpt_dir)
            engine.node_ip = args.node_ip
            log.info("restored from checkpoint %s", fields(
                path=ckpt_dir, topologies=len(store.list()),
                links=engine.num_active))
        except checkpoint.CheckpointMissingError:
            pass  # no checkpoint yet: first start
        except checkpoint.CheckpointError:
            # corrupt, unsupported version, ... — cold-start LOUDLY so a
            # discarded warm state is never invisible (the next graceful
            # save will replace the unusable directory)
            log.exception("checkpoint unusable; cold-starting %s",
                          fields(path=ckpt_dir))
            store = engine = None
    if store is None:
        store = TopologyStore()
        engine = SimEngine(store, node_ip=args.node_ip)
    daemon = Daemon(engine)
    if getattr(args, "capture", None):
        from kubedtn_tpu.utils.pcap import CaptureManager

        daemon.capture = CaptureManager()
        daemon.capture.open(args.capture)
        log.info("capture on %s", fields(path=args.capture))
    dataplane = WireDataPlane(daemon)
    from kubedtn_tpu.tenancy import TenantRegistry

    # multi-tenant serving plane: namespace→tenant mapping, admission
    # buckets, QoS drain weights, Local.Tenant* RPCs (empty registry =
    # zero enforcement until `kdt tenant create` tightens quotas).
    # A checkpointed registry restores quotas / QoS / block
    # entitlements / namespace bindings so a restart never silently
    # resets tenants to unenforced.
    tenancy = None
    if ckpt_dir:
        from kubedtn_tpu import checkpoint as _ckpt

        try:
            tenancy = _ckpt.load_tenancy(ckpt_dir, engine)
        except _ckpt.CheckpointError:
            log.exception("tenancy restore failed; starting with an "
                          "empty registry %s", fields(path=ckpt_dir))
        else:
            if tenancy is not None:
                log.info("tenant registry restored %s", fields(
                    tenants=len(tenancy.list())))
    if tenancy is None:
        tenancy = TenantRegistry(engine)
    dataplane.attach_tenancy(tenancy)
    # federation: this plane registers with a controller so
    # Local.MigrateTenant / MigrationStatus (and `kdt migrate`) can
    # move tenants between planes registered in this process
    from kubedtn_tpu.federation import (FederationController,
                                        PlaneHandle)
    from kubedtn_tpu.federation import stats_for as migration_stats_for

    # SIBLING of the checkpoint dir, never inside it: checkpoint.save
    # replaces the directory wholesale (atomic swap), so a journal
    # nested in it would be deleted by every save — or make the save
    # refuse outright on a manifest-less mixed directory
    journal_root = (getattr(args, "migration_journal", None)
                    or (ckpt_dir.rstrip("/") + "-migrations"
                        if ckpt_dir else
                        os.path.join(os.path.expanduser("~"), ".cache",
                                     "kubedtn-migrations")))
    migration_stats = migration_stats_for(daemon)
    federation = FederationController(journal_root,
                                      stats=migration_stats)
    federation.register(PlaneHandle(name=args.node_ip, daemon=daemon,
                                    plane=dataplane, registry=tenancy,
                                    checkpoint_dir=ckpt_dir))
    # fleet supervision: plane health watching (Local.Health /
    # FleetStatus), the journaled placement ledger, and — on boot —
    # auto-resume of any migration journal a crash left `running`
    # (an interrupted migration no longer waits for an operator
    # `kdt migrate --resume`)
    from kubedtn_tpu.federation.supervisor import FleetSupervisor

    fleet_root = (ckpt_dir.rstrip("/") + "-fleet" if ckpt_dir else
                  os.path.join(os.path.expanduser("~"), ".cache",
                               "kubedtn-fleet"))
    fleet = FleetSupervisor(federation, fleet_root).attach()
    fleet.start(interval_s=2.0)
    slo_eval = autopilot = None
    if not getattr(args, "no_telemetry", False):
        # link telemetry plane: per-edge window ring + sampled flight
        # recorder, riding the fused tick (no extra device dispatch)
        dataplane.enable_telemetry(
            window_s=getattr(args, "telemetry_window", 1.0),
            sample_period=getattr(args, "telemetry_sample", 256),
            node=args.node_ip)
        log.info("link telemetry on %s", fields(
            window_s=getattr(args, "telemetry_window", 1.0),
            sample_period=getattr(args, "telemetry_sample", 256)))
        # SLO plane: per-tenant objectives evaluated at every telemetry
        # window rollover on a sidecar thread (zero tick-path work; the
        # Local.ObserveSLO / `kdt slo` / kubedtn_slo_* surface)
        from kubedtn_tpu.slo import SloEvaluator

        slo_eval = SloEvaluator(tenancy, dataplane).attach(daemon)
        slo_eval.start()
        log.info("slo evaluation on %s", fields(
            window_s=getattr(args, "telemetry_window", 1.0)))
        # SLO autopilot: the closed loop from a paging burn verdict to
        # a twin-gated staged remediation (Local.Autopilot* / `kdt
        # autopilot` / kubedtn_autopilot_*). The sidecar always runs;
        # remediation stays OFF until `kdt autopilot enable` (or
        # --autopilot) flips it — observing is free, acting is opt-in.
        from kubedtn_tpu.autopilot import Autopilot

        autopilot = Autopilot(tenancy, dataplane, slo_eval,
                              fleet=fleet).attach(daemon)
        if getattr(args, "autopilot", False):
            autopilot.enable()
        if getattr(args, "autopilot_dry_run", False):
            autopilot.set_dry_run(True)
        autopilot.start(poll_s=getattr(args, "autopilot_poll", 1.0))
        log.info("slo autopilot on %s", fields(
            enabled=autopilot.enabled, dry_run=autopilot.dry_run))
    shard = getattr(args, "shard_mesh", 0)
    if shard:
        # edge-sharded live plane: SoA columns block-shard across the
        # device mesh, cross-shard row state rides the mailbox ring
        # (ARCHITECTURE.md "Sharded live plane"); -1 = largest
        # power-of-two count of local devices, 0 = off (guard above)
        mesh = dataplane.enable_sharding(
            n_devices=None if shard < 0 else shard)
        log.info("sharded live plane %s", fields(
            mesh_devices=int(mesh.devices.size)))
    shm_ingest = None
    shm_dir = getattr(args, "shm_dir", None)
    if shm_dir:
        # shared-memory ingest plane: producer rings in this directory
        # feed drain_ingress directly (admission at the ring head);
        # gRPC stays up as the compatibility fallback + control surface
        from kubedtn_tpu.shm import ShmIngest

        os.makedirs(shm_dir, exist_ok=True)
        shm_ingest = ShmIngest(shm_dir)
        dataplane.attach_shm(shm_ingest)
        log.info("shm ingest on %s", fields(dir=shm_dir))
    trace_out = getattr(args, "trace_out", None)
    trace_stop = None
    if trace_out:
        # Crash-safe trace capture: rotate (append + truncate buffer)
        # on a sidecar so a SIGKILL'd daemon loses at most one rotation
        # interval of spans, not the whole buffer. Truncate any stale
        # file first — rotate_out appends, and a previous run's dump
        # would otherwise corrupt the array.
        import threading as _threading

        from kubedtn_tpu.utils.tracing import default_tracer

        open(trace_out, "w").close()
        trace_stop = _threading.Event()

        def _trace_rotator() -> None:
            tr = default_tracer()
            last = time.monotonic()
            while not trace_stop.wait(2.0):
                now = time.monotonic()
                if tr.pending() >= 20_000 or (
                        now - last >= 30.0 and tr.pending() > 0):
                    try:
                        n = tr.rotate_out(trace_out)
                        if n:
                            last = now
                    except Exception:
                        log.exception("trace rotation failed %s",
                                      fields(path=trace_out))
                        last = now  # don't hot-loop a broken path

        _threading.Thread(target=_trace_rotator, daemon=True,
                          name="trace-rotator").start()
    jax_profile = getattr(args, "jax_profile", None)
    if jax_profile:
        # opt-in XLA device profiling for the daemon's whole lifetime
        # (today only stage_shares was consumed; this is the device
        # half of the host spans)
        try:
            import jax as _jax

            _jax.profiler.start_trace(jax_profile)
            log.info("jax profiler capturing %s",
                     fields(dir=jax_profile))
        except Exception:
            log.exception("jax profiler start failed; continuing")
            jax_profile = None
    if ckpt_dir:
        try:
            # the wire registry and cumulative per-edge counters come
            # back with the rows: clients need not re-register wires,
            # and the per-interface delivery series keep counting from
            # where the previous incarnation stopped
            n_wires = checkpoint.load_wires(ckpt_dir, daemon)
            n_ingress = checkpoint.load_ingress(ckpt_dir, daemon)
            if checkpoint.restore_plane_counters(ckpt_dir, dataplane):
                log.info("plane counters restored %s",
                         fields(wires=n_wires,
                                ingress_frames=n_ingress))
        except checkpoint.CheckpointError:
            log.exception("wire/counter restore failed; continuing "
                          "without %s", fields(path=ckpt_dir))
        try:
            n_pending = checkpoint.load_pending(ckpt_dir, dataplane)
        except checkpoint.CheckpointError:
            # the file stays on disk: a transient read error (or a
            # fixed binary) can still restore these frames on the next
            # start — consuming here would destroy them unrestored
            log.exception("pending-frame restore failed; continuing "
                          "without %s", fields(path=ckpt_dir))
        else:
            if n_pending:
                log.info("restored in-flight frames %s",
                         fields(n=n_pending))
            # consume the pending file once RESTORED (from the SAME
            # generation load_pending resolved): a crash before the
            # next graceful checkpoint must NOT re-deliver these
            # frames again
            checkpoint.consume_pending(ckpt_dir)
    from kubedtn_tpu.twin.query import stats_for
    from kubedtn_tpu.updates.stager import stats_for as update_stats_for

    registry, hist = make_registry(engine,
                                   sim_counters_fn=dataplane.counters_fn,
                                   dataplane=dataplane,
                                   whatif_stats=stats_for(daemon),
                                   update_stats=update_stats_for(daemon),
                                   tenancy=tenancy,
                                   migration_stats=migration_stats,
                                   fleet=fleet, slo=slo_eval,
                                   shm=shm_ingest, autopilot=autopilot)
    engine.stats.observer = hist
    daemon.hist = hist
    server, port = make_server(daemon, port=args.port)
    metrics = MetricsServer(registry, port=args.metrics_port)
    metrics.start()
    server.start()
    dataplane.start()
    autosaver = None
    interval = getattr(args, "checkpoint_interval", 0.0) or 0.0
    if ckpt_dir and interval > 0:
        # periodic crash-consistent autosave: capture at one flush
        # barrier off the tick path, write with the atomic staged swap.
        # This bounds the failover RPO — without it a SIGKILL loses
        # everything since start (state otherwise saves only on the
        # graceful SIGTERM path below).
        autosaver = checkpoint.Autosaver(ckpt_dir, store, engine,
                                         dataplane,
                                         interval_s=interval)
        autosaver.start()
        log.info("autosave on %s", fields(path=ckpt_dir,
                                          interval_s=interval))
    import signal as _signal

    def _on_term(*_):
        # a second SIGTERM during cleanup must not abort it
        _signal.signal(_signal.SIGTERM, _signal.SIG_IGN)
        raise KeyboardInterrupt

    try:
        # a DaemonSet pod stop is SIGTERM, not Ctrl-C: route it through
        # the same graceful-shutdown path (checkpoint, capture close,
        # plane stop). Registered inside the try and BEFORE the ready
        # line, so a supervisor reacting to that line can never land a
        # TERM that escapes the cleanup below.
        _signal.signal(_signal.SIGTERM, _on_term)
        log.info("daemon up %s", fields(grpc_port=port,
                                        metrics_port=metrics.port,
                                        node_ip=args.node_ip))
        print(f"kubedtn-tpu daemon: gRPC on :{port}, "
              f"metrics on :{metrics.port}/metrics", flush=True)
        server.wait_for_termination()
    except KeyboardInterrupt:
        fleet.stop()
        if autopilot is not None:
            autopilot.stop()
        if slo_eval is not None:
            slo_eval.stop()
        if autosaver is not None:
            # a mid-shutdown autosave would race the final save below
            autosaver.stop()
        server.stop(0)
        dataplane.stop()
        if shm_ingest is not None:
            shm_ingest.close()
        if ckpt_dir:
            try:
                checkpoint.save(ckpt_dir, store, engine,
                                dataplane=dataplane)
                log.info("checkpoint written %s", fields(path=ckpt_dir))
            except Exception:
                # a full disk must not abort the remaining cleanup
                log.exception("checkpoint save failed %s",
                              fields(path=ckpt_dir))
        if daemon.capture is not None:
            daemon.capture.close_all()
        if jax_profile:
            try:
                import jax as _jax

                _jax.profiler.stop_trace()
            except Exception:
                log.exception("jax profiler stop failed")
        if trace_out:
            # catapult/Perfetto JSON of the daemon's structured spans
            # (reconcile / checkpoint / barrier pauses) — the sidecar
            # already rotated periodically; this final rotation drains
            # whatever landed since, in the same array format
            from kubedtn_tpu.utils.tracing import default_tracer

            if trace_stop is not None:
                trace_stop.set()
            try:
                n = default_tracer().rotate_out(trace_out)
                log.info("trace written %s", fields(
                    path=trace_out, spans=n))
            except Exception:
                log.exception("trace export failed %s",
                              fields(path=trace_out))
        metrics.stop()
    return 0


def cmd_manager(args) -> int:
    """Run the controller manager standalone — the reference's controller
    binary (reference main.go:80-126): continuous reconcile with worker
    pool, healthz/readyz probes, optional leader election."""
    from kubedtn_tpu.topology import SimEngine, TopologyStore
    from kubedtn_tpu.topology.manager import ControllerManager
    from kubedtn_tpu.utils.logging import fields, get_logger, setup

    setup()
    log = get_logger("manager")
    store = TopologyStore()
    engine = SimEngine(store, node_ip=args.node_ip)
    mgr = ControllerManager(store, engine, identity=args.identity,
                            workers=args.workers,
                            leader_election=args.leader_elect,
                            probe_port=args.probe_port,
                            metrics_port=args.metrics_port)
    mgr.start()
    log.info("manager up %s", fields(identity=args.identity,
                                     workers=args.workers,
                                     probe_port=mgr.probe_port,
                                     metrics_port=mgr.metrics_port,
                                     leader_election=args.leader_elect))
    print(f"kubedtn-tpu manager: probes on :{mgr.probe_port} "
          f"(healthz/readyz), metrics on :{mgr.metrics_port}/metrics",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        mgr.stop()
    return 0


def cmd_physical_join(args) -> int:
    """Join a physical host to the twin (reference cmd/main.go:26-101):
    read {link, remote_ip} YAML and ask the daemon to realize the
    host-side end via Remote.Update."""
    import yaml

    from kubedtn_tpu.api.types import Link
    from kubedtn_tpu.topology.engine import vni_from_uid
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient

    with open(args.file) as f:
        q = yaml.safe_load(f)
    link = Link.from_dict(q["link"])
    if not link.is_physical():
        print("peer_pod must be physical/<ip>", file=sys.stderr)
        return 1
    client = DaemonClient(args.daemon)
    resp = client.Update(pb.RemotePod(
        net_ns="",
        intf_name=link.local_intf,
        intf_ip=link.local_ip,
        peer_vtep=q["remote_ip"],
        vni=vni_from_uid(link.uid),
        kube_ns="default",
        name=f"physical/{link.physical_peer_ip()}",
        properties=pb.props_to_proto(link.properties),
    ))
    print(json.dumps({"joined": bool(resp.response)}))
    client.close()
    return 0 if resp.response else 1


def _coerce_params(fn, params):
    """-p k=v strings → kwargs coerced by fn's signature: annotation
    first (tuple dims as 4x4x2, str passthrough), then the default
    value's type, then int/float/str guessing. One convention shared by
    `gen` and `scenario`. Raises ValueError on unknown names."""
    import inspect

    sig = inspect.signature(fn)
    kwargs = {}
    for kv in params or []:
        k, _, v = kv.partition("=")
        if k not in sig.parameters:
            raise ValueError(
                f"no parameter {k!r}; choices: {', '.join(sig.parameters)}")
        ann = str(sig.parameters[k].annotation)
        default = sig.parameters[k].default
        if "tuple" in ann or "list" in ann:  # torus dims as 4x4x2
            kwargs[k] = tuple(int(x) for x in v.split("x"))
        elif "bool" in ann or isinstance(default, bool):
            kwargs[k] = v.lower() in ("1", "true", "yes")
        elif "str" in ann:
            kwargs[k] = v
        elif "float" in ann:
            kwargs[k] = float(v)
        elif "int" in ann:
            kwargs[k] = int(v)
        elif isinstance(default, int):
            kwargs[k] = int(v)
        elif isinstance(default, float):
            kwargs[k] = float(v)
        else:
            try:
                kwargs[k] = int(v)
            except ValueError:
                try:
                    kwargs[k] = float(v)
                except ValueError:
                    kwargs[k] = v
    return kwargs


def cmd_gen(args) -> int:
    """Generate a topology-model family as Topology CR YAML (stdout or
    file) — the generated-scenario counterpart of the reference's
    hand-written sample files (reference config/samples/)."""
    import yaml

    from kubedtn_tpu.models.topologies import FAMILIES

    fam = FAMILIES.get(args.family)
    if fam is None:
        print(f"unknown family {args.family!r}; choices: "
              f"{', '.join(sorted(FAMILIES))}", file=sys.stderr)
        return 1
    import inspect

    sig = inspect.signature(fam)
    try:
        kwargs = _coerce_params(fam, args.param)
        el = fam(**kwargs)
    except (TypeError, ValueError, AssertionError) as e:
        print(f"gen {args.family}: {e}\nsignature: "
              f"{args.family}{sig}", file=sys.stderr)
        return 1
    docs = [t.to_manifest() for t in el.to_topologies()]
    text = yaml.safe_dump_all(docs, sort_keys=False)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(json.dumps({"family": args.family, "nodes": el.n_nodes,
                          "links": el.n_links, "file": args.out}))
    else:
        print(text)
    return 0


def cmd_crd(args) -> int:
    """Print the Topology CRD manifest rendered from the API types
    (reference config/crd/bases/, rendered copy at cni.yaml:14-280)."""
    import yaml

    from kubedtn_tpu.api.crd import render_crd

    print(yaml.safe_dump(render_crd(), sort_keys=False))
    return 0


def cmd_pcap(args) -> int:
    """Summarize a capture file written by --capture / CaptureManager:
    per-frame lines (ts offset, length, classified protocol when the
    native classifier is available) plus totals — the reading half of the
    reference's per-packet DecodeFrame debug logging (grpcwire.go:465-613).
    """
    import itertools
    from collections import Counter

    from kubedtn_tpu.utils.pcap import read_pcap

    classify_batch = None
    try:
        from kubedtn_tpu import native

        if native.have_native():
            classify_batch = native.classify_batch
    except Exception:
        pass

    totals: Counter[str] = Counter()
    n = 0
    t_first = None
    records = read_pcap(args.file)
    # classify in chunks: one native call per CHUNK frames, not per frame
    CHUNK = 1024
    while True:
        batch = list(itertools.islice(records, CHUNK))
        if not batch:
            break
        if classify_batch is not None:
            protos = classify_batch([rec.frame for rec in batch])
        else:
            protos = ["frame"] * len(batch)
        for rec, proto in zip(batch, protos):
            if t_first is None:
                t_first = rec.ts
            totals[proto] += 1
            n += 1
            if not args.quiet:
                print(f"{rec.ts - t_first:10.6f}s  {rec.orig_len:5d}B  "
                      f"{proto}")
    print(f"{args.file}: {n} frames "
          + " ".join(f"{k}={v}" for k, v in sorted(totals.items())))
    return 0


def _load_whatif_scenarios(path: str | None):
    """Scenario YAML → twin Scenario list. Layout:

      - name: spine0-slow
        perturbations:
          - {kind: degrade, uid: 1, properties: {latency: 50ms}}
          - {kind: scale, factor: 1.5}
      - name: leaf3-dead
        perturbations: [{kind: blackhole, node: leaf3}]
    """
    from kubedtn_tpu.api.types import LinkProperties
    from kubedtn_tpu.twin.spec import Perturbation, Scenario

    if not path:
        return []
    import yaml

    with open(path) as f:
        docs = yaml.safe_load(f)
    if not isinstance(docs, list):
        raise ValueError("what-if spec must be a YAML list of scenarios")
    out = []
    for i, d in enumerate(docs):
        if not isinstance(d, dict):
            raise ValueError(
                f"what-if spec entry {i} must be a mapping with "
                f"name/perturbations, got {type(d).__name__}")
        perts = []
        for p in d.get("perturbations", []):
            if not isinstance(p, dict):
                raise ValueError(
                    f"scenario {d.get('name', i)!r}: perturbation must "
                    f"be a mapping, got {type(p).__name__}")
            props = p.get("properties")
            perts.append(Perturbation(
                kind=p.get("kind", "degrade"),
                uid=p.get("uid"),
                props=(LinkProperties.from_dict(props)
                       if props is not None else None),
                node=p.get("node"),
                factor=float(p.get("factor", 1.0)),
            ))
        out.append(Scenario(name=d.get("name", f"scenario{i}"),
                            perturbations=tuple(perts)))
    return out


def cmd_whatif(args) -> int:
    """Run a what-if sweep — against a LIVE daemon (snapshot of its
    running data plane; the real-time runner never stops) or locally
    from a topology YAML — and print the ranked scenario comparison."""
    from kubedtn_tpu.api.parsers import parse_rate_bps
    from kubedtn_tpu.twin.engine import SweepResult
    from kubedtn_tpu.twin.report import rank_results, render_report
    from kubedtn_tpu.twin.spec import Scenario

    from kubedtn_tpu.twin.query import DEFAULT_RATE_BPS

    try:
        scenarios = _load_whatif_scenarios(args.spec)
    except (ValueError, OSError) as e:
        print(f"whatif spec: {e}", file=sys.stderr)
        return 1
    rate_bps = parse_rate_bps(args.rate) if args.rate else DEFAULT_RATE_BPS

    if args.daemon:
        from kubedtn_tpu.wire import proto as pb
        from kubedtn_tpu.wire.client import DaemonClient

        req = pb.WhatIfRequest(
            ticks=args.ticks, dt_us=args.dt_us,
            traffic_rate_bps=float(rate_bps), seed=args.seed,
            include_baseline=True,
            tenant=getattr(args, "tenant", "") or "")
        for sc in scenarios:
            msg = req.scenarios.add()
            msg.name = sc.name
            for p in sc.perturbations:
                pm = msg.perturbations.add()
                pm.kind = p.kind
                if p.uid is not None:
                    pm.uid = int(p.uid)
                if p.node is not None:
                    pm.node = str(p.node)
                pm.factor = float(p.factor)
                if p.props is not None:
                    pm.properties.CopyFrom(pb.props_to_proto(p.props))
        import grpc

        client = DaemonClient(args.daemon)
        try:
            resp = client.WhatIf(req, timeout=args.timeout)
        except grpc.RpcError as e:
            print(f"whatif: daemon {args.daemon} RPC failed: "
                  f"{_rpc_code(e)}", file=sys.stderr)
            return 1
        finally:
            client.close()
        if not resp.ok:
            print(f"whatif failed: {resp.error}", file=sys.stderr)
            return 1
        none_if = lambda v: None if v < 0 else v  # noqa: E731
        metrics = [{
            "tx_packets": m.tx_packets,
            "delivered_packets": m.delivered_packets,
            "delivered_bytes": m.delivered_bytes,
            "dropped_loss": m.dropped_loss,
            "dropped_queue": m.dropped_queue,
            "dropped_ring": m.dropped_ring,
            "throughput_bps": m.throughput_bps,
            "delivery_ratio": none_if(m.delivery_ratio),
            "p50_us": none_if(m.p50_us),
            "p90_us": none_if(m.p90_us),
            "p99_us": none_if(m.p99_us),
            "p99_censored": bool(m.p99_censored),
            "mean_queue_occupancy": m.mean_queue_occupancy,
            "latency_hist": list(m.latency_hist),
        } for m in resp.results]
        result = SweepResult(
            names=[m.name for m in resp.results], metrics=metrics,
            replicas=resp.replicas, ticks=resp.ticks,
            sim_seconds=resp.sim_seconds, compile_s=resp.compile_s,
            run_s=resp.run_s,
            replicas_steps_per_s=resp.replicas_steps_per_s)
        # the daemon already ranked server-side: display ITS ranks
        # rather than re-deriving (the two scorings must never drift)
        server_ranks = {m.name: m.rank for m in resp.results}
        title = f"what-if via {args.daemon}"
    else:
        if not args.file:
            print("whatif needs --daemon or --file", file=sys.stderr)
            return 1
        from kubedtn_tpu.twin.engine import run_sweep
        from kubedtn_tpu.twin.query import build_cbr_spec
        from kubedtn_tpu.twin.snapshot import snapshot_from_engine

        engine, _topos = _engine_from_yaml(args.file)
        snap = snapshot_from_engine(engine)
        with engine._lock:
            pod_ids = dict(engine._pod_ids)
        # the daemon path's ONE spec construction (query.build_cbr_spec)
        # with --rate applied — both modes answer the same question for
        # the same flags by sharing the code, not by copies
        spec = build_cbr_spec(snap.sim.edges, rate_bps=float(rate_bps))
        try:
            result = run_sweep(
                snap, [Scenario(name="baseline"), *scenarios],
                steps=args.ticks, dt_us=args.dt_us, seed=args.seed,
                spec=spec, pod_ids=pod_ids)
        except ValueError as e:
            # same one-line reporting as the daemon path gives the
            # identical spec (bad uid / node / duplicate names)
            print(f"whatif failed: {e}", file=sys.stderr)
            return 1
        server_ranks = None
        title = f"what-if on {args.file}"

    if args.json:
        print(json.dumps(_json_safe({
            "replicas": result.replicas, "ticks": result.ticks,
            "sim_seconds": result.sim_seconds,
            "compile_s": result.compile_s, "run_s": result.run_s,
            "replicas_steps_per_s": result.replicas_steps_per_s,
            "ranked": [{"rank": r, "name": n, **m}
                       for n, m, r in rank_results(
                           result, ranks=server_ranks)],
        })))
    else:
        print(render_report(result, title=title, ranks=server_ranks))
    return 0


def cmd_bench(args) -> int:
    # bench.py lives at the repo root, not in the package: anchor the
    # import so `python -m kubedtn_tpu.cli bench` works from any cwd
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    spec = importlib.util.spec_from_file_location("bench", path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench.main()
    return 0


def _setup_compile_cache() -> None:
    """Persistent compilation cache for EVERY CLI command, not just the
    daemon: the scenario rungs compile dozens of (n, block-size) kernel
    buckets that cost tens of seconds each on a small CPU host — a
    repeat `cli scenario ...` run should pay them once."""
    try:
        import jax as _jax

        cache_dir = os.environ.get(
            "KUBEDTN_JAX_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "kubedtn-jax"))
        _jax.config.update("jax_compilation_cache_dir", cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                           1.0)
    except Exception:  # an optimization, never fatal
        pass


def main(argv=None) -> int:
    # Honor JAX_PLATFORMS before any backend initializes: the axon
    # TPU-tunnel platform ignores the env var alone, so CPU-pinned runs
    # (tests, CI) need the explicit config update (same workaround as
    # tests/conftest.py and __graft_entry__.dryrun_multichip).
    if os.environ.get("JAX_PLATFORMS"):
        try:
            import jax

            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except RuntimeError:
            pass
    _setup_compile_cache()

    p = argparse.ArgumentParser(prog="tpudtn")
    sub = p.add_subparsers(dest="cmd", required=True)

    ap = sub.add_parser(
        "apply",
        help="load topology YAML and reconcile, or apply a staged "
             "plan (--plan ID --daemon)")
    ap.add_argument("file", nargs="?", default=None)
    ap.add_argument("--plan", type=int, default=None, metavar="ID",
                    help="apply a plan previously verified by "
                         "`kdt plan` (Local.ApplyPlan)")
    ap.add_argument("--daemon", default="127.0.0.1:51111",
                    metavar="HOST:PORT")
    ap.add_argument("--observe-ticks", type=int, default=0,
                    help="live ticks watched after each staged round "
                         "(0 = daemon default)")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.set_defaults(fn=cmd_apply)

    plp = sub.add_parser(
        "plan",
        help="build + twin-verify an update schedule for the YAML's "
             "desired links against a live daemon (Local.PlanUpdate)")
    plp.add_argument("file", help="topology YAML declaring the DESIRED "
                                  "link sets")
    plp.add_argument("--daemon", default="127.0.0.1:51111",
                     metavar="HOST:PORT")
    plp.add_argument("--ticks", type=int, default=0,
                     help="gate sweep horizon in virtual ticks "
                          "(0 = daemon default)")
    plp.add_argument("--dt-us", type=float, default=0.0)
    plp.add_argument("--max-delivery-drop", type=float, default=0.0,
                     help="guardrail: max absolute delivery-ratio drop "
                          "vs baseline (0 = daemon default)")
    plp.add_argument("--max-p99-factor", type=float, default=0.0,
                     help="guardrail: max p99 growth factor vs "
                          "baseline (0 = daemon default)")
    plp.add_argument("--max-round-edits", type=int, default=0,
                     help="split rounds to at most this many edits "
                          "(0 = one round per phase)")
    plp.add_argument("--seed", type=int, default=0)
    plp.add_argument("--timeout", type=float, default=300.0)
    plp.add_argument("--json", action="store_true")
    plp.set_defaults(fn=cmd_plan)

    pp = sub.add_parser("ping", help="ping-equivalent probe between pods")
    pp.add_argument("a")
    pp.add_argument("b")
    pp.add_argument("--uid", type=int, default=None)
    pp.add_argument("--file", required=True)
    pp.set_defaults(fn=cmd_ping)

    tp = sub.add_parser(
        "trace",
        help="path query (a b --file) or sampled-frame flight-recorder "
             "trace (<trace-id|latest> --daemon ...)")
    tp.add_argument("a", help="source pod, or a trace id / 'latest' "
                              "with --daemon")
    tp.add_argument("b", nargs="?", default=None)
    tp.add_argument("--file", default=None)
    tp.add_argument("--max-hops", type=int, default=16)
    tp.add_argument("--daemon", action="append", default=None,
                    metavar="HOST:PORT",
                    help="flight-recorder mode: merge this daemon's "
                         "trace events (repeat for cross-node "
                         "correlation)")
    tp.add_argument("--json", action="store_true")
    tp.set_defaults(fn=cmd_trace)

    top = sub.add_parser(
        "top",
        help="live ranked per-link table (rate, p50/p99, drops by "
             "cause) from a daemon's link telemetry plane")
    top.add_argument("--daemon", default="127.0.0.1:51111",
                     metavar="HOST:PORT")
    top.add_argument("-n", "--top", type=int, default=20,
                     help="links to show (busiest first)")
    top.add_argument("--windows", type=int, default=0,
                     help="closed telemetry windows to cover (0 = all "
                          "retained)")
    top.add_argument("--count", type=int, default=1,
                     help="refreshes to print (watch mode)")
    top.add_argument("--interval", type=float, default=2.0,
                     help="seconds between refreshes")
    top.add_argument("--json", action="store_true")
    top.set_defaults(fn=cmd_top)

    slp = sub.add_parser(
        "slo",
        help="per-tenant SLO attainment, censored-tail-estimated "
             "p99/p99.9, burn rates and error budgets "
             "(Local.ObserveSLO); --fleet merges across planes")
    slp.add_argument("--daemon", action="append", default=None,
                     metavar="HOST:PORT",
                     help="daemon(s) to query (repeat with --fleet for "
                          "a client-side cross-plane merge; default "
                          "127.0.0.1:51111)")
    slp.add_argument("--tenant", default="",
                     help="show only this tenant")
    slp.add_argument("--fleet", action="store_true",
                     help="fleet-merged view: exact histogram merge on "
                          "the shared bucket ladder, stitched with "
                          "frozen migration-journal slices (one daemon "
                          "= its supervisor's server-side merge; "
                          "several = client-side)")
    slp.add_argument("--json", action="store_true")
    slp.add_argument("--timeout", type=float, default=30.0)
    slp.set_defaults(fn=cmd_slo)

    pup = sub.add_parser(
        "pauses",
        help="barrier-pause attribution: ranked worst-cause table of "
             "every tick-lock pause the plane paid — checkpoint / "
             "compact / staged update / migration / flush / shm stall "
             "/ jit compile / GC (Local.ObservePauses)")
    pup.add_argument("--daemon", default="127.0.0.1:51111",
                     metavar="HOST:PORT")
    pup.add_argument("--cause", default="",
                     help="show only this cause")
    pup.add_argument("--events", type=int, default=0, metavar="N",
                     help="also list the N most recent attributed "
                          "pause events (0 = aggregates only)")
    pup.add_argument("--watch", action="store_true",
                     help="refresh every --interval seconds until "
                          "Ctrl-C")
    pup.add_argument("--interval", type=float, default=2.0)
    pup.add_argument("--json", action="store_true")
    pup.add_argument("--timeout", type=float, default=30.0)
    pup.set_defaults(fn=cmd_pauses)

    app = sub.add_parser(
        "autopilot",
        help="SLO autopilot: the burn-page → twin-gated staged "
             "remediation loop (Local.AutopilotCtl / AutopilotStatus)")
    app.add_argument("action",
                     choices=("status", "enable", "disable", "dry-run",
                              "history"))
    app.add_argument("value", nargs="?", default="on",
                     choices=("on", "off"),
                     help="dry-run only: on (gate + rank, stage "
                          "nothing) or off")
    app.add_argument("--daemon", default="127.0.0.1:51111",
                     metavar="HOST:PORT")
    app.add_argument("--tenant", default="",
                     help="restrict status/history to this tenant")
    app.add_argument("--limit", type=int, default=50,
                     help="history entries to show (newest first)")
    app.add_argument("--json", action="store_true")
    app.add_argument("--timeout", type=float, default=30.0)
    app.set_defaults(fn=cmd_autopilot)

    tnp = sub.add_parser(
        "tenant",
        help="multi-tenant plane: create/list/quota/stats against a "
             "live daemon (Local.Tenant*)")
    tnp.add_argument("action",
                     choices=("create", "list", "quota", "stats",
                              "delete"))
    tnp.add_argument("name", nargs="?", default="")
    tnp.add_argument("--daemon", default="127.0.0.1:51111",
                     metavar="HOST:PORT")
    tnp.add_argument("--qos", default=None,
                     choices=("gold", "silver", "bronze"),
                     help="QoS class → drain-budget weight 1/0.5/0.25")
    tnp.add_argument("--frames-per-s", type=float, default=-1.0,
                     help="admission frame budget (0 = unlimited; "
                          "omitted = leave unchanged)")
    tnp.add_argument("--bytes-per-s", type=float, default=-1.0,
                     help="admission byte budget (0 = unlimited; "
                          "omitted = leave unchanged)")
    tnp.add_argument("--block-edges", type=int, default=0,
                     help="reserve this many contiguous SoA rows for "
                          "the tenant (create only)")
    tnp.add_argument("--namespace", action="append", default=None,
                     help="bind these namespaces (default: the tenant "
                          "name itself)")
    tnp.add_argument("--timeout", type=float, default=30.0)
    tnp.set_defaults(fn=cmd_tenant)

    sp = sub.add_parser("scenario", help="run a BASELINE ladder scenario")
    sp.add_argument("name")
    sp.add_argument("-p", "--param", action="append", metavar="k=v",
                    help="scenario kwargs, e.g. -p n_spine=20 -p workers=8")
    sp.set_defaults(fn=cmd_scenario)

    # Env-var defaults keep the reference daemon's config surface
    # (reference daemon/main.go:27-40: GRPC_PORT, HTTP_ADDR, HOST_IP).
    # None defaults — the env is resolved inside cmd_daemon so a malformed
    # variable yields a daemon-scoped error, not a crash of every command.
    dp = sub.add_parser("daemon", help="serve the gRPC control plane")
    dp.add_argument("--port", type=int, default=None)
    dp.add_argument("--metrics-port", type=int, default=None)
    dp.add_argument("--node-ip",
                    default=os.environ.get("HOST_IP", "10.0.0.1"))
    dp.add_argument("--capture", default=None, metavar="PCAP",
                    help="record all wire traffic to this pcap file "
                         "(tcpdump/wireshark-readable)")
    dp.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                    help="restore state from DIR on boot (if present) and "
                         "checkpoint to it on shutdown, incl. in-flight "
                         "delay-line frames")
    dp.add_argument("--checkpoint-interval", type=float, default=0.0,
                    metavar="SECONDS",
                    help="ALSO autosave a crash-consistent checkpoint "
                         "every N seconds at a flush barrier off the "
                         "tick path (0 = only on SIGTERM) — bounds the "
                         "fleet failover RPO")
    dp.add_argument("--no-telemetry", action="store_true",
                    help="disable the link telemetry plane (per-edge "
                         "window ring + sampled flight recorder; on by "
                         "default)")
    dp.add_argument("--telemetry-window", type=float, default=1.0,
                    metavar="SECONDS",
                    help="link-telemetry window length (default 1s)")
    dp.add_argument("--telemetry-sample", type=int, default=256,
                    metavar="N", help="flight-recorder sampling period: "
                                      "1 frame in N (default 256)")
    dp.add_argument("--shard-mesh", type=int, default=0,
                    metavar="N",
                    help="shard the live plane's edge state across N "
                         "devices (-1 = all local devices; 0 = off; "
                         "power of two)")
    dp.add_argument("--trace-out", default=None, metavar="JSON",
                    help="stream catapult/Perfetto trace JSON (spans "
                         "around reconcile / checkpoint / barrier "
                         "pauses); rotated to disk periodically so a "
                         "crash loses at most one rotation, with a "
                         "final rotation on stop/SIGTERM")
    dp.add_argument("--jax-profile", default=None, metavar="DIR",
                    help="opt-in jax.profiler device capture for the "
                         "daemon's lifetime (TensorBoard-loadable)")
    dp.add_argument("--shm-dir", default=None, metavar="DIR",
                    help="serve the shared-memory ingest plane from "
                         "this directory: every producer ring "
                         "(*.ring, see kubedtn_tpu.shm.ShmSender) in "
                         "it feeds the data plane directly — "
                         "admission enforced at the ring head, gRPC "
                         "kept as the compatibility fallback")
    dp.add_argument("--autopilot", action="store_true",
                    help="enable the SLO autopilot sidecar at boot "
                         "(burn page → candidate sweep → twin-gated "
                         "staged remediation; attached but disabled "
                         "otherwise — flip live with `kdt autopilot "
                         "enable`)")
    dp.add_argument("--autopilot-dry-run", action="store_true",
                    help="autopilot evaluates and gates but stages "
                         "nothing (audit mode)")
    dp.add_argument("--autopilot-poll", type=float, default=1.0,
                    metavar="SECONDS",
                    help="autopilot control-loop poll period "
                         "(default 1s)")
    dp.add_argument("--migration-journal", default=None, metavar="DIR",
                    help="journal root for live tenant migrations "
                         "(default: <checkpoint-dir>-migrations — a "
                         "SIBLING, the checkpoint swap replaces its "
                         "own dir wholesale — or "
                         "~/.cache/kubedtn-migrations)")
    dp.set_defaults(fn=cmd_daemon)

    mgp = sub.add_parser(
        "migrate",
        help="live tenant migration between federation planes "
             "(Local.MigrateTenant / Local.MigrationStatus)")
    mgp.add_argument("tenant", nargs="?", default="")
    mgp.add_argument("--daemon", default="127.0.0.1:51111",
                     metavar="HOST:PORT",
                     help="daemon whose federation controller runs "
                          "the migration")
    mgp.add_argument("--src", default="",
                     help="source plane name (default: the serving "
                          "daemon's own plane)")
    mgp.add_argument("--dst", default="",
                     help="destination plane name")
    mgp.add_argument("--id", dest="migration_id", default="",
                     help="migration id (with --resume / --status)")
    mgp.add_argument("--resume", action="store_true",
                     help="resume the journaled migration named by "
                          "--id instead of starting a new one")
    mgp.add_argument("--status", action="store_true",
                     help="list journaled migrations (optionally "
                          "filtered by tenant / --id)")
    mgp.add_argument("--timeout", type=float, default=60.0)
    mgp.set_defaults(fn=cmd_migrate)

    flp = sub.add_parser(
        "fleet",
        help="fleet supervision: per-plane health + placement ledger "
             "(status), rolling upgrades with zero frame loss "
             "(upgrade) — Local.FleetStatus / Local.FleetUpgrade")
    flp.add_argument("action", choices=("status", "upgrade"))
    flp.add_argument("--daemon", default="127.0.0.1:51111",
                     metavar="HOST:PORT",
                     help="daemon whose fleet supervisor answers")
    flp.add_argument("--plane", action="append", default=None,
                     help="upgrade only these planes (default: every "
                          "healthy plane, one at a time)")
    flp.add_argument("--verify-probes", type=int, default=0,
                     help="consecutive clean health probes required "
                          "before refill (0 = supervisor default)")
    flp.add_argument("--timeout", type=float, default=600.0)
    flp.set_defaults(fn=cmd_fleet)

    pcp = sub.add_parser("pcap", help="summarize a capture file")
    pcp.add_argument("file")
    pcp.add_argument("-q", "--quiet", action="store_true",
                     help="totals only, no per-frame lines")
    pcp.set_defaults(fn=cmd_pcap)

    mp = sub.add_parser("manager",
                        help="run the topology controller manager "
                             "(reconcile loop + probes + leader election)")
    mp.add_argument("--workers", type=int, default=32,
                    help="concurrent reconcile workers (reference: 32)")
    mp.add_argument("--probe-port", type=int, default=8081,
                    help="healthz/readyz port (reference probe-addr :8081)")
    mp.add_argument("--metrics-port", type=int, default=8080,
                    help="controller metrics port (reference "
                         "metrics-bind-address :8080)")
    mp.add_argument("--leader-elect", action="store_true",
                    help="enable leader election (reference "
                         "--leader-elect)")
    mp.add_argument("--identity",
                    default=os.environ.get("POD_NAME", "manager-0"))
    mp.add_argument("--node-ip", default=os.environ.get("HOST_IP",
                                                        "10.0.0.1"))
    mp.set_defaults(fn=cmd_manager)

    cp = sub.add_parser("crd", help="render the Topology CRD manifest")
    cp.set_defaults(fn=cmd_crd)

    gp = sub.add_parser("gen", help="generate a topology family as YAML")
    gp.add_argument("family")
    gp.add_argument("-p", "--param", action="append", metavar="k=v",
                    help="generator kwargs, e.g. -p k=8, -p dims=4x4")
    gp.add_argument("-o", "--out", default=None)
    gp.set_defaults(fn=cmd_gen)

    jp = sub.add_parser("physical-join",
                        help="join a physical host via a daemon")
    jp.add_argument("file")
    jp.add_argument("--daemon", default="127.0.0.1:51111")
    jp.set_defaults(fn=cmd_physical_join)

    wp = sub.add_parser(
        "whatif",
        help="what-if sweep: fork a snapshot (live daemon or topology "
             "YAML), run perturbed replicas, print a ranked comparison")
    wp.add_argument("--daemon", default=None, metavar="HOST:PORT",
                    help="query a LIVE daemon (snapshot of its running "
                         "data plane)")
    wp.add_argument("--file", default=None,
                    help="topology YAML for a local (daemonless) sweep")
    wp.add_argument("--spec", default=None, metavar="YAML",
                    help="scenario spec file (see `whatif` docs); "
                         "omitted = baseline only")
    wp.add_argument("--tenant", default=None,
                    help="tenant-scoped fork: sweep only this "
                         "tenant's edge slice (daemon mode)")
    wp.add_argument("--ticks", type=int, default=1000)
    wp.add_argument("--dt-us", type=float, default=1000.0)
    wp.add_argument("--rate", default=None,
                    help="offered CBR per edge, e.g. 1Mbit (default)")
    wp.add_argument("--seed", type=int, default=0)
    wp.add_argument("--timeout", type=float, default=300.0)
    wp.add_argument("--json", action="store_true",
                    help="machine-readable output instead of the table")
    wp.set_defaults(fn=cmd_whatif)

    bp = sub.add_parser("bench", help="run the headline benchmark")
    bp.set_defaults(fn=cmd_bench)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # downstream pipe (e.g. `| head`) closed early — normal for a CLI
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
