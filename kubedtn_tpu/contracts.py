"""Concurrency contracts: the `@guarded_by` attribute registry and the
instrumented lock harness that enforces lock-ordering at runtime.

Five review rounds per PR kept finding the same two defect shapes by
hand: a plane/sender/telemetry attribute touched off its owning lock
(torn counters, racy ring reads) and lock-acquisition orders that only
deadlock under load. This module turns both into declared, checkable
contracts:

- ``@guarded_by("_tick_lock", "attr", ...)`` on a class declares which
  lock owns which attributes. The static side
  (``kubedtn_tpu.analysis.passes.lock_discipline``) parses the same
  decorator from the AST and flags any ``self.attr`` access outside a
  ``with self._tick_lock`` block; the declaration also lands in a
  runtime registry (``guarded_attrs``) so tests can introspect it.
- ``@requires_lock("_tick_lock")`` on a method declares "my caller
  holds the lock" — the static pass treats the whole method body as
  covered instead of flagging every line.
- ``InstrumentedLock`` wraps a real ``threading.Lock``/``RLock`` and
  records every held→acquiring pair into a shared ``LockOrderGraph``;
  the graph raises ``LockOrderError`` the moment an acquisition closes
  a cycle (the classic AB/BA inversion), instead of leaving the
  deadlock to a soak run. ``instrument_locks`` swaps an object's lock
  attributes in place for tests.

No jax / numpy imports here: the decorators are applied at import time
by ``runtime.py`` / ``telemetry.py`` / ``fault.py`` and must stay
dependency-free and cheap.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, TypeVar

_C = TypeVar("_C", bound=type)
_F = TypeVar("_F", bound=Callable[..., Any])

# class qualname ("module.Class") -> {attribute name: owning lock name}
_GUARDED: dict[str, dict[str, str]] = {}


def guarded_by(lock: str, *attrs: str) -> Callable[[_C], _C]:
    """Class decorator: the listed attributes are owned by ``self.<lock>``.

    Purely declarative at runtime (a registry entry plus a
    ``__dtnlint_guarded__`` mapping on the class); the static pass and
    the test harness do the enforcement.
    """

    def deco(cls: _C) -> _C:
        key = f"{cls.__module__}.{cls.__qualname__}"
        reg = _GUARDED.setdefault(key, {})
        merged = dict(getattr(cls, "__dtnlint_guarded__", {}))
        for a in attrs:
            reg[a] = lock
            merged[a] = lock
        cls.__dtnlint_guarded__ = merged  # type: ignore[attr-defined]
        return cls

    return deco


def requires_lock(lock: str) -> Callable[[_F], _F]:
    """Method decorator: the caller holds ``self.<lock>`` for the whole
    call. The static lock pass treats the body as covered."""

    def deco(fn: _F) -> _F:
        held = set(getattr(fn, "__dtnlint_requires__", ()))
        held.add(lock)
        fn.__dtnlint_requires__ = frozenset(held)  # type: ignore[attr-defined]
        return fn

    return deco


def guarded_attrs(cls: type) -> dict[str, str]:
    """The attribute→lock map a class (or its bases) declared."""
    return dict(getattr(cls, "__dtnlint_guarded__", {}))


def registry() -> dict[str, dict[str, str]]:
    """Snapshot of every ``guarded_by`` declaration seen this process."""
    return {k: dict(v) for k, v in _GUARDED.items()}


class LockOrderError(AssertionError):
    """An instrumented acquisition closed a cycle in the lock-order
    graph — the AB/BA inversion that deadlocks under contention."""


class LockOrderGraph:
    """Directed held→acquiring edges over named locks, cycle-checked on
    every new edge. Shared by all ``InstrumentedLock``s of one harness;
    thread-safe."""

    def __init__(self, raise_on_cycle: bool = True) -> None:
        self.raise_on_cycle = raise_on_cycle
        self._edges: dict[str, set[str]] = {}
        self._mu = threading.Lock()
        self.violations: list[str] = []

    def record(self, held: str, acquiring: str) -> None:
        if held == acquiring:  # re-entrant RLock acquisition
            return
        with self._mu:
            known = acquiring in self._edges.get(held, ())
            self._edges.setdefault(held, set()).add(acquiring)
            if known:
                return
            cycle = self._find_path(acquiring, held)
            if cycle is not None:
                msg = (f"lock-order cycle: acquiring {acquiring!r} while "
                       f"holding {held!r}, but an established order runs "
                       + " -> ".join([*cycle, acquiring]))
                self.violations.append(msg)
        if cycle is not None and self.raise_on_cycle:
            raise LockOrderError(msg)

    def _find_path(self, src: str, dst: str) -> list[str] | None:
        """DFS path src→dst over recorded edges (caller holds _mu)."""
        stack: list[tuple[str, list[str]]] = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def edges(self) -> dict[str, set[str]]:
        with self._mu:
            return {k: set(v) for k, v in self._edges.items()}

    def assert_acyclic(self) -> None:
        if self.violations:
            raise LockOrderError("; ".join(self.violations))


class InstrumentedLock:
    """Drop-in wrapper over a ``threading.Lock``/``RLock`` that feeds a
    ``LockOrderGraph``. Each thread's held-lock stack is tracked in a
    class-level ``threading.local`` shared by every instrumented lock,
    so cross-lock ordering is observed no matter which objects own
    them."""

    _tls = threading.local()

    def __init__(self, name: str, graph: LockOrderGraph,
                 lock: Any | None = None) -> None:
        self.name = name
        self.graph = graph
        self._lock = lock if lock is not None else threading.Lock()

    @classmethod
    def _stack(cls) -> list["InstrumentedLock"]:
        stack = getattr(cls._tls, "stack", None)
        if stack is None:
            stack = []
            cls._tls.stack = stack
        return stack

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        for held in self._stack():
            self.graph.record(held.name, self.name)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._stack().append(self)
        return ok

    def release(self) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._lock.release()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()


def instrument_locks(obj: Any, graph: LockOrderGraph,
                     attrs: Iterable[str]) -> dict[str, InstrumentedLock]:
    """Swap ``obj``'s named lock attributes for instrumented wrappers
    (tests only). Returns the wrappers by attribute name."""
    out: dict[str, InstrumentedLock] = {}
    for a in attrs:
        real = getattr(obj, a)
        name = f"{type(obj).__name__}.{a}"
        wrapped = InstrumentedLock(name, graph, lock=real)
        setattr(obj, a, wrapped)
        out[a] = wrapped
    return out
