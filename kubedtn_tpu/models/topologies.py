"""Topology model families — generators for the scenario ladder.

The reference ships hand-written YAML topologies (reference
config/samples/3node.yml, config/samples/tc/*.yaml); at TPU scale the
topologies in BASELINE.md's ladder (64-node fat-tree → 100k-link Clos) are
generated. Generators emit an array-native EdgeList (structure-of-arrays,
ready for the device) plus converters to Topology CRs for the control-plane
path, so the same model drives both the batched fast path and the full
reconcile pipeline.

Conventions match the reference sample format: per-node Topology with one
Link per incident edge, shared uid on both endpoint views, eth<i> interface
naming, 10.x.y.z/24 point-to-point addressing where applicable.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from kubedtn_tpu.api.types import Link, LinkProperties, Topology, TopologySpec
from kubedtn_tpu.ops import edge_state as es


@dataclasses.dataclass
class EdgeList:
    """Undirected p2p links in array form (one row per link, not per
    direction — the engine/device layer expands to directed rows)."""

    node_names: list[str]
    a: np.ndarray        # int32[L] endpoint A node index
    b: np.ndarray        # int32[L] endpoint B node index
    uid: np.ndarray      # int32[L] unique link id (1-based like the samples)
    props: np.ndarray    # float32[L, NPROP] shared link properties

    @property
    def n_links(self) -> int:
        return len(self.uid)

    @property
    def n_nodes(self) -> int:
        return len(self.node_names)

    def directed(self):
        """Expand to directed rows: (src, dst, uid, props), 2L entries —
        each endpoint's egress, the device-array representation."""
        src = np.concatenate([self.a, self.b]).astype(np.int32)
        dst = np.concatenate([self.b, self.a]).astype(np.int32)
        uid = np.concatenate([self.uid, self.uid]).astype(np.int32)
        props = np.concatenate([self.props, self.props]).astype(np.float32)
        return src, dst, uid, props

    def to_topologies(self, namespace: str = "default") -> list[Topology]:
        """Materialize per-node Topology CRs (sample-file format)."""
        links_by_node: dict[int, list[Link]] = {i: [] for i in
                                                range(self.n_nodes)}
        numeric_names = es.PROP_NAMES
        for i in range(self.n_links):
            a, b, uid = int(self.a[i]), int(self.b[i]), int(self.uid[i])
            props = _props_to_strings(self.props[i], numeric_names)
            ia = len(links_by_node[a]) + 1
            ib = len(links_by_node[b]) + 1
            links_by_node[a].append(Link(
                local_intf=f"eth{ia}", peer_intf=f"eth{ib}",
                peer_pod=self.node_names[b], uid=uid, properties=props))
            links_by_node[b].append(Link(
                local_intf=f"eth{ib}", peer_intf=f"eth{ia}",
                peer_pod=self.node_names[a], uid=uid, properties=props))
        return [
            Topology(name=self.node_names[i], namespace=namespace,
                     spec=TopologySpec(links=links_by_node[i]))
            for i in range(self.n_nodes)
        ]


def _props_to_strings(row: np.ndarray, names) -> LinkProperties:
    """Invert props_row: numeric row back to string-typed LinkProperties."""
    d = {n: float(v) for n, v in zip(names, row)}

    def us(v):
        # integer microseconds: never scientific notation, always matches
        # the CRD duration pattern
        return "" if v == 0 else f"{int(v)}us"

    def pc(v):
        if v == 0:
            return ""
        s = f"{v:.8f}".rstrip("0").rstrip(".")
        return s if s else "0"

    return LinkProperties(
        latency=us(d["latency_us"]),
        latency_corr=pc(d["latency_corr"]),
        jitter=us(d["jitter_us"]),
        loss=pc(d["loss"]),
        loss_corr=pc(d["loss_corr"]),
        rate="" if d["rate_bps"] == 0 else f"{int(d['rate_bps'])}bit",
        gap=int(d["gap"]),
        duplicate=pc(d["duplicate"]),
        duplicate_corr=pc(d["duplicate_corr"]),
        reorder_prob=pc(d["reorder_prob"]),
        reorder_corr=pc(d["reorder_corr"]),
        corrupt_prob=pc(d["corrupt_prob"]),
        corrupt_corr=pc(d["corrupt_corr"]),
    )


def _mk(node_names, pairs, props: LinkProperties | None = None,
        prop_rows: np.ndarray | None = None) -> EdgeList:
    pairs = np.asarray(pairs, dtype=np.int32).reshape(-1, 2)
    L = len(pairs)
    if prop_rows is None:
        row = np.asarray(es.props_row(
            (props or LinkProperties()).to_numeric()), np.float32)
        prop_rows = np.broadcast_to(row, (L, es.NPROP)).copy()
    return EdgeList(
        node_names=list(node_names),
        a=pairs[:, 0].copy(),
        b=pairs[:, 1].copy(),
        uid=np.arange(1, L + 1, dtype=np.int32),
        props=prop_rows.astype(np.float32),
    )


def line(n: int, props: LinkProperties | None = None) -> EdgeList:
    names = [f"n{i}" for i in range(n)]
    return _mk(names, [(i, i + 1) for i in range(n - 1)], props)


def ring(n: int, props: LinkProperties | None = None) -> EdgeList:
    names = [f"n{i}" for i in range(n)]
    return _mk(names, [(i, (i + 1) % n) for i in range(n)], props)


def star(n_leaves: int, props: LinkProperties | None = None) -> EdgeList:
    names = ["hub"] + [f"leaf{i}" for i in range(n_leaves)]
    return _mk(names, [(0, i + 1) for i in range(n_leaves)], props)


def full_mesh(n: int, props: LinkProperties | None = None) -> EdgeList:
    names = [f"r{i + 1}" for i in range(n)]
    return _mk(names, [(i, j) for i in range(n) for j in range(i + 1, n)],
               props)


def random_mesh(n_nodes: int, n_links: int, seed: int = 0,
                props: LinkProperties | None = None) -> EdgeList:
    """Random connected-ish mesh: a spanning backbone plus random extra
    links (no self-loops; parallel links allowed, distinct uids — matching
    the reference's model where uid, not endpoints, identifies a link)."""
    rng = np.random.default_rng(seed)
    names = [f"n{i}" for i in range(n_nodes)]
    backbone = [(i, rng.integers(0, i)) for i in range(1, min(n_nodes,
                                                              n_links + 1))]
    extra = n_links - len(backbone)
    pairs = list(backbone)
    if extra > 0:
        a = rng.integers(0, n_nodes, extra)
        off = rng.integers(1, n_nodes, extra)
        b = (a + off) % n_nodes
        pairs += list(zip(a.tolist(), b.tolist()))
    return _mk(names, pairs, props)


def three_tier(pods: int = 100, leaves_per_pod: int = 96,
               aggs_per_pod: int = 4, cores: int = 40,
               uplinks_per_leaf: int = 2, cores_per_agg: int = 10,
               seed: int = 0,
               props: LinkProperties | None = None) -> EdgeList:
    """Three-tier DC fabric at cluster scale: `pods` pods of
    leaves + aggs, a shared core layer — the 10k-node structured
    topology for the flap-reconvergence rung (a k8s cluster network's
    shape, unlike random_mesh's high-betweenness sparse graph). Each
    leaf uplinks to `uplinks_per_leaf` of its pod's aggs, each agg to
    `cores_per_agg` cores. Per-link latencies get a deterministic ±10%
    spread (seeded) so shortest paths are mostly unique — the
    realistic-reconvergence regime rather than the all-ties one.

    Defaults: 100*(96+4)+40 = 10_040 nodes, 100*96*2 + 100*4*10 =
    23_200 links."""
    rng = np.random.default_rng(seed)
    names = [f"core{c}" for c in range(cores)]
    names += [f"p{p}-agg{a}" for p in range(pods)
              for a in range(aggs_per_pod)]
    names += [f"p{p}-leaf{i}" for p in range(pods)
              for i in range(leaves_per_pod)]
    agg0 = cores
    leaf0 = cores + pods * aggs_per_pod
    pairs = []
    for p in range(pods):
        for i in range(leaves_per_pod):
            leaf = leaf0 + p * leaves_per_pod + i
            for u in range(uplinks_per_leaf):
                agg = agg0 + p * aggs_per_pod + (i + u) % aggs_per_pod
                pairs.append((leaf, agg))
        for a in range(aggs_per_pod):
            agg = agg0 + p * aggs_per_pod + a
            for c in range(cores_per_agg):
                core = (a * cores_per_agg + c + p) % cores
                pairs.append((agg, core))
    el = _mk(names, pairs, props)
    base = el.props[:, es.P_LATENCY_US].copy()
    base = np.where(base > 0, base, 1000.0)
    el.props[:, es.P_LATENCY_US] = base * rng.uniform(0.9, 1.1,
                                                      el.n_links)
    return el


def fat_tree(k: int, props: LinkProperties | None = None) -> EdgeList:
    """Standard k-ary fat-tree (k even): (k/2)² cores, k pods of k/2 agg +
    k/2 edge switches, k²/4 core-agg links per pod side, agg-edge full
    bipartite within pods. k=8 → 80 switches, 256 links (the 64-node-scale
    scenario of BASELINE.md's ladder)."""
    assert k % 2 == 0, "fat-tree arity must be even"
    half = k // 2
    cores = [f"core{i}" for i in range(half * half)]
    aggs = [f"pod{p}-agg{i}" for p in range(k) for i in range(half)]
    edges = [f"pod{p}-edge{i}" for p in range(k) for i in range(half)]
    names = cores + aggs + edges
    idx = {n: i for i, n in enumerate(names)}
    pairs = []
    for p in range(k):
        for i in range(half):
            agg = idx[f"pod{p}-agg{i}"]
            # each agg connects to half cores: core group i*half..i*half+half
            for j in range(half):
                pairs.append((idx[f"core{i * half + j}"], agg))
            # full bipartite agg-edge inside the pod
            for e in range(half):
                pairs.append((agg, idx[f"pod{p}-edge{e}"]))
    return _mk(names, pairs, props)


def clos(n_spine: int, n_leaf: int, hosts_per_leaf: int = 0,
         props: LinkProperties | None = None,
         links_per_pair: int = 1) -> EdgeList:
    """2-tier spine-leaf Clos: every leaf connects to every spine
    (`links_per_pair` parallel links each), plus optional hosts per leaf.
    clos(100, 500, 0, links_per_pair=2) = 100_000 fabric links — the
    100k-link BASELINE scenario bench.py runs."""
    spines = [f"spine{i}" for i in range(n_spine)]
    leaves = [f"leaf{i}" for i in range(n_leaf)]
    hosts = [f"leaf{i}-h{j}" for i in range(n_leaf)
             for j in range(hosts_per_leaf)]
    names = spines + leaves + hosts
    pairs = []
    for li in range(n_leaf):
        leaf = n_spine + li
        for si in range(n_spine):
            for _ in range(links_per_pair):
                pairs.append((si, leaf))
        for j in range(hosts_per_leaf):
            pairs.append((leaf, n_spine + n_leaf + li * hosts_per_leaf + j))
    return _mk(names, pairs, props)


def torus(dims: tuple[int, ...] | list[int],
          props: LinkProperties | None = None) -> EdgeList:
    """k-ary n-dimensional torus (wraparound grid) — the ICI topology of a
    TPU pod itself, and a standard HPC interconnect. torus((4, 4)) = 16
    nodes, 32 links; torus((4, 4, 4)) = 64 nodes, 192 links."""
    dims = tuple(int(d) for d in dims)
    assert all(d >= 2 for d in dims), "each torus dimension needs >= 2 nodes"
    shape = np.array(dims)
    n = int(shape.prod())
    coords = np.stack(np.unravel_index(np.arange(n), dims), axis=1)
    names = ["t" + "-".join(str(c) for c in row) for row in coords]
    pairs = []
    for axis, d in enumerate(dims):
        nxt = coords.copy()
        nxt[:, axis] = (nxt[:, axis] + 1) % d
        nbr = np.ravel_multi_index(tuple(nxt.T), dims)
        for i in range(n):
            j = int(nbr[i])
            # a dimension of size 2 has a single link per pair, not two
            if d == 2 and j < i:
                continue
            pairs.append((i, j))
    return _mk(names, pairs, props)


def hypercube(d: int, props: LinkProperties | None = None) -> EdgeList:
    """d-dimensional binary hypercube: 2^d nodes, d·2^(d-1) links."""
    n = 1 << d
    names = [f"h{i:0{max(d, 1)}b}" for i in range(n)]
    pairs = [(i, i ^ (1 << bit)) for i in range(n) for bit in range(d)
             if i < (i ^ (1 << bit))]
    return _mk(names, pairs, props)


def dragonfly(groups: int, routers_per_group: int,
              global_links_per_router: int = 1,
              props: LinkProperties | None = None) -> EdgeList:
    """Dragonfly: fully-meshed groups joined by global links spread
    round-robin over the routers of each group (the Cray/Slingshot-style
    hierarchical low-diameter fabric)."""
    g, a, h = groups, routers_per_group, global_links_per_router
    assert g >= 2 and a >= 1 and h >= 1
    names = [f"g{gi}-r{ri}" for gi in range(g) for ri in range(a)]
    pairs = []
    for gi in range(g):
        base = gi * a
        pairs.extend((base + i, base + j)
                     for i in range(a) for j in range(i + 1, a))
    # global channels: g·(g-1)/2 group pairs, each realized h times,
    # endpoints rotated through the group's routers
    counter = [0] * g
    for gi in range(g):
        for gj in range(gi + 1, g):
            for _ in range(h):
                ri = counter[gi] % a
                rj = counter[gj] % a
                counter[gi] += 1
                counter[gj] += 1
                pairs.append((gi * a + ri, gj * a + rj))
    return _mk(names, pairs, props)


def barabasi_albert(n: int, m: int = 2, seed: int = 0,
                    props: LinkProperties | None = None) -> EdgeList:
    """Scale-free preferential-attachment graph (Barabási–Albert): each
    new node attaches to m existing nodes with probability proportional
    to degree — heavy-tailed AS-/internet-like topologies."""
    assert 1 <= m < n
    rng = np.random.default_rng(seed)
    names = [f"as{i}" for i in range(n)]
    pairs: list[tuple[int, int]] = []
    # attachment pool: every edge endpoint once (degree-proportional draw)
    pool: list[int] = []
    for new in range(m, n):
        if not pool:
            targets = list(range(new))[:m]
        else:
            targets = []
            seen: set[int] = set()
            while len(targets) < m:
                t = int(pool[rng.integers(0, len(pool))])
                if t not in seen and t != new:
                    seen.add(t)
                    targets.append(t)
        for t in targets:
            pairs.append((new, t))
            pool.extend((new, t))
    return _mk(names, pairs, props)


def watts_strogatz(n: int, k: int = 4, beta: float = 0.1, seed: int = 0,
                   props: LinkProperties | None = None) -> EdgeList:
    """Small-world ring lattice with rewiring (Watts–Strogatz): each node
    starts linked to its k nearest ring neighbors; each link's far end is
    rewired with probability beta."""
    assert k % 2 == 0 and k < n
    rng = np.random.default_rng(seed)
    names = [f"n{i}" for i in range(n)]
    existing: set[tuple[int, int]] = set()
    pairs = []
    for i in range(n):
        for off in range(1, k // 2 + 1):
            j = (i + off) % n
            key = (min(i, j), max(i, j))
            if key in existing:
                continue
            if rng.random() < beta:
                for _ in range(8):  # bounded retries
                    cand = int(rng.integers(0, n))
                    ck = (min(i, cand), max(i, cand))
                    if cand != i and ck not in existing:
                        key = ck
                        break
            existing.add(key)
            pairs.append(key)
    return _mk(names, pairs, props)


def geo_wan(n: int, degree: int = 3, seed: int = 0,
            rate: str = "10Gbit") -> EdgeList:
    """Geographic WAN: n sites at random plane coordinates (km), each
    linked to its `degree` nearest neighbors, with per-link latency from
    fiber distance (~5 µs/km — the c/1.5 rule of thumb). Unlike the other
    families every link gets its own property row, exercising the
    heterogeneous-props path end to end."""
    assert n >= 2 and 1 <= degree < n, "need n >= 2 and 1 <= degree < n"
    rng = np.random.default_rng(seed)
    xy = rng.uniform(0, 5000.0, (n, 2))  # continental scale, km
    names = [f"site{i}" for i in range(n)]
    d2 = ((xy[:, None, :] - xy[None, :, :]) ** 2).sum(-1)
    np.fill_diagonal(d2, np.inf)
    pairs = []
    seen: set[tuple[int, int]] = set()
    # spanning backbone first (like random_mesh): each site links to its
    # geographically nearest already-placed site, so the WAN is connected
    # regardless of how the k-NN extras fall
    for i in range(1, n):
        j = int(np.argmin(d2[i, :i]))
        seen.add((j, i))
        pairs.append((j, i))
    order = np.argsort(d2, axis=1)
    for i in range(n):
        for j in order[i, :degree]:
            key = (min(i, int(j)), max(i, int(j)))
            if key not in seen:
                seen.add(key)
                pairs.append(key)
    pairs_arr = np.asarray(pairs, np.int32)
    km = np.sqrt(d2[pairs_arr[:, 0], pairs_arr[:, 1]])
    base = es.props_row(LinkProperties(rate=rate).to_numeric())
    prop_rows = np.broadcast_to(np.asarray(base, np.float32),
                                (len(pairs), es.NPROP)).copy()
    lat_col = es.PROP_NAMES.index("latency_us")
    prop_rows[:, lat_col] = np.maximum(1.0, np.round(km * 5.0))
    return _mk(names, pairs, prop_rows=prop_rows)


FAMILIES = {
    "line": line, "ring": ring, "star": star, "full_mesh": full_mesh,
    "random_mesh": random_mesh, "fat_tree": fat_tree, "clos": clos,
    "torus": torus, "hypercube": hypercube, "dragonfly": dragonfly,
    "barabasi_albert": barabasi_albert, "watts_strogatz": watts_strogatz,
    "geo_wan": geo_wan,
}


def random_link_props(n: int, seed: int,
                      rates=(20e6, 50e6, 100e6, 1e9, 10e9)) -> np.ndarray:
    """n random-but-valid numeric property rows — the shared benchmark
    workload (bench.py's headline and the scale_1m rung must draw from
    the SAME distribution so their updates/sec numbers stay comparable):
    latency 1-100ms, jitter 0-5ms, loss 0-2%, rate drawn from `rates`."""
    rng = np.random.default_rng(seed)
    base = np.zeros((n, es.NPROP), np.float32)
    base[:, es.P_LATENCY_US] = rng.integers(1_000, 100_000, n)
    base[:, es.P_JITTER_US] = rng.integers(0, 5_000, n)
    base[:, es.P_LOSS] = rng.uniform(0, 2, n)
    base[:, es.P_RATE_BPS] = rng.choice(np.asarray(rates), n)
    return base


def load_edge_list_into_state(el: EdgeList, capacity: int | None = None):
    """Fast path: place a generated topology directly into a fresh
    EdgeState, bypassing the per-link control plane. Returns
    (state, rows) where rows[i] is the row of directed edge i."""
    import jax.numpy as jnp

    src, dst, uid, props = el.directed()
    n = len(src)
    if capacity is None:
        capacity = max(8, int(2 ** np.ceil(np.log2(n + 1))))
    state = es.init_state(capacity)
    rows = np.arange(n, dtype=np.int32)
    state = es.apply_links(
        state, jnp.asarray(rows), jnp.asarray(uid), jnp.asarray(src),
        jnp.asarray(dst), jnp.asarray(props), jnp.ones(n, dtype=bool))
    return state, rows
