"""Traffic models — per-edge packet sources for the data plane.

The reference measures its data plane with external traffic generators
(ping in hack/test-3node.sh, iperf pods in config/samples/tc/bandwidth.yaml);
here the generators are part of the framework, vectorized per edge:

- CBR: constant bit rate, byte-credit accumulator.
- Poisson: Poisson packet arrivals at a mean rate.
- ON/OFF: two-state bursty source (exponential sojourn times) gating a CBR.

Each step every edge emits up to K packet slots (sizes, validity, arrival
offsets inside the step) — fully static shapes, advanced by one fused
kernel.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

MODE_OFF = 0
MODE_CBR = 1
MODE_POISSON = 2
MODE_ONOFF = 3


@dataclasses.dataclass(frozen=True)
class TrafficSpec:
    """Static per-edge traffic configuration."""

    mode: jax.Array       # i32[E]
    rate_bps: jax.Array   # f32[E] offered load (mean for poisson/onoff)
    pkt_bytes: jax.Array  # f32[E]
    on_us: jax.Array      # f32[E] mean ON sojourn (onoff)
    off_us: jax.Array     # f32[E] mean OFF sojourn (onoff)


@dataclasses.dataclass(frozen=True)
class TrafficState:
    """Mutable per-edge source state."""

    credit: jax.Array     # f32[E] accumulated bytes not yet emitted
    on: jax.Array         # bool[E] ON/OFF gate


for _cls in (TrafficSpec, TrafficState):
    jax.tree_util.register_dataclass(
        _cls,
        data_fields=[f.name for f in dataclasses.fields(_cls)],
        meta_fields=[],
    )


def cbr_everywhere(capacity: int, n_edges: int, rate_bps: float,
                   pkt_bytes: float = 1500.0) -> TrafficSpec:
    """Convenience: CBR on the first n_edges rows, off elsewhere."""
    idx = jnp.arange(capacity)
    on = idx < n_edges
    return TrafficSpec(
        mode=jnp.where(on, MODE_CBR, MODE_OFF).astype(jnp.int32),
        rate_bps=jnp.where(on, rate_bps, 0.0).astype(jnp.float32),
        pkt_bytes=jnp.full((capacity,), pkt_bytes, jnp.float32),
        on_us=jnp.zeros((capacity,), jnp.float32),
        off_us=jnp.zeros((capacity,), jnp.float32),
    )


def init_traffic_state(capacity: int) -> TrafficState:
    return TrafficState(
        credit=jnp.zeros((capacity,), jnp.float32),
        on=jnp.ones((capacity,), dtype=bool),
    )


def generate(spec: TrafficSpec, ts: TrafficState, dt_us: jax.Array,
             k: int, key: jax.Array):
    """Emit up to k packets per edge for one step of length dt_us.

    Returns (ts', sizes f32[E,K], valid bool[E,K], t_arrival f32[E,K]).
    Arrivals are offsets in [0, dt_us), sorted along K.
    """
    E = spec.mode.shape[0]
    k_onoff, k_poisson, k_arr = jax.random.split(key, 3)

    rate_b_us = spec.rate_bps / 8e6  # bytes per µs

    # ON/OFF gate: per-step toggle probabilities from exponential sojourns.
    p_off2on = jnp.where(spec.off_us > 0, 1 - jnp.exp(-dt_us / jnp.maximum(
        spec.off_us, 1.0)), 1.0)
    p_on2off = jnp.where(spec.on_us > 0, 1 - jnp.exp(-dt_us / jnp.maximum(
        spec.on_us, 1.0)), 0.0)
    u = jax.random.uniform(k_onoff, (E,))
    toggled_on = jnp.where(ts.on, u >= p_on2off, u < p_off2on)
    gate = jnp.where(spec.mode == MODE_ONOFF, toggled_on, True)

    # CBR / ON-gated CBR: credit accumulator.
    is_cbr = (spec.mode == MODE_CBR) | ((spec.mode == MODE_ONOFF) & gate)
    credit = ts.credit + jnp.where(is_cbr, rate_b_us * dt_us, 0.0)
    n_cbr = jnp.floor(credit / jnp.maximum(spec.pkt_bytes, 1.0))

    # Poisson: mean packets per step = rate / pkt_size.
    lam = rate_b_us * dt_us / jnp.maximum(spec.pkt_bytes, 1.0)
    n_poi = jax.random.poisson(
        k_poisson, jnp.where(spec.mode == MODE_POISSON, lam, 0.0),
        (E,)).astype(jnp.float32)

    n = jnp.where(spec.mode == MODE_POISSON, n_poi, n_cbr)
    n = jnp.where((spec.mode == MODE_OFF), 0.0, n)
    n = jnp.minimum(n, float(k))
    credit = jnp.where(is_cbr, credit - n * spec.pkt_bytes, credit)

    lane = jnp.arange(k, dtype=jnp.float32)[None, :]      # [1, K]
    valid = lane < n[:, None]
    sizes = jnp.where(valid, spec.pkt_bytes[:, None], 0.0)

    # arrivals: CBR evenly spaced; poisson uniform-sorted.
    even = (lane + 0.5) / jnp.maximum(n[:, None], 1.0) * dt_us
    rand = jnp.sort(
        jax.random.uniform(k_arr, (E, k), maxval=dt_us), axis=1)
    t_arr = jnp.where((spec.mode == MODE_POISSON)[:, None], rand, even)
    t_arr = jnp.where(valid, t_arr, 0.0)

    return (
        TrafficState(credit=credit, on=jnp.where(
            spec.mode == MODE_ONOFF, toggled_on, ts.on)),
        sizes, valid, t_arr,
    )
