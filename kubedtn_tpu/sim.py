"""NetworkSim — the time-stepped data-plane runtime.

This is the steady-state engine the reference implements as kernel machinery
per link (veth + qdiscs + VXLAN/grpc-wire threads, reference
daemon/grpcwire/grpcwire.go:386-462): traffic sources emit packets, the
netem+TBF chain shapes them, delay lines hold them in flight, deliveries
update per-edge counters — all as one fused, jitted device step over every
edge at once. Virtual time advances in fixed steps; wall-clock binding (for
interactive use) is a matter of pacing `step` calls.

Composes with the routing layer (kubedtn_tpu.ops.routing) for multi-hop
forwarding: delivered packets whose final_dst is not the edge's dst re-enter
the fabric on the next-hop edge.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from kubedtn_tpu.ops import netem
from kubedtn_tpu.ops.edge_state import EdgeState
from kubedtn_tpu.ops.queues import (
    EdgeCounters,
    InFlight,
    init_counters,
    init_inflight,
    insert_inflight,
    pop_due,
    shape_packets,
)
from kubedtn_tpu.models.traffic import (
    TrafficSpec,
    TrafficState,
    generate,
    init_traffic_state,
)


@dataclasses.dataclass(frozen=True)
class SimState:
    """Everything the data plane carries between steps."""

    edges: EdgeState
    inflight: InFlight
    counters: EdgeCounters
    traffic: TrafficState
    clock_us: jax.Array  # f32 VIRTUAL clock (bounded-horizon sims;
    # wall-clock anchors stay f64 host-side — twin/snapshot)


jax.tree_util.register_dataclass(
    SimState,
    data_fields=[f.name for f in dataclasses.fields(SimState)],
    meta_fields=[],
)


def init_sim(edges: EdgeState, q: int = 32) -> SimState:
    cap = edges.capacity
    return SimState(
        edges=edges,
        inflight=init_inflight(cap, q),
        counters=init_counters(cap),
        traffic=init_traffic_state(cap),
        clock_us=jnp.zeros((), jnp.float32),  # dtnlint: dtype-ok(device virtual clock, f32 SoA contract; the f64-anchor rule protects WALL-clock anchors, which live host-side in twin/snapshot since PR 3)
    )


def _add(c: EdgeCounters, **deltas) -> EdgeCounters:
    return dataclasses.replace(
        c, **{k: getattr(c, k) + v for k, v in deltas.items()})


def _step_parts(sim: SimState, spec: TrafficSpec, key: jax.Array,
                k_slots: int, dt_us: jax.Array, size_scale=None):
    """Shared body of `sim_step`: generate → shape → enqueue → deliver,
    split so the what-if twin engine (kubedtn_tpu.twin.engine) can
    reuse it piecewise: traffic generation is replica-INDEPENDENT (the
    active mask applies after it, and nothing downstream feeds back),
    so a replica sweep hoists `generate` out of its vmap — one
    unbatched call per step, bit-identical to this function's — and
    vmaps only `_finish_step`. `size_scale` (scalar) multiplies
    generated packet sizes — the twin's per-replica offered-load dial;
    None traces the exact historical program.

    Returns (sim', due, res, sizes, t_arr)."""
    kg, ks = jax.random.split(key)

    # 1. traffic sources
    tstate, sizes, valid, t_arr = generate(spec, sim.traffic, dt_us,
                                           k_slots, kg)
    return _finish_step(sim, tstate, sizes, valid, t_arr, ks, dt_us,
                        size_scale)


def _finish_step(sim: SimState, tstate, sizes, valid, t_arr, ks,
                 dt_us: jax.Array, size_scale=None):
    """Steps 2-4 of the data-plane step (everything after traffic
    generation): shape → enqueue → deliver → counters → epoch roll."""
    valid = valid & sim.edges.active[:, None]
    sizes = jnp.where(valid, sizes, 0.0)  # keep byte counters honest
    if size_scale is not None:
        sizes = sizes * size_scale

    # 2. qdisc chain (netem root + TBF child), K sequential slots per edge
    edges, res = shape_packets(sim.edges, sizes, valid, t_arr, ks)

    # 3. duplicates: the kernel re-enqueues a copy through the qdisc; the
    #    copy here shares its original's departure time (one extra lane
    #    per duplicated packet).
    dep_all = jnp.concatenate([res.depart_us, res.depart_us], axis=1)
    sz_all = jnp.concatenate([sizes, sizes], axis=1)
    corr_all = jnp.concatenate([res.corrupted, res.corrupted], axis=1)
    deliver_all = jnp.concatenate(
        [res.delivered, res.delivered & res.duplicated], axis=1)
    fdst = jnp.broadcast_to(edges.dst[:, None], dep_all.shape)

    fl, dropped_ring = insert_inflight(
        sim.inflight, dep_all, sz_all, fdst, corr_all, deliver_all)

    # 4. deliver everything due inside this step (reads pre-clear arrays)
    fl_after, due = pop_due(fl, dt_us)
    rx_p = due.sum(axis=1).astype(jnp.float32)
    rx_b = jnp.where(due, fl.size, 0.0).sum(axis=1)
    rx_c = jnp.where(due, fl.corrupted, False).sum(axis=1).astype(jnp.float32)

    counters = _add(
        sim.counters,
        tx_packets=valid.sum(axis=1).astype(jnp.float32),
        tx_bytes=sizes.sum(axis=1),
        rx_packets=rx_p,
        rx_bytes=rx_b,
        rx_corrupted=rx_c,
        dropped_loss=res.dropped_loss.sum(axis=1).astype(jnp.float32),
        dropped_queue=res.dropped_queue.sum(axis=1).astype(jnp.float32),
        dropped_ring=dropped_ring,
        duplicated=res.duplicated.sum(axis=1).astype(jnp.float32),
        reordered=res.reordered.sum(axis=1).astype(jnp.float32),
    )

    edges = netem.roll_epoch.__wrapped__(edges, dt_us)
    sim2 = SimState(edges=edges, inflight=fl_after, counters=counters,
                    traffic=tstate, clock_us=sim.clock_us + dt_us)
    return sim2, due, res, sizes, t_arr


@partial(jax.jit, static_argnums=(3,), donate_argnums=0)
def sim_step(sim: SimState, spec: TrafficSpec, key: jax.Array,
             k_slots: int, dt_us: jax.Array):
    """One data-plane step: generate → shape → enqueue → deliver.

    Returns (sim', delivered_mask bool[E, Q]) — the mask refers to the
    pre-pop in-flight arrays for callers needing per-packet delivery times.
    """
    sim2, due, _res, _sizes, _t_arr = _step_parts(sim, spec, key, k_slots,
                                                  dt_us)
    return sim2, due


def run(sim: SimState, spec: TrafficSpec, steps: int, dt_us: float,
        k_slots: int = 8, seed: int = 0) -> SimState:
    """Advance `steps` × dt_us of virtual time under one scan."""

    keys = jax.random.split(jax.random.key(seed), steps)
    dt = jnp.float32(dt_us)

    @partial(jax.jit, static_argnums=(2,))
    def _run(sim, keys, k_slots):
        def body(s, k):
            s2, _ = sim_step.__wrapped__(s, spec, k, k_slots, dt)
            return s2, None

        s, _ = jax.lax.scan(body, sim, keys)
        return s

    return _run(sim, keys, k_slots)


def throughput_bps(before: EdgeCounters, after: EdgeCounters,
                   elapsed_us: float):
    """Achieved per-edge goodput between two counter snapshots — the
    iperf-equivalent measurement for the bandwidth scenario (reference
    config/samples/tc/bandwidth.yaml)."""
    return (after.rx_bytes - before.rx_bytes) * 8.0 / (elapsed_us / 1e6)
