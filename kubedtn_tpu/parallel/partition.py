"""Edge partitioner for the sharded live data plane.

The live plane's edge-state SoA is BLOCK-sharded along the edge axis
(`jax.sharding.PartitionSpec("edge")`): shard s owns the contiguous row
range [s*E/S, (s+1)*E/S). The partitioner's job is therefore not an
arbitrary row→shard map but (a) steering the engine's row ALLOCATION so
that the two directed rows of one link — and hence both endpoints of
every frame's hop — land in the same block where possible, and (b)
describing the cross-shard MAILBOX traffic that remains: which ordered
shard pairs exchange rows each tick, bounded by the per-tick drain.

A frame is CROSS-SHARD when the shard owning its ingress edge row
differs from the shard owning its destination (peer) edge row; those
are exactly the rows whose state rides the ring exchange
(parallel/exchange.py) instead of staying shard-local.
"""

from __future__ import annotations

from typing import Any

import numpy as np
import numpy.typing as npt

__all__ = ["shard_ranges", "shard_of_rows", "colocation_stats",
           "mailbox_layout", "pick_pair_rows", "tenant_block",
           "tenant_blocks"]


def shard_ranges(capacity: int, n_shards: int) -> list[tuple[int, int]]:
    """[(lo, hi)) row range per shard for block sharding. Requires
    capacity % n_shards == 0 (the plane pads capacity at enable time)."""
    if n_shards <= 0 or capacity % n_shards:
        raise ValueError(
            f"capacity {capacity} not divisible by {n_shards} shards")
    loc = capacity // n_shards
    return [(s * loc, (s + 1) * loc) for s in range(n_shards)]


def shard_of_rows(rows: npt.ArrayLike, capacity: int,
                  n_shards: int) -> np.ndarray:
    """Owner shard per row (block sharding)."""
    loc = capacity // n_shards
    return np.asarray(rows, np.int64) // loc


def pick_pair_rows(free, capacity: int, n_shards: int,
                   scan_limit: int = 64) -> tuple[int, int]:
    """Pop TWO free rows colocated in one shard block where possible.

    `free` is the engine's columnar free-list STACK
    (`topology.freelist.FreeStack`; pop from the top). The first row
    pops normally; the second is the nearest free row to the top —
    within a `scan_limit`-entry window — in the SAME block, falling
    back to a plain pop when the block has no other free row in reach.
    The window scan is ONE vectorized compare over at most
    `scan_limit` int32 entries (the historical per-element Python
    scan, byte-identical pick order), O(1) in the common
    fresh-allocation case (the free list is initialized descending,
    so consecutive pops are consecutive rows)."""
    r1 = free.pop()
    if n_shards <= 1:
        return r1, free.pop()
    loc = capacity // n_shards
    blk = r1 // loc
    # duck-typed: FreeStack gives a zero-copy window; a plain list
    # (tests, embedders) pays one small copy
    window = (free.top_view(scan_limit) if hasattr(free, "top_view")
              else np.asarray(free[max(0, len(free) - scan_limit):],
                              np.int64))
    hits = np.nonzero(window // loc == blk)[0]
    if hits.size:
        i = len(free) - window.shape[0] + int(hits[-1])
        return r1, (free.pop_at(i) if hasattr(free, "pop_at")
                    else free.pop(i))
    return r1, free.pop()


def tenant_blocks(free, capacity: int, n_shards: int,
                  requests: list[int]) -> list[tuple[int, int] | None]:
    """Carve a CONTIGUOUS run of currently-free rows out of the
    engine's free list for EACH requested tenant edge block, in ONE
    sorted pass — the batch behind `tenant_block` and the registry's
    whole-registry re-carve after a compact (T tenants cost one sort
    of the free list and one rebuild, not T of each, and the free list
    is engine state mutated under the engine lock the tick path's
    allocator also wants).

    Composition with shard blocks: for each request, a candidate run
    that fits entirely inside one shard's [s*E/S, (s+1)*E/S) range is
    preferred — a tenant whose block sits inside one shard never pays
    the cross-shard mailbox for intra-tenant hops — falling back to a
    boundary-spanning run (still contiguous, still isolated) only when
    no shard-local run is free. Requests are served in order; returns
    a same-length list of [lo, hi) (rows removed from `free`) or None
    when no contiguous run of that length exists (the caller then
    leaves that tenant on the shared pool)."""
    loc = (capacity // n_shards
           if n_shards > 1 and capacity % n_shards == 0 else capacity)
    rows = np.sort(np.asarray(
        free.view() if hasattr(free, "view") else free, np.int64))
    # maximal contiguous runs as half-open [lo, hi) intervals, kept
    # sorted as carved windows split them
    runs: list[tuple[int, int]] = []
    if rows.size:
        breaks = np.nonzero(np.diff(rows) != 1)[0] + 1
        starts = [0, *breaks.tolist(), rows.size]
        runs = [(int(rows[a]), int(rows[b - 1]) + 1)
                for a, b in zip(starts[:-1], starts[1:])]
    out: list[tuple[int, int] | None] = []
    carved: list[tuple[int, int]] = []
    for n_rows in requests:
        if n_rows <= 0:
            out.append(None)
            continue
        local: tuple[int, int, int] | None = None
        spanning: tuple[int, int, int] | None = None
        for idx, (lo, hi) in enumerate(runs):
            if hi - lo < n_rows:
                continue
            if spanning is None:
                spanning = (idx, lo, lo + n_rows)
            # the earliest window inside the run that does not
            # straddle a shard-block boundary wins — computed
            # directly: `lo` itself, or the next boundary when lo's
            # window would cross it (no position in between can avoid
            # the crossing); impossible outright when the window
            # outsizes a shard block
            if n_rows <= loc:
                w_lo = (lo if lo // loc == (lo + n_rows - 1) // loc
                        else (lo // loc + 1) * loc)
                if w_lo + n_rows <= hi:
                    local = (idx, w_lo, w_lo + n_rows)
                    break
        best = local if local is not None else spanning
        if best is None:
            out.append(None)
            continue
        idx, lo, hi = best
        rlo, rhi = runs[idx]
        runs[idx:idx + 1] = [r for r in ((rlo, lo), (hi, rhi))
                             if r[1] > r[0]]
        carved.append((lo, hi))
        out.append((lo, hi))
    if carved:
        # ONE vectorized order-preserving filter of the free stack
        # (FreeStack.remove_rows) — the historical per-element
        # `[r for r in free if r not in taken]` rebuild was an
        # O(capacity) Python walk under the engine lock
        taken = np.concatenate(
            [np.arange(lo, hi, dtype=np.int64) for lo, hi in carved])
        if hasattr(free, "remove_rows"):
            free.remove_rows(taken)
        else:  # plain-list callers (tests, embedders)
            tset = set(taken.tolist())
            free[:] = [r for r in free if r not in tset]
    return out


def tenant_block(free, capacity: int, n_shards: int,
                 n_rows: int) -> tuple[int, int] | None:
    """Single-request form of `tenant_blocks` (same preference order
    and free-list contract)."""
    return tenant_blocks(free, capacity, n_shards, [n_rows])[0]


def colocation_stats(engine: Any, n_shards: int) -> dict[str, object]:
    """Partition quality of the CURRENT topology: per-shard active edge
    counts, load imbalance (max/mean - 1 over non-empty planes), and
    the fraction of peered links whose two directed rows share a shard
    (the frames that never touch the ring exchange)."""
    import numpy as np  # noqa: F811 (kept local for clarity)

    with engine._lock:
        engine._flush_device_locked()
        state = engine._state
        peer = dict(engine._peer)
        rows = dict(engine._rows)
    E = state.capacity
    if E % n_shards:
        raise ValueError(f"capacity {E} not divisible by {n_shards}")
    active = np.asarray(state.active)
    per_shard = active.reshape(n_shards, E // n_shards).sum(axis=1)
    total = int(per_shard.sum())
    mean = total / n_shards if n_shards else 0.0
    imbalance = (float(per_shard.max()) / mean - 1.0) if total else 0.0
    loc = E // n_shards
    pairs = colocated = 0
    for k, pk in peer.items():
        if k > pk:
            continue  # count each link once
        r1, r2 = rows.get(k), rows.get(pk)
        if r1 is None or r2 is None:
            continue
        pairs += 1
        if r1 // loc == r2 // loc:
            colocated += 1
    return {
        "n_shards": int(n_shards),
        "edges_per_shard": [int(x) for x in per_shard],
        "total_edges": total,
        "imbalance": round(imbalance, 4),
        "links_paired": pairs,
        "links_colocated": colocated,
        "colocated_frac": round(colocated / pairs, 4) if pairs else 1.0,
    }


def mailbox_layout(src_rows: npt.ArrayLike, dst_rows: npt.ArrayLike,
                   capacity: int, n_shards: int) -> dict[str, object]:
    """Per-ordered-neighbor-pair mailbox slot counts for one tick's
    busy rows: src_rows are the rows with traffic, dst_rows the peer
    (destination) edge rows (-1 = unknown/none). Returns the non-zero
    (src_shard, dst_shard) → slot-count map plus the bound the ring
    exchange actually allocates (every busy row rides the mailbox once
    per ring step, so the per-step block size is len(src_rows))."""
    src_sh = shard_of_rows(src_rows, capacity, n_shards)
    dst = np.asarray(dst_rows, np.int64)
    known = dst >= 0
    dst_sh = np.full_like(src_sh, -1)
    dst_sh[known] = shard_of_rows(dst[known], capacity, n_shards)
    pairs: dict[tuple[int, int], int] = {}
    for s, t in zip(src_sh.tolist(), dst_sh.tolist()):
        if t >= 0 and s != t:
            pairs[(s, t)] = pairs.get((s, t), 0) + 1
    return {
        "pairs": pairs,
        "cross_rows": int(sum(pairs.values())),
        "mailbox_slots": int(len(src_sh)),
    }
