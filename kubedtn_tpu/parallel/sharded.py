"""Edge-sharded simulation step: pjit + shard_map over the device mesh.

This is the scale path of the framework — the TPU-native replacement for the
reference's "many daemons, peer-to-peer RPC" architecture (SURVEY.md §5.8):

- The batched link ops (update/apply scatters) run under jit over arrays
  whose edge dimension is sharded across the mesh; XLA partitions the
  scatters and inserts the necessary traffic.
- The per-edge shaping kernel is embarrassingly parallel along the edge
  axis: zero communication.
- Per-node counters (the daemon's interface-statistics collection, reference
  daemon/metrics/interface_statistics.go:79-133) need cross-shard reduction:
  each shard segment-sums its local edges into a [n_nodes] partial, then a
  `psum` over the edge axis — one ICI all-reduce — replaces the reference's
  per-node Prometheus scrape aggregation.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.ops import netem
from kubedtn_tpu.parallel.mesh import EDGE_AXIS, shard_map


@dataclasses.dataclass(frozen=True)
class NodeStats:
    """Per-node traffic counters — the schema of the reference's
    per-interface Prometheus collector (interface_statistics.go:19-65),
    aggregated to nodes."""

    tx_packets: jax.Array  # f32[n_nodes]
    tx_bytes: jax.Array
    rx_packets: jax.Array  # delivered into the node
    rx_bytes: jax.Array
    dropped: jax.Array     # loss + queue drops on the node's egress


jax.tree_util.register_dataclass(
    NodeStats,
    data_fields=[f.name for f in dataclasses.fields(NodeStats)],
    meta_fields=[],
)


def make_node_stats_fn(mesh, n_nodes: int):
    """Build the shard_map'd per-node counter reduction."""

    def local_partial(src, dst, delivered, sizes, dropped):
        # [E_local] inputs on this shard
        deliv_b = jnp.where(delivered, sizes, 0.0)
        deliv_p = delivered.astype(jnp.float32)
        drop_p = dropped.astype(jnp.float32)
        tx_p = jax.ops.segment_sum(deliv_p, src, num_segments=n_nodes)
        tx_b = jax.ops.segment_sum(deliv_b, src, num_segments=n_nodes)
        rx_p = jax.ops.segment_sum(deliv_p, dst, num_segments=n_nodes)
        rx_b = jax.ops.segment_sum(deliv_b, dst, num_segments=n_nodes)
        dr_p = jax.ops.segment_sum(drop_p, src, num_segments=n_nodes)
        # one ICI all-reduce merges every shard's partials
        out = NodeStats(
            tx_packets=jax.lax.psum(tx_p, EDGE_AXIS),
            tx_bytes=jax.lax.psum(tx_b, EDGE_AXIS),
            rx_packets=jax.lax.psum(rx_p, EDGE_AXIS),
            rx_bytes=jax.lax.psum(rx_b, EDGE_AXIS),
            dropped=jax.lax.psum(dr_p, EDGE_AXIS),
        )
        return out

    edge = P(EDGE_AXIS)
    return shard_map(
        local_partial,
        mesh=mesh,
        in_specs=(edge, edge, edge, edge, edge),
        out_specs=NodeStats(*([P()] * 5)),
    )


def make_sharded_step(mesh, n_nodes: int):
    """The full sharded simulation step: link updates → shaping → stats.

    Returns a jitted function
        step(state, urows, uprops, uvalid, sizes, have, t_arr, key)
            -> (state', ShapeResult, NodeStats)
    with the EdgeState pinned to edge-dim sharding throughout.
    """
    edge_sh = NamedSharding(mesh, P(EDGE_AXIS))
    stats_fn = make_node_stats_fn(mesh, n_nodes)

    def pin(state: es.EdgeState) -> es.EdgeState:
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, edge_sh), state)

    @partial(jax.jit, donate_argnums=0)
    def step(state, urows, uprops, uvalid, sizes, have, t_arr, key):
        # 1. control plane: batched property updates (sharded scatter)
        state = es.update_links(state, urows, uprops, uvalid)
        state = pin(state)
        # 2. data plane: per-edge shaping (no communication). Deliberately
        # the vmapped XLA path, not the Pallas kernel: this step is
        # GSPMD-partitioned by jit, and XLA can shard elementwise HLOs
        # along the edge axis automatically, while a pallas_call has no
        # partitioning rule and would force replication here.
        state, res = netem.shape_step(state, sizes, have, t_arr, key)
        state = pin(state)
        # 3. observability: cross-shard per-node counters (psum over ICI)
        stats = stats_fn(state.src, state.dst, res.delivered, sizes,
                         res.dropped_loss | res.dropped_queue)
        return state, res, stats

    return step
