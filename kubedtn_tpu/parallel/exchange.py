"""Cross-shard mailbox exchange for the sharded live data plane.

The sharded fused tick (runtime `_make_sharded_fused`) keeps the
edge-state SoA block-sharded along the edge axis; each tick's busy rows
are scattered across shards, but every shard must run the SAME shaping
program over the SAME gathered per-row state for the results to stay
byte-identical to the unsharded plane (the kernels draw their uniforms
over the whole padded [R, K] batch). This module moves that per-row
state between shards as a bounded per-tick MAILBOX:

- Each shard packs the rows it OWNS into fixed-size mailbox blocks
  (`[R, Wf]` float32 payload + `[R, Wi]` int32 payload whose column 0 is
  the ownership flag) and zeroes the rest.
- The mailbox travels the ring: S-1 steps, each step one bounded
  neighbor-pair transfer (shard s → shard s+1 mod S). After the full
  ring every shard holds every row's owner payload.
- The combine is a SELECT, not a sum: exactly one shard owns each row,
  so `where(owned, incoming, acc)` moves the owner's bits verbatim —
  no floating-point arithmetic ever touches the payload, which is what
  makes the N-shard plane bit-identical to the 1-shard plane.

Backends:

- **TPU**: each ring step is a Pallas `make_async_remote_copy` remote
  DMA (`_dma_right_shift`) with send/recv DMA semaphores in scratch —
  the SNIPPETS right-permute pattern — so cross-shard frame-state
  movement stays on the ICI fabric, never the host.
- **everywhere else** (the tier-1 CPU mesh under
  `--xla_force_host_platform_device_count`): the identical ring with
  each DMA swapped for a `lax.ppermute` — same mailbox layout, same
  step count, same select-combine, same bits.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from kubedtn_tpu.parallel.mesh import EDGE_AXIS

__all__ = ["use_remote_dma", "make_ring_exchange", "dma_right_shift",
           "OWNER_COL"]

# Column of the int mailbox payload that carries the ownership flag
# (1 on the owning shard, 0 elsewhere). The combine below selects on
# it, and dtnverify's sharding audit (analysis/verify/sharding_audit)
# verifies at the jaxpr level that foreign payload bits reach the
# kernels ONLY through that select — never arithmetic.
OWNER_COL = 0


def use_remote_dma(mesh=None) -> bool:
    """True when the Pallas remote-DMA ring should carry the exchange:
    every device of the mesh (default: all local devices) is a TPU.
    The ppermute ring is the fallback everywhere else — identical
    mailbox layout and bits, different transport."""
    try:
        devs = (list(mesh.devices.flat) if mesh is not None
                else jax.devices())
        return bool(devs) and all(d.platform == "tpu" for d in devs)
    except Exception:
        return False


# -- TPU remote-DMA ring step ------------------------------------------

def _right_permute_kernel(in_ref, out_ref, send_sem, recv_sem, *,
                          axis: str, n_shards: int):
    """One ring step: DMA this shard's mailbox block into the right
    neighbor's output buffer. DMA semaphores live in scratch; the wait
    covers both the local send completing and the left neighbor's copy
    landing in `out_ref` (recv_sem)."""
    from jax.experimental.pallas import tpu as pltpu

    my_id = lax.axis_index(axis)
    right = lax.rem(my_id + 1, n_shards)
    rdma = pltpu.make_async_remote_copy(
        src_ref=in_ref,
        dst_ref=out_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=(right,),
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    rdma.start()
    rdma.wait()


def dma_right_shift(x, axis: str = EDGE_AXIS, n_shards: int | None = None):
    """`lax.ppermute(x, axis, [(s, s+1 mod S)])` as a Pallas remote-DMA
    kernel — must be called inside a shard_map over `axis` on a TPU
    mesh. `x` is one shard's mailbox block `[R, W]`."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if n_shards is None:
        n_shards = lax.axis_size(axis)
    return pl.pallas_call(
        functools.partial(_right_permute_kernel, axis=axis,
                          n_shards=n_shards),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
    )(x)


# -- the ring exchange --------------------------------------------------

def make_ring_exchange(n_shards: int, axis: str = EDGE_AXIS,
                       use_dma: bool = False):
    """Build the per-tick mailbox exchange for an `n_shards` ring.

    Returns `exch(fmail, imail) -> (fmail', imail')` to be called
    INSIDE a shard_map body over `axis`:

    - `fmail` float32 `[R, Wf]`: the shard's owned rows' float payload
      (props / clocks / correlation memory), zero elsewhere.
    - `imail` int32 `[R, Wi]`: integer payload with **column 0 the
      ownership flag** (1 on the owner shard, 0 elsewhere).

    After the call both mailboxes hold, on EVERY shard, each row's
    owner payload — assembled by S-1 bounded neighbor-pair transfers
    with a bitwise select-combine (module docstring)."""
    if n_shards <= 1:
        return lambda fmail, imail: (fmail, imail)
    perm = [(s, (s + 1) % n_shards) for s in range(n_shards)]
    if use_dma:
        def shift(x):
            return dma_right_shift(x, axis=axis, n_shards=n_shards)
    else:
        def shift(x):
            return lax.ppermute(x, axis, perm)

    def exch(fmail, imail):
        accf, acci = fmail, imail
        rf, ri = fmail, imail
        for _ in range(n_shards - 1):
            rf = shift(rf)
            ri = shift(ri)
            own = ri[:, OWNER_COL:OWNER_COL + 1] > 0
            accf = jnp.where(own, rf, accf)
            acci = jnp.where(own, ri, acci)
        return accf, acci

    return exch
