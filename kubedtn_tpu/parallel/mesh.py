"""Device-mesh construction for the edge-sharded simulation.

The reference scales by placing pods (and hence their links) across K8s
nodes, each node's daemon owning its local links and completing cross-node
edges peer-to-peer over gRPC/VXLAN (reference daemon/kubedtn/handler.go:419-453,
common/utils.go:39-68). Here the scaling axis is the **edge dimension of the
simulation arrays**: edges are sharded over a `jax.sharding.Mesh`, XLA
collectives over ICI/DCN replace daemon-to-daemon RPC, and multi-host runs
extend the same mesh via jax.distributed.

Axis names:
- "edge": the data-parallel axis over edge rows (always present).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6: shard_map is a top-level export
    from jax import shard_map
except ImportError:  # older jax (e.g. 0.4.x): experimental home, where
    # the check_rep replication checker predates while_loop support
    # (poisson traffic gen trips it) — modern jax dropped the check, so
    # disabling it here gives the same semantics on every version
    import functools as _functools

    from jax.experimental.shard_map import shard_map as _shard_map_exp

    shard_map = _functools.partial(_shard_map_exp, check_rep=False)

__all__ = ["EDGE_AXIS", "REPLICA_AXIS", "make_mesh", "make_replica_mesh",
           "edge_sharding", "replica_sharding", "replicated",
           "init_distributed", "shard_map"]

EDGE_AXIS = "edge"
# The what-if twin's scaling axis (kubedtn_tpu.twin.engine): replicas of
# the whole edge state, embarrassingly parallel — a sweep sharded over
# this axis partitions with zero collectives.
REPLICA_AXIS = "replica"


def make_mesh(n_devices: int | None = None,
              devices: list | None = None) -> Mesh:
    """1-D mesh over `n_devices` (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (EDGE_AXIS,))


def make_replica_mesh(n_devices: int | None = None,
                      devices: list | None = None) -> Mesh:
    """1-D mesh over the what-if REPLICA axis (twin sweeps shard their
    leading replica dimension across it; N must be a multiple of the
    mesh size — twin.spec pads with unperturbed replicas)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (REPLICA_AXIS,))


def edge_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (edge) dimension, replicate the rest."""
    return NamedSharding(mesh, P(EDGE_AXIS))


def replica_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (replica) dimension, replicate the rest."""
    return NamedSharding(mesh, P(REPLICA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Join a multi-host run (jax.distributed) — the TPU-native analogue of
    the reference's daemon joining the cluster and peering over gRPC
    (reference daemon/main.go:20-107): afterwards jax.devices() spans every
    host and the collectives in the sharded step ride ICI within a slice
    and DCN across slices. No-op when already initialized."""
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # tolerate ONLY re-initialization ("distributed.initialize should
        # only be called once." in current jax); a connect/config failure
        # must surface (swallowing it leaves a silent single-process run)
        msg = str(e).lower()
        if "already" not in msg and "only be called once" not in msg:
            raise


def make_multihost_mesh() -> Mesh:
    """1-D edge mesh over EVERY process's devices, host-major.

    Host-major order means a block-sharded edge array keeps consecutive
    shards on the same host: the all_to_all segments between co-hosted
    shards ride ICI, only inter-host segments touch DCN — the layout
    recipe of the scaling-book's "pick a mesh, let XLA insert collectives".
    """
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    return Mesh(np.array(devs), (EDGE_AXIS,))


def shard_edge_state(state, mesh: Mesh):
    """Place every EdgeState array with its edge dimension sharded.

    All EdgeState arrays are [E] or [E, k]; capacity is kept a multiple of
    the mesh size by the engine's power-of-two growth.
    """
    sh = edge_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), state)
