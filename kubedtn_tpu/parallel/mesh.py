"""Device-mesh construction for the edge-sharded simulation.

The reference scales by placing pods (and hence their links) across K8s
nodes, each node's daemon owning its local links and completing cross-node
edges peer-to-peer over gRPC/VXLAN (reference daemon/kubedtn/handler.go:419-453,
common/utils.go:39-68). Here the scaling axis is the **edge dimension of the
simulation arrays**: edges are sharded over a `jax.sharding.Mesh`, XLA
collectives over ICI/DCN replace daemon-to-daemon RPC, and multi-host runs
extend the same mesh via jax.distributed.

Axis names:
- "edge": the data-parallel axis over edge rows (always present).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

EDGE_AXIS = "edge"


def make_mesh(n_devices: int | None = None,
              devices: list | None = None) -> Mesh:
    """1-D mesh over `n_devices` (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (EDGE_AXIS,))


def edge_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (edge) dimension, replicate the rest."""
    return NamedSharding(mesh, P(EDGE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_edge_state(state, mesh: Mesh):
    """Place every EdgeState array with its edge dimension sharded.

    All EdgeState arrays are [E] or [E, k]; capacity is kept a multiple of
    the mesh size by the engine's power-of-two growth.
    """
    sh = edge_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), state)
