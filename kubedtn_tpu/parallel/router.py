"""Sharded multi-hop forwarding: shard_map + all_to_all over the edge mesh.

The reference completes multi-node paths with daemon-to-daemon RPC — every
cross-node link crossing is one unary gRPC per packet (reference
daemon/grpcwire/grpcwire.go:386-462) or a kernel VXLAN hop. Here the
forwarding plane is sharded along the edge axis, and the per-step batch of
"packets whose next hop lives on another shard" crosses in ONE
`jax.lax.all_to_all` over ICI — the collective replaces the RPC mesh
(SURVEY.md §5.7-5.8).

Step anatomy (inside one shard_map over the 'edge' axis):
  1. local data plane: traffic gen → netem+TBF shaping → delay lines →
     due deliveries (all per-edge elementwise, zero communication);
  2. route lookup on the replicated next-hop table;
  3. bucket transit packets by owner shard of their next-hop edge into a
     fixed [n_shards, budget] exchange buffer (overflow counted, like a
     router input-queue drop);
  4. all_to_all the buffer; re-inject received packets into local pending
     lanes for the next step;
  5. psum per-node delivery counters across shards.

Everything is static-shape; the exchange budget bounds per-step cross-shard
traffic the way the reference's gRPC channel capacity bounds its wires.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from kubedtn_tpu.models.traffic import TrafficSpec, generate
from kubedtn_tpu.ops import netem
from kubedtn_tpu.ops.queues import insert_inflight, pop_due, shape_packets
from kubedtn_tpu.parallel.mesh import EDGE_AXIS, shard_map
from kubedtn_tpu.router import RouterState, _group_into_lanes
from kubedtn_tpu.sim import SimState, _add


def _edge_specs(rs: RouterState, n_shards: int):
    """Spec pytree: edge-dim arrays sharded, tables/counters replicated."""
    del n_shards
    sim_spec = jax.tree.map(lambda x: P(EDGE_AXIS), rs.sim)
    sim_spec = dataclasses.replace(sim_spec, clock_us=P())
    return RouterState(
        sim=sim_spec,
        next_edge=P(),
        pend_size=P(EDGE_AXIS),
        pend_dst=P(EDGE_AXIS),
        pend_corr=P(EDGE_AXIS),
        node_rx_packets=P(),
        node_rx_bytes=P(),
        fwd_dropped=P(),
        no_route_dropped=P(),
    )


def _bucket_by_shard(shard_of: jax.Array, lrow: jax.Array, size: jax.Array,
                     fdst: jax.Array, corr: jax.Array, live: jax.Array,
                     n_shards: int, budget: int):
    """Scatter flat packets into [n_shards, budget, 4] send lanes.

    Same sort+segmented-rank trick as router._group_into_lanes, keyed by
    destination shard. Fields packed f32: (local_row, size, final_dst,
    corrupted); empty lanes have local_row == -1.
    """
    M = shard_of.shape[0]
    tgt = jnp.where(live, shard_of, n_shards)
    order = jnp.argsort(tgt)
    tgt_s = tgt[order]
    idx = jnp.arange(M)
    starts = jnp.concatenate([jnp.array([True]), tgt_s[1:] != tgt_s[:-1]])
    start_idx = jax.lax.associative_scan(
        jnp.maximum, jnp.where(starts, idx, 0))
    rank = idx - start_idx

    ok = (tgt_s < n_shards) & (rank < budget)
    row = jnp.where(ok, tgt_s, n_shards)
    lane = jnp.where(ok, rank, 0)

    fields = jnp.stack([
        jnp.where(live, lrow.astype(jnp.float32), -1.0)[order],
        size[order],
        fdst.astype(jnp.float32)[order],
        corr.astype(jnp.float32)[order],
    ], axis=-1)                                   # [M, 4]
    buf = jnp.full((n_shards + 1, budget, 4), -1.0, jnp.float32)
    buf = buf.at[row, lane].set(
        jnp.where(ok[:, None], fields, -1.0), mode="drop")[:n_shards]
    dropped = ((tgt_s < n_shards) & (rank >= budget)).sum().astype(jnp.float32)
    return buf, dropped


def make_sharded_router_step(mesh, n_nodes: int, k_slots: int = 4,
                             k_fwd: int = 8, budget: int | None = None):
    """Build the jitted sharded router step.

    Returns step(rs, spec, flow_dst, key, dt_us) -> rs' with every edge-dim
    leaf of `rs` (and `spec`/`flow_dst`) sharded over the mesh's edge axis.
    """
    n_shards = mesh.devices.size
    if budget is None:
        budget = max(k_fwd * 8, 16)

    spec_edge = TrafficSpec(*([P(EDGE_AXIS)] * 5))

    def body(rs: RouterState, spec: TrafficSpec, flow_dst, key, dt_us):
        sim = rs.sim
        E_loc = sim.edges.capacity            # local block
        shard = jax.lax.axis_index(EDGE_AXIS)
        row0 = shard * E_loc                  # global row offset
        key = jax.random.fold_in(key, shard)
        kg, ks = jax.random.split(key)

        # 1. traffic + pending re-injections (local)
        tstate, sizes_t, valid_t, t_arr_t = generate(
            spec, sim.traffic, dt_us, k_slots, kg)
        valid_t = valid_t & sim.edges.active[:, None]
        sizes_t = jnp.where(valid_t, sizes_t, 0.0)
        fd = jnp.where(flow_dst >= 0, flow_dst, sim.edges.dst)
        fdst_t = jnp.broadcast_to(fd[:, None], sizes_t.shape)

        valid_p = rs.pend_dst >= 0
        sizes = jnp.concatenate([sizes_t, rs.pend_size], axis=1)
        valid = jnp.concatenate([valid_t, valid_p], axis=1)
        t_arr = jnp.concatenate([t_arr_t, jnp.zeros_like(rs.pend_size)],
                                axis=1)
        fdst_in = jnp.concatenate([fdst_t, rs.pend_dst], axis=1)

        # 2. shaping (local, elementwise over edges)
        edges, res = shape_packets(sim.edges, sizes, valid, t_arr, ks)

        # 3. delay lines (duplicates share the original's departure).
        #    Corruption persists across hops: carry the pending lanes' flag.
        corr_in = jnp.concatenate(
            [jnp.zeros_like(valid_t), rs.pend_corr & valid_p], axis=1)
        corr_now = res.corrupted | (corr_in & res.delivered)
        dep_all = jnp.concatenate([res.depart_us, res.depart_us], axis=1)
        sz_all = jnp.concatenate([sizes, sizes], axis=1)
        co_all = jnp.concatenate([corr_now, corr_now], axis=1)
        fd_all = jnp.concatenate([fdst_in, fdst_in], axis=1)
        deliver_all = jnp.concatenate(
            [res.delivered, res.delivered & res.duplicated], axis=1)
        fl, dropped_ring = insert_inflight(
            sim.inflight, dep_all, sz_all, fd_all, co_all, deliver_all)

        # 4. due deliveries
        fl_after, due = pop_due(fl, dt_us)
        here = jnp.broadcast_to(edges.dst[:, None], due.shape)
        at_dest = due & (fl.final_dst == here)
        in_transit = due & ~at_dest

        # 4a. final deliveries -> per-node counters (psum'd below)
        n = rs.node_rx_packets.shape[0]
        local_rx_p = jnp.zeros((n,), jnp.float32).at[
            jnp.where(at_dest, here, n)].add(1.0, mode="drop")
        local_rx_b = jnp.zeros((n,), jnp.float32).at[
            jnp.where(at_dest, here, n)].add(
            jnp.where(at_dest, fl.size, 0.0), mode="drop")

        # 4b. transit -> next-hop edge (global row), bucket by owner shard
        flat_here = here.reshape(-1)
        flat_fd = fl.final_dst.reshape(-1)
        flat_live = in_transit.reshape(-1)
        safe_here = jnp.where(flat_live, flat_here, 0)
        safe_fd = jnp.where(flat_live, jnp.maximum(flat_fd, 0), 0)
        nxt = rs.next_edge[safe_here, safe_fd]    # global edge row
        no_route = flat_live & (nxt < 0)
        live = flat_live & (nxt >= 0)
        shard_of = jnp.where(live, nxt // E_loc, n_shards)
        lrow = jnp.where(live, nxt - shard_of * E_loc, -1)

        send, fwd_drop_tx = _bucket_by_shard(
            shard_of, lrow, fl.size.reshape(-1), flat_fd,
            fl.corrupted.reshape(-1), live, n_shards, budget)

        # --- THE collective: one all_to_all replaces the per-packet RPC
        recv = jax.lax.all_to_all(send, EDGE_AXIS, split_axis=0,
                                  concat_axis=0, tiled=True)
        r = recv.reshape(-1, 4)                   # [n_shards*budget, 4]
        r_row = r[:, 0].astype(jnp.int32)
        r_live = r_row >= 0
        p_sz, p_dst, p_co, p_ok, fwd_drop_rx = _group_into_lanes(
            jnp.where(r_live, r_row, E_loc), r[:, 1],
            r[:, 2].astype(jnp.int32), r[:, 3] > 0.5, r_live, E_loc, k_fwd)

        counters = _add(
            sim.counters,
            tx_packets=valid.sum(axis=1).astype(jnp.float32),
            tx_bytes=sizes.sum(axis=1),
            rx_packets=due.sum(axis=1).astype(jnp.float32),
            rx_bytes=jnp.where(due, fl.size, 0.0).sum(axis=1),
            rx_corrupted=jnp.where(due, fl.corrupted, False).sum(
                axis=1).astype(jnp.float32),
            dropped_loss=res.dropped_loss.sum(axis=1).astype(jnp.float32),
            dropped_queue=res.dropped_queue.sum(axis=1).astype(jnp.float32),
            dropped_ring=dropped_ring,
            duplicated=res.duplicated.sum(axis=1).astype(jnp.float32),
            reordered=res.reordered.sum(axis=1).astype(jnp.float32),
        )

        edges = netem.roll_epoch.__wrapped__(edges, dt_us)
        sim2 = SimState(edges=edges, inflight=fl_after, counters=counters,
                        traffic=tstate, clock_us=sim.clock_us + dt_us)
        return RouterState(
            sim=sim2,
            next_edge=rs.next_edge,
            pend_size=jnp.where(p_ok, p_sz, 0.0),
            pend_dst=jnp.where(p_ok, p_dst, -1),
            pend_corr=p_co & p_ok,
            node_rx_packets=rs.node_rx_packets +
            jax.lax.psum(local_rx_p, EDGE_AXIS),
            node_rx_bytes=rs.node_rx_bytes +
            jax.lax.psum(local_rx_b, EDGE_AXIS),
            fwd_dropped=rs.fwd_dropped + jax.lax.psum(
                fwd_drop_tx + fwd_drop_rx, EDGE_AXIS),
            no_route_dropped=rs.no_route_dropped + jax.lax.psum(
                no_route.sum().astype(jnp.float32), EDGE_AXIS),
        )

    def rs_specs(rs_like: RouterState) -> RouterState:
        return _edge_specs(rs_like, n_shards)

    def make(rs_template: RouterState):
        specs = rs_specs(rs_template)
        mapped = shard_map(
            body, mesh=mesh,
            in_specs=(specs, spec_edge, P(EDGE_AXIS), P(), P()),
            out_specs=specs,
        )
        return jax.jit(mapped, donate_argnums=0)

    _cache: dict = {}

    def step(rs: RouterState, spec: TrafficSpec, flow_dst, key, dt_us):
        if "fn" not in _cache:
            _cache["fn"] = make(rs)
        return _cache["fn"](rs, spec, flow_dst, key,
                            jnp.float32(dt_us))

    return step


def shard_router_state(rs: RouterState, mesh) -> RouterState:
    """Place a host-built RouterState onto the mesh with the step's
    shardings (edge-dim leaves split, tables replicated)."""
    assert rs.next_edge.ndim == 2, (
        "sharded router forwards single-path tables; build ECMP groups "
        "with recompute_routes_ecmp for the local router only")
    specs = _edge_specs(rs, mesh.devices.size)

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, rs, specs)
