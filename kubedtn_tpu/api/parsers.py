"""Golden-parity parsers for link-property strings.

The reference parses user-facing property strings in Go
(reference common/qdisc.go:128-199); these functions reproduce that exact
semantics so a topology written for the reference behaves identically here:

- percentages:  float in [0, 100], "" -> 0            (qdisc.go:128-143)
- durations:    Go time.ParseDuration, truncated to whole microseconds,
                negative rejected, "" -> 0            (qdisc.go:145-158)
- rates:        integer + optional SI/IEC prefix + "bit"|"bps" suffix,
                "bps" multiplies by 8, "" -> 0        (qdisc.go:160-199)
- TBF burst:    max(rate/250, 5000) bytes             (qdisc.go:360-370)

The parsers are pure Python (control plane, runs once per link update); the
parsed numerics land in device arrays (see kubedtn_tpu.ops.edge_state).
"""

from __future__ import annotations

import math
import re

# TBF qdisc constants the reference hard-codes when installing the qdisc
# (tc invocation at reference common/qdisc.go:253-266).
TBF_LATENCY_US = 50_000  # "latency 50ms"
TBF_MINBURST = 1500  # "minburst 1500"

_GO_UNIT_NS = {
    "ns": 1,
    "us": 1_000,
    "µs": 1_000,  # µs (micro sign)
    "μs": 1_000,  # μs (greek mu)
    "ms": 1_000_000,
    "s": 1_000_000_000,
    "m": 60_000_000_000,
    "h": 3_600_000_000_000,
}

_DURATION_TOKEN = re.compile(r"(\d+(?:\.\d*)?|\.\d+)(ns|us|µs|μs|ms|s|m|h)")


def parse_percentage(value: str | None) -> float:
    """Percentage string -> float in [0, 100]; "" -> 0.

    Mirrors ParseFloatPercentage (reference common/qdisc.go:128-143): empty is
    zero, NaN and out-of-range rejected.
    """
    if not value:
        return 0.0
    try:
        v = float(value)
    except ValueError as e:
        raise ValueError(f"invalid percentage {value!r}: {e}") from None
    if math.isnan(v):
        raise ValueError("percentage value must be a number")
    if v < 0 or v > 100:
        raise ValueError("percentage value must be between 0 and 100")
    return v


def parse_duration_us(value: str | None) -> int:
    """Duration string -> whole microseconds; "" -> 0.

    Mirrors ParseDuration (reference common/qdisc.go:145-158), which delegates
    to Go time.ParseDuration then truncates to microseconds: a duration is one
    or more `<decimal><unit>` tokens ("1.5s", "1h2m", "300ms"), units
    ns/us/µs/ms/s/m/h; "0" alone is valid; negatives rejected.
    """
    if not value:
        return 0
    s = value.strip()
    neg = False
    if s and s[0] in "+-":
        neg = s[0] == "-"
        s = s[1:]
    if s == "0":
        return 0
    total_ns = 0
    pos = 0
    matched = False
    while pos < len(s):
        m = _DURATION_TOKEN.match(s, pos)
        if not m:
            raise ValueError(f"invalid duration {value!r}")
        matched = True
        total_ns += float(m.group(1)) * _GO_UNIT_NS[m.group(2)]
        pos = m.end()
    if not matched:
        raise ValueError(f"invalid duration {value!r}")
    if neg:
        raise ValueError("duration value must be positive")
    return int(total_ns) // 1_000


def parse_rate_bps(value: str | None) -> int:
    """Rate string -> bits per second; "" -> 0.

    Mirrors ParseRate (reference common/qdisc.go:160-199): lowercase, trim;
    strip "bit" (x1) or "bps" (x8) suffix; "i" selects IEC base 1024 over SI
    1000; k/m/g/t prefix gives base^1..4; the remainder must parse as an unsigned
    integer (decimals are rejected, exactly like Go strconv.ParseUint).
    Examples: "1000" -> 1000, "100kbit" -> 100_000, "100Mbps" -> 800_000_000,
    "1Gibps" -> 8*1024^3.
    """
    if value is None:
        return 0
    s = value.strip().lower()
    if not s:
        return 0

    mult = 1
    if s.endswith("bit"):
        s = s[: -len("bit")]
    elif s.endswith("bps"):
        s = s[: -len("bps")]
        mult = 8

    base = 1000
    if s.endswith("i"):
        s = s[:-1]
        base = 1024

    for i, unit in enumerate(("k", "m", "g", "t")):
        if s.endswith(unit):
            s = s[:-1]
            mult *= base ** (i + 1)
            break

    if not re.fullmatch(r"\d+", s):
        raise ValueError(f"invalid rate {value!r}")
    return int(s) * mult


def tbf_burst_bytes(rate_bps: int) -> int:
    """Token-bucket burst size for a given rate.

    Mirrors getTbfBurst (reference common/qdisc.go:360-370): at least
    rate/250 (kernel HZ), floored at 5000 bytes.
    """
    return max(rate_bps // 250, 5000)
