from kubedtn_tpu.api.parsers import (
    parse_duration_us,
    parse_percentage,
    parse_rate_bps,
    tbf_burst_bytes,
    TBF_LATENCY_US,
    TBF_MINBURST,
)
from kubedtn_tpu.api.types import (
    Link,
    LinkProperties,
    Topology,
    TopologySpec,
    TopologyStatus,
    links_equal_without_properties,
)

__all__ = [
    "parse_duration_us",
    "parse_percentage",
    "parse_rate_bps",
    "tbf_burst_bytes",
    "TBF_LATENCY_US",
    "TBF_MINBURST",
    "Link",
    "LinkProperties",
    "Topology",
    "TopologySpec",
    "TopologyStatus",
    "links_equal_without_properties",
]
