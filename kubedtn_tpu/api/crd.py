"""CRD rendering — the Topology CustomResourceDefinition as data.

The reference generates its CRD with kubebuilder from Go struct markers
(reference api/v1/topology_types.go:59-176; rendered in cni.yaml:14-280 and
config/crd/bases/). Here the CRD is rendered from the same source of truth
this framework validates against at load time: the dataclasses and regex
patterns in :mod:`kubedtn_tpu.api.types`. One definition, two consumers —
Python-side validation and the K8s apiserver schema — so they cannot drift.

`render_crd()` returns the manifest as a dict; `python -m kubedtn_tpu.cli
crd` prints it; the checked-in `config/crd/topologies.yaml` is its output
(regenerate with `make crd`).
"""

from __future__ import annotations

from typing import Any

from kubedtn_tpu import GROUP, VERSION
from kubedtn_tpu.api import types as T

PLURAL = "topologies"
CRD_NAME = f"{PLURAL}.{GROUP}"


def _percentage() -> dict[str, Any]:
    return {"type": "string", "pattern": T.PERCENTAGE_PATTERN.pattern}


def _duration() -> dict[str, Any]:
    return {"type": "string", "pattern": T.DURATION_PATTERN.pattern}


def link_properties_schema() -> dict[str, Any]:
    """OpenAPI v3 schema for LinkProperties — field-for-field with
    reference api/v1/topology_types.go:119-176 (defaults included)."""
    return {
        "type": "object",
        "description": "Emulated link properties applied to this link's "
                       "egress shaping (netem/tbf semantics).",
        "properties": {
            "latency": {**_duration(),
                        "description": "propagation delay, e.g. 10ms"},
            "latency_corr": {**_percentage(),
                             "description": "delay correlation percent"},
            "jitter": {**_duration(),
                       "description": "random delay variation, e.g. 1ms"},
            "loss": {**_percentage(),
                     "description": "random packet loss percent"},
            "loss_corr": _percentage(),
            "rate": {"type": "string", "pattern": T.RATE_PATTERN.pattern,
                     "description": "egress rate limit, e.g. 100Mbit"},
            "gap": {"type": "integer", "minimum": 0,
                    "description": "reorder gap (every Nth packet sent "
                                   "immediately when reordering)"},
            "duplicate": _percentage(),
            "duplicate_corr": _percentage(),
            "reorder_prob": _percentage(),
            "reorder_corr": _percentage(),
            "corrupt_prob": _percentage(),
            "corrupt_corr": _percentage(),
        },
    }


def _ip() -> dict[str, Any]:
    return {"type": "string", "pattern": T.IP_PATTERN.pattern}


def _mac() -> dict[str, Any]:
    return {"type": "string", "pattern": T.MAC_PATTERN.pattern}


def link_schema() -> dict[str, Any]:
    """Schema for one Link (reference api/v1/topology_types.go:59-95).

    Every sub-schema dict is freshly constructed (no shared objects), so
    yaml dumpers emit a plain manifest without anchors/aliases.
    """
    return {
        "type": "object",
        "required": ["local_intf", "peer_pod", "uid"],
        "properties": {
            "local_intf": {"type": "string",
                           "description": "interface name in the local pod"},
            "local_ip": _ip(),
            "local_mac": _mac(),
            "peer_intf": {"type": "string"},
            "peer_ip": _ip(),
            "peer_mac": _mac(),
            "peer_pod": {"type": "string",
                         "description": "peer pod name; 'localhost' for a "
                                        "macvlan link, 'physical/<ip>' for "
                                        "a physical-host link"},
            "uid": {"type": "integer",
                    "description": "cluster-unique link id (VNI = 5000+uid)"},
            "properties": link_properties_schema(),
        },
    }


def _links() -> dict[str, Any]:
    return {"type": "array", "items": link_schema()}


def topology_schema() -> dict[str, Any]:
    return {
        "type": "object",
        "properties": {
            "apiVersion": {"type": "string"},
            "kind": {"type": "string"},
            "metadata": {"type": "object"},
            "spec": {
                "type": "object",
                "description": "desired set of links for this pod",
                "properties": {"links": _links()},
            },
            "status": {
                "type": "object",
                "description": "observed state, written by the daemon "
                               "(placement) and reconciler (applied links)",
                "properties": {
                    "skipped": {"type": "array",
                                "items": {"type": "string"},
                                "description": "peers that were not alive "
                                               "at setup time"},
                    "src_ip": {"type": "string",
                               "description": "node IP of the pod's host"},
                    "net_ns": {"type": "string",
                               "description": "pod network-namespace path"},
                    "links": _links(),
                },
            },
        },
    }


def render_crd() -> dict[str, Any]:
    """The full CustomResourceDefinition manifest, apiextensions.k8s.io/v1."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": CRD_NAME},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": "Topology",
                "listKind": "TopologyList",
                "plural": PLURAL,
                "singular": "topology",
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "schema": {"openAPIV3Schema": topology_schema()},
                    # status is a subresource: meta/spec updates and status
                    # updates go through distinct endpoints, which is what
                    # makes the reference's CNI-vs-controller status race
                    # discipline work (reference api/clientset/v1beta1/
                    # topology.go:171-184; SURVEY.md §7 hard-part (f)).
                    "subresources": {"status": {}},
                }
            ],
        },
    }
