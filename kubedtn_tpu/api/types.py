"""Topology API types — parity schema with the reference CRD.

Mirrors the reference's Topology custom resource (reference
api/v1/topology_types.go:28-219): a Topology is one pod's view of its
point-to-point links; each Link carries local/peer interface names, optional
IP/MAC, a peer pod name, a cluster-unique uid, and shaping properties.

Field names and JSON keys are kept identical to the reference so its YAML
samples (reference config/samples/) load unmodified. Validation patterns are
the same kubebuilder regexes (topology_types.go:65-175).
"""

from __future__ import annotations

import functools
import re
from dataclasses import dataclass, field, asdict
from typing import Any, Iterable

from kubedtn_tpu.api.parsers import (
    parse_duration_us,
    parse_percentage,
    parse_rate_bps,
)

# kubebuilder validation patterns from the reference CRD
# (reference api/v1/topology_types.go:65,70,112,116,145).
IP_PATTERN = re.compile(
    r"^((([0-9]|[1-9][0-9]|1[0-9]{2}|2[0-4][0-9]|25[0-5])\.){3}"
    r"([0-9]|[1-9][0-9]|1[0-9]{2}|2[0-4][0-9]|25[0-5])"
    r"(\/(3[0-2]|[1-2][0-9]|[0-9]))?)?$"
)
MAC_PATTERN = re.compile(r"^(([0-9A-Fa-f]{2}[:-]){5}[0-9A-Fa-f]{2})?$")
PERCENTAGE_PATTERN = re.compile(r"^(100(\.0+)?|\d{1,2}(\.\d+)?)$")
DURATION_PATTERN = re.compile(r"^(\d+(\.\d+)?(ns|us|µs|μs|ms|s|m|h))+$")
RATE_PATTERN = re.compile(r"^\d+(\.\d+)?([KkMmGg]i?)?(bit|bps)?$")

# Sentinel peer names with special dispatch in the reference daemon:
# "localhost" selects a macvlan link (reference daemon/kubedtn/handler.go:333),
# "physical/<ip>" a link to a physical host (handler.go:348).
LOCALHOST = "localhost"
PHYSICAL_PREFIX = "physical/"


@dataclass(frozen=True)
class LinkProperties:
    """Emulated link properties (reference api/v1/topology_types.go:119-176).

    All string-typed fields keep the reference's string encodings (durations
    "10ms", percentages "25.5", rates "100Mbps"); `to_numeric` produces the
    parsed record that lands in device arrays.
    """

    latency: str = ""
    latency_corr: str = ""
    jitter: str = ""
    loss: str = ""
    loss_corr: str = ""
    rate: str = ""
    gap: int = 0
    duplicate: str = ""
    duplicate_corr: str = ""
    reorder_prob: str = ""
    reorder_corr: str = ""
    corrupt_prob: str = ""
    corrupt_corr: str = ""

    def validate(self) -> None:
        """Apply the CRD's kubebuilder validation patterns."""
        for name in (
            "latency_corr", "loss", "loss_corr", "duplicate", "duplicate_corr",
            "reorder_prob", "reorder_corr", "corrupt_prob", "corrupt_corr",
        ):
            v = getattr(self, name)
            if v and not PERCENTAGE_PATTERN.match(v):
                raise ValueError(f"invalid percentage for {name}: {v!r}")
        for name in ("latency", "jitter"):
            v = getattr(self, name)
            if v and not DURATION_PATTERN.match(v):
                raise ValueError(f"invalid duration for {name}: {v!r}")
        if self.rate and not RATE_PATTERN.match(self.rate):
            raise ValueError(f"invalid rate: {self.rate!r}")
        if self.gap < 0:
            raise ValueError("gap must be >= 0")

    def is_empty(self) -> bool:
        """True when no property is set (the reference skips qdisc creation
        entirely in that case — common/qdisc.go:24-26)."""
        return self == LinkProperties()

    def to_numeric(self) -> dict[str, float | int]:
        """Parse to the numeric record stored per edge on device.

        Same parse calls, in the same units, as MakeQdiscs (reference
        common/qdisc.go:20-126): durations to whole µs, percentages to floats
        in [0,100], rate to bits/sec.

        Memoized on the (frozen, hashable) instance: at 100k-link scale the
        same handful of property sets is parsed millions of times, and the
        string parsing dominated reconcile profiles.
        """
        return dict(_numeric_memo(self))

    @classmethod
    def from_dict(cls, d: dict[str, Any] | None) -> "LinkProperties":
        if not d:
            return cls()
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown LinkProperties fields: {sorted(unknown)}")
        return cls(**{k: d[k] for k in d})

    def to_dict(self) -> dict[str, Any]:
        out = {}
        for k, v in asdict(self).items():
            if v not in ("", 0):
                out[k] = v
        return out


@functools.lru_cache(maxsize=65536)
def _numeric_memo(props: "LinkProperties") -> tuple:
    """Cached parse of one LinkProperties value (frozen ⇒ hashable). Stored
    as an items-tuple so the cache entry itself is immutable; to_numeric
    hands each caller a fresh dict."""
    return (
        ("latency_us", parse_duration_us(props.latency)),
        ("latency_corr", parse_percentage(props.latency_corr)),
        ("jitter_us", parse_duration_us(props.jitter)),
        ("loss", parse_percentage(props.loss)),
        ("loss_corr", parse_percentage(props.loss_corr)),
        ("rate_bps", parse_rate_bps(props.rate)),
        ("gap", int(props.gap)),
        ("duplicate", parse_percentage(props.duplicate)),
        ("duplicate_corr", parse_percentage(props.duplicate_corr)),
        ("reorder_prob", parse_percentage(props.reorder_prob)),
        ("reorder_corr", parse_percentage(props.reorder_corr)),
        ("corrupt_prob", parse_percentage(props.corrupt_prob)),
        ("corrupt_corr", parse_percentage(props.corrupt_corr)),
    )


@dataclass(frozen=True)
class Link:
    """One p2p link from the local pod's perspective
    (reference api/v1/topology_types.go:59-95)."""

    local_intf: str
    peer_intf: str
    peer_pod: str
    uid: int
    local_ip: str = ""
    peer_ip: str = ""
    local_mac: str = ""
    peer_mac: str = ""
    properties: LinkProperties = field(default_factory=LinkProperties)

    def with_properties(self, properties: "LinkProperties") -> "Link":
        """Copy of this link with different properties — the hot spec-edit
        operation (UpdateLinks churn touches every link). ~4× faster than
        dataclasses.replace, which re-runs field resolution per call;
        identity fields are shared, so calc_diff still matches by key."""
        new = object.__new__(Link)
        new.__dict__.update(self.__dict__)
        new.__dict__["properties"] = properties
        return new

    def validate(self) -> None:
        for name in ("local_ip", "peer_ip"):
            v = getattr(self, name)
            if not IP_PATTERN.match(v):
                raise ValueError(f"invalid IP for {name}: {v!r}")
        for name in ("local_mac", "peer_mac"):
            v = getattr(self, name)
            if not MAC_PATTERN.match(v):
                raise ValueError(f"invalid MAC for {name}: {v!r}")
        self.properties.validate()

    def is_macvlan(self) -> bool:
        return self.peer_pod == LOCALHOST

    def is_physical(self) -> bool:
        return self.peer_pod.startswith(PHYSICAL_PREFIX)

    def physical_peer_ip(self) -> str:
        return self.peer_pod[len(PHYSICAL_PREFIX):]

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Link":
        return cls(
            local_intf=d["local_intf"],
            peer_intf=d.get("peer_intf", ""),
            peer_pod=d["peer_pod"],
            uid=int(d["uid"]),
            local_ip=d.get("local_ip", ""),
            peer_ip=d.get("peer_ip", ""),
            local_mac=d.get("local_mac", ""),
            peer_mac=d.get("peer_mac", ""),
            properties=LinkProperties.from_dict(d.get("properties")),
        )

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "local_intf": self.local_intf,
            "peer_intf": self.peer_intf,
            "peer_pod": self.peer_pod,
            "uid": self.uid,
        }
        for k in ("local_ip", "peer_ip", "local_mac", "peer_mac"):
            v = getattr(self, k)
            if v:
                out[k] = v
        props = self.properties.to_dict()
        if props:
            out["properties"] = props
        return out


def links_equal_without_properties(a: Link, b: Link) -> bool:
    """Identity comparison ignoring shaping properties — the reconciler's
    notion of "same link" (reference controllers/topology_controller.go:342-351)."""
    return (
        a.local_intf == b.local_intf
        and a.local_ip == b.local_ip
        and a.local_mac == b.local_mac
        and a.peer_intf == b.peer_intf
        and a.peer_ip == b.peer_ip
        and a.peer_mac == b.peer_mac
        and a.peer_pod == b.peer_pod
        and a.uid == b.uid
    )


@dataclass
class TopologySpec:
    """Desired state (reference api/v1/topology_types.go:28-34)."""

    links: list[Link] = field(default_factory=list)

    def clone(self) -> "TopologySpec":
        """List copy; Link objects are immutable and shared."""
        return TopologySpec(links=list(self.links))


@dataclass
class TopologyStatus:
    """Observed state (reference api/v1/topology_types.go:37-56).

    `links` is None (not empty list) until first reconcile — the reconciler's
    "first-seen" rule keys off that distinction
    (reference controllers/topology_controller.go:81-85).
    """

    skipped: list[str] = field(default_factory=list)
    src_ip: str = ""
    net_ns: str = ""
    links: list[Link] | None = None

    def clone(self) -> "TopologyStatus":
        """List copies; Link objects are immutable and shared."""
        return TopologyStatus(
            skipped=list(self.skipped),
            src_ip=self.src_ip,
            net_ns=self.net_ns,
            links=list(self.links) if self.links is not None else None,
        )


@dataclass
class Topology:
    """One pod's topology resource (reference api/v1/topology_types.go:200-206)."""

    name: str
    namespace: str = "default"
    spec: TopologySpec = field(default_factory=TopologySpec)
    status: TopologyStatus = field(default_factory=TopologyStatus)
    finalizers: list[str] = field(default_factory=list)
    resource_version: int = 0
    deletion_requested: bool = False

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "Topology":
        """Structural copy exploiting Link/LinkProperties immutability
        (both are frozen dataclasses): lists are copied, Link objects are
        SHARED. Equivalent to deepcopy for every supported mutation
        (callers replace Links, never mutate them) at a fraction of the
        cost — the store clones on every read/write, and generic deepcopy
        dominated reconcile profiles at 100k links."""
        return Topology(
            name=self.name,
            namespace=self.namespace,
            spec=self.spec.clone(),
            status=self.status.clone(),
            finalizers=list(self.finalizers),
            resource_version=self.resource_version,
            deletion_requested=self.deletion_requested,
        )

    def is_alive(self) -> bool:
        """A pod is alive when placement is known (reference
        daemon/kubedtn/handler.go:99,386)."""
        return bool(self.status.src_ip) and bool(self.status.net_ns)

    def validate(self) -> None:
        seen: set[tuple[str, int]] = set()
        for link in self.spec.links:
            link.validate()
            k = (link.local_intf, link.uid)
            if k in seen:
                raise ValueError(
                    f"duplicate (local_intf, uid) in {self.name}: {k}"
                )
            seen.add(k)

    @classmethod
    def from_manifest(cls, d: dict[str, Any]) -> "Topology":
        """Build from a K8s-style manifest dict (apiVersion/kind/metadata/spec),
        the format of the reference's samples (reference config/samples/3node.yml)."""
        meta = d.get("metadata", {})
        spec = d.get("spec", {}) or {}
        links = [Link.from_dict(x) for x in (spec.get("links") or [])]
        status_d = d.get("status") or {}
        status = TopologyStatus(
            skipped=list(status_d.get("skipped") or []),
            src_ip=status_d.get("src_ip", ""),
            net_ns=status_d.get("net_ns", ""),
            links=(
                [Link.from_dict(x) for x in status_d["links"]]
                if status_d.get("links") is not None
                else None
            ),
        )
        return cls(
            name=meta["name"],
            namespace=meta.get("namespace", "default"),
            spec=TopologySpec(links=links),
            status=status,
        )

    def to_manifest(self) -> dict[str, Any]:
        from kubedtn_tpu import GROUP_VERSION

        d: dict[str, Any] = {
            "apiVersion": GROUP_VERSION,
            "kind": "Topology",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {"links": [l.to_dict() for l in self.spec.links]},
        }
        status: dict[str, Any] = {}
        if self.status.skipped:
            status["skipped"] = list(self.status.skipped)
        if self.status.src_ip:
            status["src_ip"] = self.status.src_ip
        if self.status.net_ns:
            status["net_ns"] = self.status.net_ns
        if self.status.links is not None:
            status["links"] = [l.to_dict() for l in self.status.links]
        if status:
            d["status"] = status
        return d


def load_manifests(docs: Iterable[dict[str, Any]]) -> list[Topology]:
    """Extract Topology resources from a stream of K8s manifests, unwrapping
    v1 Lists — accepts the reference's sample files as-is."""
    out: list[Topology] = []
    for doc in docs:
        if not doc:
            continue
        kind = doc.get("kind", "")
        if kind == "List":
            out.extend(load_manifests(doc.get("items", [])))
        elif kind == "Topology":
            out.append(Topology.from_manifest(doc))
    return out


def load_yaml(path_or_text: str) -> list[Topology]:
    """Load Topology resources from a YAML file path or YAML text."""
    import os

    import yaml

    if os.path.exists(path_or_text):
        with open(path_or_text) as f:
            text = f.read()
    else:
        text = path_or_text
    return load_manifests(yaml.safe_load_all(text))
