"""Pause & stall observability plane — barrier-pause attribution.

BENCH_host_scale is honest that compact() and checkpoint_save are
whole-plane stop-the-world passes, and ROADMAP.md names them the
availability ceiling for constellation churn — but in a *running*
daemon those pauses were invisible: no metric, no trace span on the
tick path, no way to answer "why did tick latency spike at 14:02".
This module is the measurement substrate the incremental
checkpoint/compact refactor will be judged against.

The contract is a lock-cheap `PauseLedger` every tick-lock barrier
site reports into:

- **Cause taxonomy** (`CAUSES`): checkpoint save/load, compact,
  staged updates, migration fork/restore/cutover, pipeline flush, shm
  batch-dequeue stalls, jit recompiles (compile seconds per shape
  bucket), and GC pauses. Each event carries its cause, duration, and
  whatever detail the site knows — rows/bytes touched, the
  tenant/plan/migration id that triggered it.
- **Per-cause aggregates**: count / seconds / max / last, plus summed
  rows and bytes, under one short-hold lock. A bounded event ring
  keeps the most recent occurrences for `kdt pauses` and the wire
  `ObservePauses` query; overflow is counted, never silent
  (`dropped_events`), matching the telemetry ring's contract.
- **Tick-latency-by-cause histograms**: the data plane times every
  public `tick()` around the tick-lock acquisition (so lock-wait
  behind a barrier holder is included) and calls `note_tick(dur_s)`;
  the ledger attributes that tick's wall latency to the DOMINANT cause
  among pauses recorded since the previous tick ("none" when the tick
  was clean) and accumulates per-cause histograms on the reference
  bucket ladder (metrics.BUCKETS, ms → seconds edges). This is the
  feed for `kubedtn_tick_latency_seconds{cause}`.
- **Tracer streaming**: every `pause()` context also opens a
  `pause:<cause>` span on the process tracer, so `--trace-out`
  Perfetto dumps show barriers on the tick timeline next to the
  reconcile/checkpoint spans that caused them.
- **A/B switch**: `enabled=False` turns every hook into a
  near-zero-cost branch — the `pause_observability` bench phase
  measures the on/off delta on the plane-only probe and holds it
  under 2% (the `savail` budget's `hook_overhead_pct`).

Thread model: `record()`/`pause()` may be called from any thread (GC
callbacks land on whoever triggered collection); `note_tick()` is
tick-thread only. One plain Lock, held for dict arithmetic only —
never across a barrier, an allocation burst, or a device sync.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque

from kubedtn_tpu.metrics.metrics import BUCKETS

# Canonical cause taxonomy — every barrier site reports one of these.
# (An unknown cause is still recorded — the metrics cardinality cap and
# the savail "unbudgeted cause" check are the guards — but sites should
# stay on-taxonomy so budgets and docs line up.)
CAUSES = (
    "checkpoint_save",
    "checkpoint_load",
    "compact",
    "staged_update",
    "migration_fork",
    "migration_restore",
    "migration_cutover",
    "pipeline_flush",
    "shm_stall",
    "jit_compile",
    "gc",
)

# Tick-latency bucket upper edges in SECONDS — the reference daemon's
# request-duration ladder (metrics.BUCKETS, milliseconds) rescaled, one
# overflow bin at the end.
TICK_EDGES_S = tuple(float(b) / 1000.0 for b in BUCKETS[1:])
N_TICK_BINS = len(TICK_EDGES_S) + 1


class PauseLedger:
    """Thread-safe per-cause pause accounting (see module docstring)."""

    def __init__(self, max_events: int = 2048, tracer=None,
                 enabled: bool = True) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        # cause -> [count, seconds, max_s, last_s, last_t, rows, bytes]
        self._agg: dict[str, list[float]] = {}
        self._events: deque[dict] = deque(maxlen=max_events)
        self.dropped_events = 0
        # cause -> seconds since the last note_tick() — the attribution
        # window for tick-latency-by-cause
        self._since_tick: dict[str, float] = {}
        # cause -> [N_TICK_BINS] bucket counts (+ count/sum for the
        # Prometheus histogram exposition)
        self._tick_hist: dict[str, list[int]] = {}
        self._tick_count: dict[str, int] = {}
        self._tick_sum: dict[str, float] = {}
        self._tracer = tracer
        self._t0 = time.monotonic()

    # -- recording ------------------------------------------------------

    @contextlib.contextmanager
    def pause(self, cause: str, **detail):
        """Time a barrier region and record it under `cause`.

        Detail keys are free-form; `rows=` and `bytes=` feed the
        per-cause touched totals, ids (tenant/plan/migration) ride the
        event ring. The span lands via record() below.
        """
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(cause, time.perf_counter() - t0, **detail)

    def record(self, cause: str, dur_s: float, **detail) -> None:
        """Record one timed pause (any thread: the pause() exit path,
        the GC callback, a site that measured its own region). Streams
        a `pause:<cause>` span onto the process tracer so `--trace-out`
        Perfetto dumps show the barrier on the tick timeline."""
        if not self.enabled:
            return
        dur_s = float(dur_s)
        now = time.monotonic() - self._t0
        rows = float(detail.get("rows", 0) or 0)
        nbytes = float(detail.get("bytes", 0) or 0)
        with self._lock:
            a = self._agg.get(cause)
            if a is None:
                a = self._agg[cause] = [0.0, 0.0, 0.0, 0.0, 0.0,
                                        0.0, 0.0]
            a[0] += 1.0
            a[1] += dur_s
            if dur_s > a[2]:
                a[2] = dur_s
            a[3] = dur_s
            a[4] = now
            a[5] += rows
            a[6] += nbytes
            self._since_tick[cause] = \
                self._since_tick.get(cause, 0.0) + dur_s
            if len(self._events) == self._events.maxlen:
                self.dropped_events += 1
            ev = {"cause": cause, "dur_s": round(dur_s, 6),
                  "t_s": round(now, 3)}
            if detail:
                ev.update({k: v for k, v in detail.items()
                           if v is not None})
            self._events.append(ev)
        tracer = self._tracer
        if tracer is None:
            from kubedtn_tpu.utils.tracing import default_tracer
            tracer = self._tracer = default_tracer()
        tracer.add_span(f"pause:{cause}", dur_s, **detail)

    def note_tick(self, dur_s: float) -> None:
        """Attribute one tick's wall latency (lock-wait included) to
        the dominant cause recorded since the previous tick, and fold
        it into that cause's latency histogram. Tick thread only."""
        if not self.enabled:
            return
        with self._lock:
            if self._since_tick:
                cause = max(self._since_tick,
                            key=self._since_tick.get)
                self._since_tick.clear()
            else:
                cause = "none"
            h = self._tick_hist.get(cause)
            if h is None:
                h = self._tick_hist[cause] = [0] * N_TICK_BINS
                self._tick_count[cause] = 0
                self._tick_sum[cause] = 0.0
            i = 0
            for edge in TICK_EDGES_S:
                if dur_s <= edge:
                    break
                i += 1
            h[i] += 1
            self._tick_count[cause] += 1
            self._tick_sum[cause] += dur_s

    # -- readouts -------------------------------------------------------

    def causes(self) -> dict[str, dict[str, float]]:
        """Per-cause aggregate snapshot, one lock hold. Shape:
        {cause: {count, seconds, max_s, last_s, last_t_s, rows,
        bytes}}."""
        with self._lock:
            return {
                c: {"count": int(a[0]), "seconds": a[1], "max_s": a[2],
                    "last_s": a[3], "last_t_s": a[4],
                    "rows": int(a[5]), "bytes": int(a[6])}
                for c, a in self._agg.items()
            }

    def events(self, n: int = 50) -> list[dict]:
        """The most recent `n` events, oldest first."""
        with self._lock:
            evs = list(self._events)
        return evs[-n:] if n >= 0 else evs

    def tick_hist(self) -> dict[str, dict]:
        """Per-cause tick-latency histograms: {cause: {buckets: [...],
        count, sum_s}} on the TICK_EDGES_S ladder."""
        with self._lock:
            return {
                c: {"buckets": list(h), "count": self._tick_count[c],
                    "sum_s": self._tick_sum[c]}
                for c, h in self._tick_hist.items()
            }

    def snapshot(self) -> dict:
        """Everything the wire/metrics/bench surfaces consume, in one
        consistent read: aggregates, histograms, uptime, ring health."""
        with self._lock:
            causes = {
                c: {"count": int(a[0]), "seconds": round(a[1], 6),
                    "max_s": round(a[2], 6), "last_s": round(a[3], 6),
                    "last_t_s": round(a[4], 3),
                    "rows": int(a[5]), "bytes": int(a[6])}
                for c, a in self._agg.items()
            }
            hist = {
                c: {"buckets": list(h), "count": self._tick_count[c],
                    "sum_s": round(self._tick_sum[c], 6)}
                for c, h in self._tick_hist.items()
            }
            dropped = self.dropped_events
        return {
            "enabled": self.enabled,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "causes": causes,
            "tick_hist": hist,
            "tick_edges_s": list(TICK_EDGES_S),
            "dropped_events": dropped,
        }

    def total_pause_s(self) -> float:
        with self._lock:
            return sum(a[1] for a in self._agg.values())

    def reset(self) -> None:
        with self._lock:
            self._agg.clear()
            self._events.clear()
            self._since_tick.clear()
            self._tick_hist.clear()
            self._tick_count.clear()
            self._tick_sum.clear()
            self.dropped_events = 0
            self._t0 = time.monotonic()
