"""RouterSim — multi-hop packet forwarding over the simulated fabric.

Composes the data plane (kubedtn_tpu.sim) with the routing kernels
(kubedtn_tpu.ops.routing): packets carry a final destination node; when a
packet is delivered out of an edge whose far end is not its destination, it
re-enters the fabric on that node's next-hop edge in the following step.
This is the piece the reference delegates to real routing daemons running
inside pods over its emulated links — here the whole forwarding plane is
device arrays.

Forwarding is static-shape: every step, due packets are grouped by their
next-hop edge with a sort + segmented-rank, then scattered into at most
`k_fwd` re-injection lanes per edge (excess packets drop and are counted,
like a router's input-queue overflow).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from kubedtn_tpu.models.traffic import TrafficSpec, generate
from kubedtn_tpu.ops import netem
from kubedtn_tpu.ops.queues import insert_inflight, pop_due
from kubedtn_tpu.ops.queues import shape_packets
from kubedtn_tpu.sim import SimState, _add, init_sim


@dataclasses.dataclass(frozen=True)
class RouterState:
    """Forwarding-plane state carried between steps."""

    sim: SimState
    # i32[n, n] single-path routing table (edge rows), or i32[n, n, K]
    # ECMP next-hop groups from recompute_routes_ecmp (-1 padded): flows
    # hash across the group per (ingress edge, destination)
    next_edge: jax.Array
    pend_size: jax.Array       # f32[E, Kf] packets awaiting re-injection
    pend_dst: jax.Array        # i32[E, Kf] their final destinations
    pend_corr: jax.Array       # bool[E, Kf]
    node_rx_packets: jax.Array  # f32[n] packets that reached their dest
    node_rx_bytes: jax.Array    # f32[n]
    fwd_dropped: jax.Array      # f32[] packets lost to forwarding overflow
    no_route_dropped: jax.Array  # f32[] packets with no route to dest


jax.tree_util.register_dataclass(
    RouterState,
    data_fields=[f.name for f in dataclasses.fields(RouterState)],
    meta_fields=[],
)


def init_router(edges, next_edge: jax.Array, n_nodes: int, q: int = 32,
                k_fwd: int = 8) -> RouterState:
    sim = init_sim(edges, q=q)
    E = edges.capacity
    return RouterState(
        sim=sim,
        next_edge=next_edge,
        pend_size=jnp.zeros((E, k_fwd), jnp.float32),
        pend_dst=jnp.full((E, k_fwd), -1, jnp.int32),
        pend_corr=jnp.zeros((E, k_fwd), dtype=bool),
        node_rx_packets=jnp.zeros((n_nodes,), jnp.float32),
        node_rx_bytes=jnp.zeros((n_nodes,), jnp.float32),
        fwd_dropped=jnp.zeros((), jnp.float32),
        no_route_dropped=jnp.zeros((), jnp.float32),
    )


def _group_into_lanes(target: jax.Array, size: jax.Array, fdst: jax.Array,
                      corr: jax.Array, live: jax.Array, E: int, k_fwd: int):
    """Scatter flat packets into per-edge lanes [E, k_fwd].

    target: i32[M] destination edge row per packet (E = drop).
    Returns (size[E,Kf], dst[E,Kf], corr[E,Kf], valid[E,Kf], dropped count).
    """
    M = target.shape[0]
    tgt = jnp.where(live, target, E)
    order = jnp.argsort(tgt)
    tgt_s = tgt[order]
    # segmented rank: position within each equal-target run
    idx = jnp.arange(M)
    starts = jnp.concatenate([jnp.array([True]), tgt_s[1:] != tgt_s[:-1]])
    start_idx = jax.lax.associative_scan(
        jnp.maximum, jnp.where(starts, idx, 0))
    rank = idx - start_idx

    ok = (tgt_s < E) & (rank < k_fwd)
    row = jnp.where(ok, tgt_s, E)
    lane = jnp.where(ok, rank, 0)

    out_sz = jnp.zeros((E + 1, k_fwd), jnp.float32)
    out_dst = jnp.full((E + 1, k_fwd), -1, jnp.int32)
    out_co = jnp.zeros((E + 1, k_fwd), dtype=bool)
    out_ok = jnp.zeros((E + 1, k_fwd), dtype=bool)

    out_sz = out_sz.at[row, lane].set(size[order], mode="drop")[:E]
    out_dst = out_dst.at[row, lane].set(fdst[order], mode="drop")[:E]
    out_co = out_co.at[row, lane].set(corr[order], mode="drop")[:E]
    out_ok = out_ok.at[row, lane].set(ok, mode="drop")[:E]

    dropped = ((tgt_s < E) & (rank >= k_fwd)).sum().astype(jnp.float32)
    return out_sz, out_dst, out_co, out_ok, dropped


@partial(jax.jit, static_argnums=(4, 5), donate_argnums=0)
def router_step(rs: RouterState, spec: TrafficSpec, flow_dst: jax.Array,
                key: jax.Array, k_slots: int, k_fwd: int, dt_us: jax.Array):
    """One routed data-plane step.

    `flow_dst` (i32[E]) gives the final-destination node of the host flow
    sourced on each edge; entries < 0 default to the edge's own far end
    (single-hop). Pending lanes are forwarded packets re-entering mid-path.
    """
    kg, ks = jax.random.split(key)

    # 1. traffic + pending-forward arrivals
    tstate, sizes_t, valid_t, t_arr_t = generate(spec, rs.sim.traffic,
                                                 dt_us, k_slots, kg)
    return _finish_router_step(rs, spec, flow_dst, tstate, sizes_t,
                               valid_t, t_arr_t, ks, k_fwd, dt_us)


def _finish_router_step(rs: RouterState, spec: TrafficSpec,
                        flow_dst: jax.Array, tstate, sizes_t, valid_t,
                        t_arr_t, ks, k_fwd: int, dt_us: jax.Array):
    """Everything after traffic generation — split out so the what-if
    twin engine (kubedtn_tpu.twin.engine) can hoist the replica-
    independent `generate` out of its vmap (traffic evolution never
    reads edge state, so one unbatched call per step serves every
    replica and keeps replica 0 bit-identical to `run_routed`)."""
    sim = rs.sim
    E = sim.edges.capacity
    valid_t = valid_t & sim.edges.active[:, None]
    sizes_t = jnp.where(valid_t, sizes_t, 0.0)  # keep byte counters honest
    fd = jnp.where(flow_dst >= 0, flow_dst, sim.edges.dst)
    fdst_t = jnp.broadcast_to(fd[:, None], sizes_t.shape)

    valid_p = rs.pend_dst >= 0
    sizes = jnp.concatenate([sizes_t, rs.pend_size], axis=1)
    valid = jnp.concatenate([valid_t, valid_p], axis=1)
    t_arr = jnp.concatenate(
        [t_arr_t, jnp.zeros_like(rs.pend_size)], axis=1)
    fdst_in = jnp.concatenate([fdst_t, rs.pend_dst], axis=1)

    # 2. shape through the qdisc chain
    edges, res = shape_packets(sim.edges, sizes, valid, t_arr, ks)

    # 3. into the delay lines (duplicates share the original's departure).
    #    A packet corrupted on ANY hop stays corrupted: carry the pending
    #    lanes' flag through this hop's result.
    corr_in = jnp.concatenate(
        [jnp.zeros_like(valid_t), rs.pend_corr & valid_p], axis=1)
    corr_now = res.corrupted | (corr_in & res.delivered)
    dep_all = jnp.concatenate([res.depart_us, res.depart_us], axis=1)
    sz_all = jnp.concatenate([sizes, sizes], axis=1)
    co_all = jnp.concatenate([corr_now, corr_now], axis=1)
    fd_all = jnp.concatenate([fdst_in, fdst_in], axis=1)
    deliver_all = jnp.concatenate(
        [res.delivered, res.delivered & res.duplicated], axis=1)
    fl, dropped_ring = insert_inflight(
        sim.inflight, dep_all, sz_all, fd_all, co_all, deliver_all)

    # 4. deliveries due this step
    fl_after, due = pop_due(fl, dt_us)
    here = jnp.broadcast_to(sim.edges.dst[:, None], due.shape)
    at_dest = due & (fl.final_dst == here)
    in_transit = due & ~at_dest

    # 4a. final deliveries -> per-node counters
    node_rx_p = rs.node_rx_packets.at[
        jnp.where(at_dest, here, rs.node_rx_packets.shape[0])
    ].add(1.0, mode="drop")
    node_rx_b = rs.node_rx_bytes.at[
        jnp.where(at_dest, here, rs.node_rx_bytes.shape[0])
    ].add(jnp.where(at_dest, fl.size, 0.0), mode="drop")

    # 4b. transit packets -> next-hop edge, re-inject next step
    flat_here = here.reshape(-1)
    flat_fd = fl.final_dst.reshape(-1)
    flat_live = in_transit.reshape(-1)
    safe_here = jnp.where(flat_live, flat_here, 0)
    safe_fd = jnp.where(flat_live, jnp.maximum(flat_fd, 0), 0)
    if rs.next_edge.ndim == 3:
        # ECMP: hash (ingress edge, destination) onto the next-hop group —
        # per-ingress path stickiness, the way hardware ECMP hashes header
        # fields onto a group (table built by recompute_routes_ecmp)
        group = rs.next_edge[safe_here, safe_fd]           # [M, K]
        cnt = (group >= 0).sum(axis=-1)
        ing = jnp.broadcast_to(
            jnp.arange(E, dtype=jnp.uint32)[:, None], due.shape).reshape(-1)
        h = (ing * jnp.uint32(2654435761)
             + safe_fd.astype(jnp.uint32) * jnp.uint32(40503))
        k_idx = (h % jnp.maximum(cnt, 1).astype(jnp.uint32)).astype(jnp.int32)
        nxt = jnp.take_along_axis(group, k_idx[:, None], axis=-1)[:, 0]
        nxt = jnp.where(cnt > 0, nxt, -1)
    else:
        nxt = rs.next_edge[safe_here, safe_fd]
    no_route = flat_live & (nxt < 0)
    target = jnp.where(flat_live & (nxt >= 0), nxt, E)
    p_sz, p_dst, p_co, p_ok, fwd_drop = _group_into_lanes(
        target, fl.size.reshape(-1), flat_fd, fl.corrupted.reshape(-1),
        flat_live & (nxt >= 0), E, k_fwd)

    counters = _add(
        sim.counters,
        tx_packets=valid.sum(axis=1).astype(jnp.float32),
        tx_bytes=sizes.sum(axis=1),
        rx_packets=due.sum(axis=1).astype(jnp.float32),
        rx_bytes=jnp.where(due, fl.size, 0.0).sum(axis=1),
        rx_corrupted=jnp.where(due, fl.corrupted, False).sum(
            axis=1).astype(jnp.float32),
        dropped_loss=res.dropped_loss.sum(axis=1).astype(jnp.float32),
        dropped_queue=res.dropped_queue.sum(axis=1).astype(jnp.float32),
        dropped_ring=dropped_ring,
        duplicated=res.duplicated.sum(axis=1).astype(jnp.float32),
        reordered=res.reordered.sum(axis=1).astype(jnp.float32),
    )

    edges = netem.roll_epoch.__wrapped__(edges, dt_us)
    sim2 = SimState(edges=edges, inflight=fl_after, counters=counters,
                    traffic=tstate, clock_us=sim.clock_us + dt_us)
    rs2 = RouterState(
        sim=sim2,
        next_edge=rs.next_edge,
        pend_size=jnp.where(p_ok, p_sz, 0.0),
        pend_dst=jnp.where(p_ok, p_dst, -1),
        pend_corr=p_co & p_ok,
        node_rx_packets=node_rx_p,
        node_rx_bytes=node_rx_b,
        fwd_dropped=rs.fwd_dropped + fwd_drop,
        no_route_dropped=rs.no_route_dropped +
        no_route.sum().astype(jnp.float32),
    )
    return rs2


@partial(jax.jit, static_argnums=(4, 5))
def _run_scan(rs, spec, flow_dst, keys, k_slots, k_fwd, dt):
    """Module-level so repeated run_routed calls with the same shapes hit
    the jit cache — a per-call closure recompiled the whole scan every
    invocation (measured 76s → 22s on the chaos scenario's ~10 runs)."""

    def body(s, k):
        return router_step.__wrapped__(s, spec, flow_dst, k, k_slots,
                                       k_fwd, dt), None

    s, _ = jax.lax.scan(body, rs, keys)
    return s


def run_routed(rs: RouterState, spec: TrafficSpec, flow_dst, steps: int,
               dt_us: float, k_slots: int = 4, k_fwd: int = 8, seed: int = 0
               ) -> RouterState:
    keys = jax.random.split(jax.random.key(seed), steps)
    return _run_scan(rs, spec, flow_dst, keys, k_slots, k_fwd,
                     jnp.float32(dt_us))
