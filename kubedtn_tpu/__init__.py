"""kubedtn_tpu — a TPU-native digital-twin-network framework.

Same capabilities as the reference (dtn-dslab/kube-dtn): declarative Topology
graphs of point-to-point links with emulated properties (latency, jitter, loss,
rate, reorder, corrupt, duplicate), reconciled from spec to steady state with a
live data plane — but realized as batched edge-state arrays on TPU
(JAX/XLA/pallas) instead of Linux kernel state (veth/VXLAN/netem/tbf/eBPF).

Layer map (mirrors reference SURVEY.md §1, re-architected TPU-first):

    L5  api/        Topology schema + golden-parity parsers  (ref: api/v1/)
    L4  topology/   store + reconciler                       (ref: controllers/)
    L3  wire/       gRPC control plane + engine facade       (ref: daemon/kubedtn/)
    L2  ops/        edge-state arrays, shaping & queue kernels
                                            (ref: common/qdisc.go, daemon/vxlan|grpcwire, bpf/)
    L1  parallel/   device mesh, shard_map, collectives      (ref: kernel/netlink)

Everything per-link the reference does with netlink/tc becomes a row in
structure-of-arrays edge state advanced by vmapped / shard_map-sharded kernels.
"""

__version__ = "0.1.0"

# Group/version identity kept parity-compatible with the reference CRD
# (ref: api/v1/groupversion_info.go:28-36).
GROUP = "y-young.github.io"
VERSION = "v1"
GROUP_VERSION = f"{GROUP}/{VERSION}"
