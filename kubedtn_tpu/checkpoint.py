"""Checkpoint / resume + elastic recovery.

The reference has no snapshot files: its durable state is the Topology CR
(Status.Links = last applied, Status.SrcIP/NetNs = placement) plus the
kernel devices themselves, and crash recovery is *reconstruction* — a
restarted daemon re-lists topologies and rescans pod netnses to rebuild its
managers (reference daemon/kubedtn/kubedtn.go:107-121,
daemon/vxlan/manager.go:25-55; SURVEY.md §5.3-5.4). This module provides
both halves for the TPU build:

- `rebuild_engine` — the reconstruction path: given only the store (the CR
  source of truth), re-derive the whole device-array realization, exactly
  like a daemon restart. Device arrays are rebuildable projections.
- `save` / `load` — a real checkpoint: store contents + engine registries
  as JSON, device arrays as npz. Restoring short-circuits reconstruction
  (no re-plumbing) and preserves mutable shaping state (token buckets,
  correlation memory, counters) that reconstruction would reset — the same
  distinction as kernel qdiscs surviving a daemon restart vs being
  reinstalled.

Crash consistency (round 7): `save` stages the whole checkpoint in a
temp directory beside the target — manifest carrying a sha256 per data
file — fsyncs it, then swaps it into place with atomic renames
(old → `<path>.prev`, tmp → path). A `kill -9` at ANY instant leaves
either the new complete checkpoint, the previous complete one (found at
path or recovered from `.prev`), or nothing valid — never a torn mix of
generations; rewriting the directory wholesale also means a re-save can
never leak an earlier generation's `pending_frames.npz`/`sim_state.npz`
into a later restore. `load`/`load_pending`/`load_sim` verify the
checksums and raise TYPED errors (`CheckpointCorruptError`) on any
damage; `load_or_rebuild` turns that into the reference's reconstruction
fallback instead of dying mid-restore.

Layout of a checkpoint directory:
  manifest.json   — versioned metadata + engine registries + store
                    records + per-file sha256 checksums
  edge_state.npz  — EdgeState arrays
  sim_state.npz   — optional SimState arrays (inflight/counters/traffic)
  pending_frames.npz — optional in-flight delay-line frames
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import shutil

import jax.numpy as jnp
import numpy as np

from kubedtn_tpu.api.types import Topology
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.topology.engine import SimEngine
from kubedtn_tpu.topology.store import TopologyStore

FORMAT_VERSION = 2  # 2: per-file checksums + atomic directory swap

_PREV_SUFFIX = ".prev"
_TMP_PREFIX = ".ckpt-tmp-"


class CheckpointError(Exception):
    """A checkpoint could not be used (missing, wrong version, ...)."""


class CheckpointMissingError(CheckpointError):
    """No checkpoint exists at the path (a fresh daemon's first start)
    — distinct from damage or an unsupported format, which callers must
    surface rather than silently cold-start over."""


class CheckpointCorruptError(CheckpointError):
    """A checkpoint exists but is damaged: truncated/unparseable
    manifest, unreadable npz, or a checksum mismatch. The documented
    recovery is `rebuild_engine` from the store (`load_or_rebuild`)."""


# -- store serialization ----------------------------------------------

def store_records(store: TopologyStore) -> list[dict]:
    """Full topology records incl. the metadata to_manifest omits."""
    out = []
    for t in store.list():
        out.append({
            "manifest": t.to_manifest(),
            "finalizers": list(t.finalizers),
            "resource_version": t.resource_version,
            "deletion_requested": t.deletion_requested,
        })
    return out


def restore_store(records: list[dict]) -> TopologyStore:
    store = TopologyStore()
    # Bypass create(): it resets resourceVersion/deletionRequested, but a
    # restore must preserve the optimistic-concurrency clocks so in-flight
    # clients conflict correctly against pre-checkpoint versions.
    with store._lock:
        for r in records:
            t = Topology.from_manifest(r["manifest"])
            t.finalizers = list(r.get("finalizers", []))
            t.deletion_requested = bool(r.get("deletion_requested", False))
            t.resource_version = int(r.get("resource_version", 1))
            store._objects[t.key] = t
            store._rv = max(store._rv, t.resource_version)
    return store


# -- elastic recovery (reconstruction) --------------------------------

def rebuild_engine(store: TopologyStore, capacity: int = 1024,
                   node_ip: str = "10.0.0.1") -> SimEngine:
    """Daemon-restart reconstruction: rebuild the full device-array
    realization from the store alone.

    Mirrors the reference's startup resync (kubedtn.go:107-121): list all
    topologies, seed the managers, and re-plumb every alive pod's links.
    add_links is idempotent per (pod, uid) like SetupVeth
    (common/veth.go:65-93), so plumbing both endpoint topologies converges
    to one realization. Mutable shaping state comes back fresh, exactly as
    reinstalled qdiscs would.
    """
    engine = SimEngine(store, capacity=capacity, node_ip=node_ip)
    for topo in store.list():
        if topo.is_alive():
            engine.set_alive(topo.name, topo.namespace, topo.status.src_ip,
                             topo.status.net_ns)
    # second pass so peer-aliveness checks see every pod's restored status
    for topo in store.list():
        if topo.is_alive():
            engine.add_links(topo, topo.spec.links)
    return engine


# -- crash-consistent directory plumbing ------------------------------

def _pid_alive(pid: int) -> bool:
    """Is a process with this pid running (signal-0 probe)?"""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return True  # unknown: err on the side of not deleting
    return True


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _fsync_path(path: str) -> None:
    """Best-effort fsync of a file or directory (crash durability; not
    every filesystem supports directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _read_manifest(dirpath: str) -> dict:
    """Parse + structurally validate one directory's manifest, mapping
    every damage mode to a typed error."""
    mpath = os.path.join(dirpath, "manifest.json")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except FileNotFoundError as e:
        try:
            nonempty = bool(os.listdir(dirpath))
        except OSError:
            nonempty = False
        if nonempty:
            # data files without a manifest is DAMAGE (a partial
            # restore or manual deletion), not a fresh start — callers
            # must surface it, never silently cold-start over it
            raise CheckpointCorruptError(
                f"checkpoint directory {dirpath} has data files but no "
                f"manifest") from e
        raise CheckpointMissingError(
            f"no checkpoint manifest at {mpath}") from e
    except (json.JSONDecodeError, UnicodeDecodeError, OSError) as e:
        raise CheckpointCorruptError(
            f"unreadable checkpoint manifest {mpath}: {e}") from e
    if not isinstance(manifest, dict) or "format_version" not in manifest:
        raise CheckpointCorruptError(
            f"checkpoint manifest {mpath} lacks format_version")
    if manifest["format_version"] not in (1, FORMAT_VERSION):
        raise CheckpointError(
            f"unsupported checkpoint version {manifest['format_version']}")
    return manifest


def _resolve_dir(path: str) -> tuple[str, dict]:
    """The directory actually holding the newest COMPLETE checkpoint for
    `path`: `path` itself when its manifest is valid, else the
    `<path>.prev` a crash between save()'s two renames left behind.
    Deterministic, read-only — load/load_pending/load_sim all resolve
    through here, so a fallback restore reads one coherent generation."""
    try:
        return path, _read_manifest(path)
    except CheckpointError as primary:
        prev = path + _PREV_SUFFIX
        try:
            manifest = _read_manifest(prev)
        except CheckpointError:
            raise primary from None
        return prev, manifest


def _verify_checksum(dirpath: str, manifest: dict, fname: str) -> None:
    """Raise CheckpointCorruptError when `fname` does not match the
    manifest's recorded sha256 (v1 manifests carry none — skipped)."""
    want = manifest.get("checksums", {}).get(fname)
    if want is None:
        return
    fpath = os.path.join(dirpath, fname)
    try:
        got = _sha256_file(fpath)
    except OSError as e:
        raise CheckpointCorruptError(
            f"unreadable checkpoint file {fpath}: {e}") from e
    if got != want:
        raise CheckpointCorruptError(
            f"checksum mismatch for {fpath}: manifest {want[:12]}…, "
            f"file {got[:12]}…")


def _load_npz(dirpath: str, manifest: dict, fname: str):
    """Checksum-verified np.load with npz damage mapped to the typed
    error (np.load raises half a dozen exception types on truncation)."""
    _verify_checksum(dirpath, manifest, fname)
    fpath = os.path.join(dirpath, fname)
    try:
        return np.load(fpath)
    except Exception as e:
        raise CheckpointCorruptError(
            f"damaged checkpoint array file {fpath}: {e}") from e


# -- checkpoint save/load ---------------------------------------------

# Note on sharded planes: the `_capture` gathers every column with
# np.asarray, which pulls a NamedSharding-distributed array to host —
# a checkpoint written under an N-way mesh restores on any device
# count, and vice versa (tests/test_sharded_plane.py round-trips
# 8-way ↔ 1-way bit-exact).

# SimState npz codec — the ONE flatten/unflatten for the
# "<field>.<leaf>" layout, shared by the checkpoint's sim_state.npz
# (edges stored separately in edge_state.npz) and the what-if twin's
# snapshot files (edges inlined): a field added to
# InFlight/EdgeCounters/TrafficState changes both formats in one place.

def flatten_sim_arrays(sim, include_edges: bool = False) -> dict:
    names = (("edges",) if include_edges else ()) + (
        "inflight", "counters", "traffic")
    flat = {}
    for name in names:
        sub = getattr(sim, name)
        for fld in dataclasses.fields(sub):
            flat[f"{name}.{fld.name}"] = np.asarray(
                getattr(sub, fld.name))
    flat["clock_us"] = np.asarray(sim.clock_us)
    return flat


def unflatten_sim_arrays(z, edges=None):
    """SimState from a flattened npz mapping; `edges` supplies the
    EdgeState when the file excludes it (checkpoint layout)."""
    from kubedtn_tpu.models.traffic import TrafficState
    from kubedtn_tpu.ops.queues import EdgeCounters, InFlight
    from kubedtn_tpu.sim import SimState

    def sub(cls, prefix):
        return cls(**{
            f.name: jnp.asarray(z[f"{prefix}.{f.name}"])
            for f in dataclasses.fields(cls)
        })

    return SimState(
        edges=edges if edges is not None else sub(es.EdgeState, "edges"),
        inflight=sub(InFlight, "inflight"),
        counters=sub(EdgeCounters, "counters"),
        traffic=sub(TrafficState, "traffic"),
        clock_us=jnp.asarray(z["clock_us"]),
    )


def save(path: str, store: TopologyStore, engine: SimEngine,
         sim=None, dataplane=None) -> None:
    """Write a checkpoint directory ATOMICALLY: stage everything in a
    temp directory beside `path`, record per-file sha256 checksums in
    the manifest, fsync, then swap into place with renames. A crash at
    any point leaves the previous complete checkpoint restorable (at
    `path` or `<path>.prev`); a reused directory can never leak stale
    `pending_frames.npz`/`sim_state.npz` from an earlier save because
    the directory is replaced wholesale. With `dataplane`, in-flight
    delay-line frames, wire definitions and the plane's cumulative
    per-edge counters are persisted too, so a restarted (or evacuated
    — federation.supervisor) daemon completes the frames' remaining
    delays and keeps its delivery accounting. For a checkpoint of a
    plane whose runner is STILL TICKING, use `save_live` (this entry
    refuses, because an unsynchronized capture could double-deliver or
    lose frames)."""
    if dataplane is not None and getattr(dataplane, "running", False):
        # a live runner can release exported frames (duplicate on
        # restore) or shape new ones after the export (lost): the
        # checkpoint must be a consistent point-in-time cut
        raise RuntimeError(
            "stop() the data plane before checkpointing its pending "
            "frames, or use save_live() for a barrier-consistent "
            "autosave")
    from kubedtn_tpu.utils import tracing

    with tracing.span("checkpoint-save", path=path):
        pauses = getattr(dataplane, "pauses", None)
        if pauses is not None:
            # stopped plane, but the pause still lands in the ledger so
            # a restart-heavy fleet's checkpoint cost stays attributable
            with pauses.pause("checkpoint_save", path=path,
                              rows=int(engine._state.capacity)):
                cap = _capture(store, engine, sim, dataplane)
        else:
            cap = _capture(store, engine, sim, dataplane)
        return _write_captured(path, cap)


def save_live(path: str, store: TopologyStore, engine: SimEngine,
              dataplane) -> None:
    """Crash-consistent checkpoint of a RUNNING plane — the periodic
    autosave entry (`kdt daemon --checkpoint-interval`). The capture
    happens at one `stage_update_round` flush barrier (every in-flight
    dispatch's write-back lands first, the runner pauses one barrier —
    the twin-snapshot consistency contract), then the staging, fsync
    and atomic swap run OFF the tick path so disk I/O never blocks a
    tick. This is what bounds the fleet's failover RPO: before it, a
    SIGKILL lost everything since start, because state was saved only
    on graceful SIGTERM."""
    from kubedtn_tpu.utils import tracing

    with tracing.span("checkpoint-save-live", path=path):
        # the barrier is the pause: staging/fsync/swap run off the tick
        # path afterwards, so only the capture is attributed (cause
        # checkpoint_save, rows = the engine's full column height — the
        # capture is O(capacity), which is exactly why the ledger and
        # the savail budget exist)
        cap = dataplane.stage_update_round(
            lambda: _capture(store, engine, None, dataplane),
            cause="checkpoint_save", path=path,
            rows=int(engine._state.capacity))
        return _write_captured(path, cap)


def _capture(store: TopologyStore, engine: SimEngine,
             sim=None, dataplane=None) -> dict:
    """Consistent point-in-time cut of everything a checkpoint
    persists, as host arrays + JSON-ready manifest sections — no disk
    I/O. Runs either with the plane stopped (`save`) or inside a tick-
    lock flush barrier (`save_live`); the engine lock is held across
    the state gather and the registry snapshot so the two can never
    show different generations."""
    cap: dict = {"sim": None, "pending": None, "counters": None,
                 "ingress": None}
    if dataplane is not None:
        cap["pending"] = dataplane.export_pending()
        cap["counters"] = {
            f.name: np.asarray(getattr(dataplane.counters, f.name))
            for f in dataclasses.fields(type(dataplane.counters))}
        # queued-but-undrained INGRESS frames: accepted from producers
        # but not yet shaped — without these a restart (or failover)
        # silently loses every frame the plane accepted since its last
        # drain. Ticks are blocked at the capture barrier, so the
        # snapshot is exactly the undrained set; a producer appending
        # DURING a live capture may land after the cut (reported as
        # loss on crash, normal delivery otherwise).
        from kubedtn_tpu.wire.server import flatten_frames

        ingress = []
        for w in dataplane.daemon.wires.all():
            q = w.ingress
            entries = (q.snapshot_entries()
                       if hasattr(q, "snapshot_entries") else list(q))
            for frame in flatten_frames(entries):
                ingress.append((w.pod_key, int(w.uid), frame))
        cap["ingress"] = ingress
    with engine._lock:
        engine._flush_device_locked()
        st = engine._state
        cap["edge"] = {f.name: np.asarray(getattr(st, f.name))
                       for f in dataclasses.fields(type(st))}
        manifest = {
            "format_version": FORMAT_VERSION,
            "node_ip": engine.node_ip,
            "capacity": st.capacity,
            "engine": {
                "pod_ids": dict(engine._pod_ids),
                "rows": [[k[0], k[1], v]
                         for k, v in engine._rows.items()],
                "peer": [[k[0], k[1], v[0], v[1]]
                         for k, v in engine._peer.items()],
                "free": engine._free.tolist(),
                "alive": sorted(engine._topology_manager),
            },
            "has_sim": sim is not None,
        }
    manifest["store"] = store_records(store)
    if sim is not None:
        cap["sim"] = flatten_sim_arrays(sim)
    if dataplane is not None:
        # wire definitions: the attachment registry is daemon state the
        # store cannot re-derive — without it an evacuation (or a
        # restart) would wait for every client to re-register before a
        # single frame could flow
        manifest["wires"] = [
            [w.pod_key, int(w.uid), w.peer_ip, int(w.peer_intf_id),
             w.node_iface_name]
            for w in dataplane.daemon.wires.all()]
        ls = dataplane._last_shaped_s
        manifest["plane"] = {
            "last_shaped_s": None if ls is None else float(ls),
            "has_counters": True,
        }
    tenancy = getattr(engine, "tenancy", None)
    if tenancy is not None:
        # quotas / QoS / block entitlements / namespace bindings
        # survive the restart (load_tenancy) — without this section
        # a restart silently reset every tenant to unenforced,
        # which the federation RELEASE/rollback paths must never
        # rely on
        manifest["tenancy"] = tenancy.export_config()
        # reservations are registry state re-carved at restore: the
        # persisted free list must include the blocks' unused rows,
        # or each restart would leak them (gone from the global
        # pool AND from the new blocks). A tenancy-less load keeps
        # them in the global pool — also correct.
        manifest["engine"]["free"] = (
            manifest["engine"]["free"]
            + sorted(tenancy.reserved_free_rows(), reverse=True))
    cap["manifest"] = manifest
    return cap


def _write_captured(path: str, cap: dict) -> None:
    """Stage a captured checkpoint beside `path` and swap it into place
    atomically (the write half of `save`/`save_live` — pure disk work,
    never touches live state)."""
    path = os.path.abspath(path)
    _CKPT_FILES = {"manifest.json", "edge_state.npz", "sim_state.npz",
                   "pending_frames.npz", "plane_counters.npz",
                   "wire_ingress.npz"}
    if (os.path.isdir(path) and os.listdir(path)
            and not os.path.exists(os.path.join(path, "manifest.json"))
            and not set(os.listdir(path)) <= _CKPT_FILES):
        # a manifest-less dir of ONLY checkpoint files is damaged debris
        # this save may replace; anything else is presumably the user's
        # and must not be clobbered
        raise CheckpointError(
            f"refusing to replace {path}: non-empty directory without a "
            f"checkpoint manifest")
    parent = os.path.dirname(path)
    os.makedirs(parent, exist_ok=True)
    # sweep staging dirs leaked by CRASHED saves: a crash-looping
    # daemon must not accumulate one full checkpoint copy per kill
    # until the volume fills. Exact `<prefix><basename>-<pid>` match
    # only (a bare prefix match would also hit a sibling checkpoint
    # named `<basename>-x`), and a pid that is still alive is another
    # process's LIVE staging — never touched.
    pat = re.compile(
        re.escape(f"{_TMP_PREFIX}{os.path.basename(path)}-") + r"(\d+)$")
    for entry in os.listdir(parent):
        m = pat.fullmatch(entry)
        if m is None:
            continue
        pid = int(m.group(1))
        if pid != os.getpid() and _pid_alive(pid):
            continue
        shutil.rmtree(os.path.join(parent, entry), ignore_errors=True)
    tmp = os.path.join(parent,
                       f"{_TMP_PREFIX}{os.path.basename(path)}-{os.getpid()}")
    os.makedirs(tmp)
    try:
        if cap["pending"] is not None:
            _pending_to_npz(os.path.join(tmp, "pending_frames.npz"),
                            cap["pending"])
        if cap["ingress"]:
            _frames_to_npz(os.path.join(tmp, "wire_ingress.npz"),
                           cap["ingress"])
        if cap["counters"] is not None:
            np.savez_compressed(os.path.join(tmp, "plane_counters.npz"),
                                **cap["counters"])
        np.savez_compressed(os.path.join(tmp, "edge_state.npz"),
                            **cap["edge"])
        if cap["sim"] is not None:
            np.savez_compressed(os.path.join(tmp, "sim_state.npz"),
                                **cap["sim"])
        checksums = {
            fname: _sha256_file(os.path.join(tmp, fname))
            for fname in sorted(os.listdir(tmp))
        }
        manifest = dict(cap["manifest"])
        manifest["checksums"] = checksums
        mpath = os.path.join(tmp, "manifest.json")
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            try:
                os.fsync(f.fileno())
            except OSError:
                pass
        for fname in checksums:
            _fsync_path(os.path.join(tmp, fname))
        _fsync_path(tmp)
        # -- atomic swap: each rename is atomic; between them `path` is
        # briefly absent but `.prev` holds the previous complete
        # generation, which load() falls back to. When `path` is ABSENT
        # (recovering from a prior mid-save crash) a leftover `.prev` is
        # the ONLY complete generation — it must survive until the new
        # one is installed, so it is pruned only at the end.
        prev = path + _PREV_SUFFIX
        if os.path.isdir(path):
            shutil.rmtree(prev, ignore_errors=True)  # superseded by path
            os.rename(path, prev)
        os.rename(tmp, path)
        _fsync_path(parent)
        shutil.rmtree(prev, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load(path: str) -> tuple[TopologyStore, SimEngine]:
    """Restore (store, engine) from a checkpoint directory, verifying
    checksums. Falls back to the `<path>.prev` generation a mid-save
    crash may have left; raises `CheckpointError`/`CheckpointCorruptError`
    (typed — see `load_or_rebuild` for the reconstruction fallback) when
    neither generation is usable."""
    from kubedtn_tpu.utils import tracing

    with tracing.span("checkpoint-load", path=path):
        return _load_traced(path)


def _load_traced(path: str) -> tuple[TopologyStore, SimEngine]:
    path = os.path.abspath(path)
    dirpath, manifest = _resolve_dir(path)

    try:
        store = restore_store(manifest["store"])
        engine = SimEngine(store, capacity=manifest["capacity"],
                           node_ip=manifest["node_ip"])
    except (KeyError, TypeError, ValueError) as e:
        raise CheckpointCorruptError(
            f"malformed checkpoint manifest in {dirpath}: {e}") from e

    with _load_npz(dirpath, manifest, "edge_state.npz") as z:
        try:
            engine.state = es.EdgeState(
                **{name: jnp.asarray(z[name]) for name in z.files})
            # rebuild the host mirror the bypass guard consults: a
            # restored shaped link must NOT read as unshaped (that would
            # let same-node TCP flows skip its netem/TBF chain entirely)
            shaped = np.flatnonzero(
                z["active"] & np.asarray(z["props"]).any(axis=1))
        except Exception as e:
            raise CheckpointCorruptError(
                f"damaged edge_state.npz in {dirpath}: {e}") from e
        engine._shaped_rows = set(int(r) for r in shaped)

    try:
        from kubedtn_tpu.topology.engine import link_key_id
        from kubedtn_tpu.topology.freelist import FreeStack

        eng = manifest["engine"]
        engine._pod_ids = dict(eng["pod_ids"])
        engine._pod_names = {v: k for k, v in engine._pod_ids.items()}
        engine._rows = {(p, int(u)): int(r) for p, u, r in eng["rows"]}
        engine._row_owner = {r: k for k, r in engine._rows.items()}
        # per-row identity key ids are derivable state: re-derive the
        # columnar column in the same registry pass (a restored link
        # must keep its identity-keyed PRNG stream — leaving the
        # column zeroed would silently drop every restored row back
        # to the legacy unkeyed draws)
        for (p, u), r in engine._rows.items():
            engine._row_keyid[r] = link_key_id(p, int(u))
        engine._peer = {(p, int(u)): (pp, int(pu))
                        for p, u, pp, pu in eng["peer"]}
        engine._free = FreeStack(eng["free"])
        engine._topology_manager = set(eng["alive"])
    except (KeyError, TypeError, ValueError, IndexError) as e:
        # IndexError: a manifest row beyond the stated capacity hits
        # the columnar key-id write — damage, same typed contract
        raise CheckpointCorruptError(
            f"malformed engine registries in {dirpath}: {e}") from e
    return store, engine


def load_or_rebuild(path: str, store: TopologyStore | None = None,
                    capacity: int = 1024, node_ip: str = "10.0.0.1",
                    mesh=None) -> tuple[TopologyStore, SimEngine, str]:
    """`load` with the documented corruption fallback: on any
    CheckpointError, reconstruct via `rebuild_engine` from `store` (the
    CR source of truth — the reference's restart rescan) instead of
    raising mid-restore. Returns (store, engine, source) with source in
    {"checkpoint", "rebuild"}; re-raises only when no fallback store was
    provided. `mesh` re-shards the restored edge state onto the CURRENT
    device mesh (checkpoints are device-count-agnostic host arrays —
    the save-side capture gathered them)."""
    try:
        s, e, src = *load(path), "checkpoint"
    except CheckpointError as err:
        if store is None:
            raise
        from kubedtn_tpu.utils.logging import fields, get_logger

        get_logger("checkpoint").warning(
            "checkpoint unusable; rebuilding from store %s",
            fields(path=path, error=f"{type(err).__name__}: {err}"))
        s, e, src = store, rebuild_engine(store, capacity=capacity,
                                          node_ip=node_ip), "rebuild"
    if mesh is not None:
        from kubedtn_tpu.parallel.mesh import shard_edge_state

        S = int(mesh.devices.size)
        with e._lock:
            e._flush_device_locked()
            st = e._state
            if st.capacity % S:
                st = es.grow_state(st, -(-st.capacity // S) * S)
            e._state = shard_edge_state(st, mesh)
            e.shard_count = S
    return s, e, src


def _pending_to_npz(fpath: str, entries) -> None:
    """Serialize exported (pod_key, uid, frame, remaining_us) entries
    as the pickle-free pending_frames.npz layout."""
    blob = b"".join(frame for _, _, frame, _ in entries)
    offs, lens, pos = [], [], 0
    for _, _, frame, _ in entries:
        offs.append(pos)
        lens.append(len(frame))
        pos += len(frame)
    np.savez_compressed(
        fpath,
        pod_keys=np.frombuffer(
            "\n".join(pk for pk, _, _, _ in entries).encode(), np.uint8),
        uids=np.array([u for _, u, _, _ in entries], np.int64),
        remaining_us=np.array([r for _, _, _, r in entries], np.float64),
        offsets=np.array(offs, np.int64),
        lengths=np.array(lens, np.int64),
        blob=np.frombuffer(blob, np.uint8),
    )


def _frames_to_npz(fpath: str, entries) -> None:
    """Serialize (pod_key, uid, frame) tuples as the pickle-free
    wire_ingress.npz layout (the pending layout minus delays)."""
    blob = b"".join(frame for _, _, frame in entries)
    offs, lens, pos = [], [], 0
    for _, _, frame in entries:
        offs.append(pos)
        lens.append(len(frame))
        pos += len(frame)
    np.savez_compressed(
        fpath,
        pod_keys=np.frombuffer(
            "\n".join(pk for pk, _, _ in entries).encode(), np.uint8),
        uids=np.array([u for _, u, _ in entries], np.int64),
        offsets=np.array(offs, np.int64),
        lengths=np.array(lens, np.int64),
        blob=np.frombuffer(blob, np.uint8),
    )


def read_ingress_entries(path: str) -> list:
    """The checkpointed queued-ingress frames as (pod_key, uid, frame)
    tuples, FIFO per wire — checksum-verified, same-generation
    resolution as `load`. [] when absent; corruption raises."""
    try:
        dirpath, manifest = _resolve_dir(os.path.abspath(path))
    except CheckpointMissingError:
        return []
    if not os.path.exists(os.path.join(dirpath, "wire_ingress.npz")):
        return []
    with _load_npz(dirpath, manifest, "wire_ingress.npz") as z:
        try:
            keys = bytes(z["pod_keys"]).decode().split("\n") if len(
                z["pod_keys"]) else []
            blob = bytes(z["blob"])
            return [
                (keys[i], int(z["uids"][i]),
                 blob[int(z["offsets"][i]):int(z["offsets"][i])
                      + int(z["lengths"][i])])
                for i in range(len(z["uids"]))
            ]
        except Exception as e:
            raise CheckpointCorruptError(
                f"damaged wire_ingress.npz in {dirpath}: {e}") from e


def load_ingress(path: str, daemon) -> int:
    """Re-queue the checkpointed ingress frames onto their wires (the
    wires must already exist — `load_wires` first). The extend fires
    the wire's notify, so restored frames mark hot and drain on the
    first tick. Returns frames restored."""
    entries = read_ingress_entries(path)
    n = 0
    by_wire: dict[tuple, list] = {}
    for pk, uid, frame in entries:
        by_wire.setdefault((pk, uid), []).append(frame)
    for (pk, uid), frames in by_wire.items():
        wire = daemon.wires.get_by_key(pk, uid)
        if wire is None:
            continue  # wire vanished from the topology: nothing owed
        wire.ingress.extend(frames)
        n += len(frames)
    return n


def save_pending(path: str, dataplane) -> int:
    """Persist the data plane's in-flight frames (pickle-free npz) —
    the delay-line analogue of kernel qdisc queues surviving a daemon
    restart in the reference. Returns the frame count. (Standalone
    callers lose the atomic-swap guarantee `save` provides.)"""
    entries = dataplane.export_pending()
    _pending_to_npz(os.path.join(path, "pending_frames.npz"), entries)
    return len(entries)


def read_pending_entries(path: str) -> list:
    """The checkpointed in-flight entries as (pod_key, uid, frame,
    remaining_us) tuples WITHOUT a plane to restore them into —
    checksum-verified, same-generation resolution as `load`. The
    federation supervisor slices these per tenant when evacuating a
    dead plane onto survivors. [] when no checkpoint / no pending
    file; corruption raises."""
    try:
        dirpath, manifest = _resolve_dir(os.path.abspath(path))
    except CheckpointMissingError:
        return []  # no checkpoint at all: nothing pending
    if not os.path.exists(os.path.join(dirpath, "pending_frames.npz")):
        return []
    with _load_npz(dirpath, manifest, "pending_frames.npz") as z:
        try:
            keys = bytes(z["pod_keys"]).decode().split("\n") if len(
                z["pod_keys"]) else []
            blob = bytes(z["blob"])
            return [
                (keys[i], int(z["uids"][i]),
                 blob[int(z["offsets"][i]):int(z["offsets"][i])
                      + int(z["lengths"][i])],
                 float(z["remaining_us"][i]))
                for i in range(len(z["uids"]))
            ]
        except Exception as e:
            raise CheckpointCorruptError(
                f"damaged pending_frames.npz in {dirpath}: {e}") from e


def load_pending(path: str, dataplane, now_s: float | None = None) -> int:
    """Re-schedule checkpointed in-flight frames with their remaining
    delays (checksum-verified, same-generation as `load`'s fallback
    resolution). Returns the restored count — 0 when the checkpoint
    carried no pending file OR no checkpoint exists at all (a fresh
    daemon's first start); corruption and unsupported formats raise."""
    entries = read_pending_entries(path)
    if not entries:
        return 0
    return dataplane.restore_pending(entries, now_s=now_s)


def consume_pending(path: str) -> None:
    """Remove the restored generation's pending_frames.npz AND
    wire_ingress.npz (from the SAME directory the loaders resolved) so
    a crash before the next graceful checkpoint cannot re-deliver the
    same frames twice."""
    try:
        dirpath, _manifest = _resolve_dir(os.path.abspath(path))
    except CheckpointError:
        return  # nothing restorable: nothing to consume
    for fname in ("pending_frames.npz", "wire_ingress.npz"):
        p = os.path.join(dirpath, fname)
        if os.path.exists(p):
            os.remove(p)


def load_tenancy(path: str, engine: SimEngine):
    """Rebuild the TenantRegistry from a checkpoint's tenancy section
    against a restored engine: quotas, QoS class, namespace bindings,
    admitted meters, and each tenant's `block_rows` entitlement (the
    block re-carves from the restored free list — same rows when the
    layout is unchanged; the ENTITLEMENT, not the position, is the
    contract). None when the checkpoint (or its tenancy section)
    doesn't exist — the caller then starts an empty registry;
    corruption and unsupported formats raise like the other loaders."""
    try:
        _dirpath, manifest = _resolve_dir(os.path.abspath(path))
    except CheckpointMissingError:
        return None
    section = manifest.get("tenancy")
    if section is None:
        return None
    from kubedtn_tpu.tenancy import TenantRegistry

    try:
        registry = TenantRegistry(
            engine, default_qos=section.get("default_qos", "gold"))
        for t in section.get("tenants", ()):
            won = registry.create(
                t["name"], qos=t.get("qos"),
                frame_budget_per_s=t.get("frame_budget_per_s"),
                byte_budget_per_s=t.get("byte_budget_per_s"),
                block_edges=int(t.get("block_rows", 0)),
                namespaces=t.get("namespaces"))
            won.admitted_frames = int(t.get("admitted_frames", 0))
            won.admitted_bytes = int(t.get("admitted_bytes", 0))
    except (KeyError, TypeError, ValueError) as e:
        raise CheckpointCorruptError(
            f"malformed tenancy section in {path}: {e}") from e
    return registry


def load_wires(path: str, daemon) -> int:
    """Re-register the checkpointed wire definitions on a daemon (the
    attachment registry is daemon state the store cannot re-derive).
    Idempotent per (pod, uid) — `get_or_create` keeps whatever a
    faster client already registered. Returns wires (re)registered; 0
    when no checkpoint or no wires section exists."""
    try:
        _dirpath, manifest = _resolve_dir(os.path.abspath(path))
    except CheckpointMissingError:
        return 0
    from kubedtn_tpu.wire.server import Wire

    n = 0
    for pod_key, uid, peer_ip, peer_intf_id, ifname in \
            manifest.get("wires", ()):
        def build(wire_id: int, _pk=pod_key, _uid=uid, _peer=peer_ip,
                  _pid=peer_intf_id, _if=ifname):
            return Wire(wire_id=wire_id, uid=int(_uid), pod_key=_pk,
                        node_iface_name=_if, peer_intf_id=int(_pid),
                        peer_ip=_peer)

        daemon.wires.get_or_create(pod_key, int(uid), build)
        n += 1
    return n


def load_plane_counters(path: str):
    """The checkpointed plane counter columns as host arrays (field
    name → np.ndarray[E]), checksum-verified — None when the
    checkpoint (or its counters file) doesn't exist. The federation
    supervisor slices these for failover accounting: delivery counted
    before the last checkpoint is the durable `delivered_src` half of
    `fed == delivered_src + delivered_dst + reported_lost`."""
    try:
        dirpath, manifest = _resolve_dir(os.path.abspath(path))
    except CheckpointMissingError:
        return None
    if not os.path.exists(os.path.join(dirpath, "plane_counters.npz")):
        return None
    with _load_npz(dirpath, manifest, "plane_counters.npz") as z:
        try:
            return {k: np.asarray(z[k]) for k in z.files}
        except Exception as e:
            raise CheckpointCorruptError(
                f"damaged plane_counters.npz in {dirpath}: {e}") from e


def restore_plane_counters(path: str, plane) -> bool:
    """Install the checkpointed counter columns on a (restored) plane,
    padded/truncated to the plane's current capacity — a restart keeps
    its cumulative delivery accounting instead of silently zeroing
    every kubedtn per-interface series. False when nothing to
    restore."""
    arrays = load_plane_counters(path)
    if arrays is None:
        return False
    cap = int(plane.engine.state.capacity)

    def fit(a: np.ndarray):
        out = np.zeros((cap,) + a.shape[1:], a.dtype)
        n = min(cap, a.shape[0])
        out[:n] = a[:n]
        return jnp.asarray(out)

    cnt = plane.counters
    plane.counters = type(cnt)(**{
        f.name: fit(arrays[f.name]) if f.name in arrays
        else getattr(cnt, f.name)
        for f in dataclasses.fields(type(cnt))})
    return True


def plane_meta(path: str) -> dict:
    """The checkpoint's `plane` manifest section ({} when absent):
    `last_shaped_s` anchors the clock rebase when a tenant slice is
    cold-restored onto a survivor plane (federation.supervisor)."""
    try:
        _dirpath, manifest = _resolve_dir(os.path.abspath(path))
    except CheckpointMissingError:
        return {}
    return dict(manifest.get("plane") or {})


class Autosaver:
    """Periodic crash-consistent autosave for a live daemon (`kdt
    daemon --checkpoint-interval N`): every `interval_s`, `save_live`
    captures the full checkpoint at one flush barrier and writes it
    with the usual atomic staged swap. This bounds the fleet's
    failover RPO — before it, state was saved only on graceful
    SIGTERM, so a SIGKILL lost everything since start. A failing save
    (full disk) is logged and counted, never fatal; the previous
    complete generation stays restorable throughout."""

    def __init__(self, path: str, store: TopologyStore,
                 engine: SimEngine, dataplane,
                 interval_s: float = 30.0) -> None:
        import threading

        self.path = path
        self.store = store
        self.engine = engine
        self.dataplane = dataplane
        self.interval_s = float(interval_s)
        self.saves = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    def save_now(self) -> None:
        """One immediate barrier-consistent save (also the loop body)."""
        save_live(self.path, self.store, self.engine, self.dataplane)
        self.saves += 1

    def start(self) -> None:
        import threading

        if self._thread is not None:
            return
        from kubedtn_tpu.utils.logging import fields, get_logger

        log = get_logger("checkpoint")

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.save_now()
                except Exception:
                    self.errors += 1
                    log.exception("autosave failed (continuing) %s",
                                  fields(path=self.path,
                                         errors=self.errors))

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="kdt-autosave")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(5.0, self.interval_s))
        self._thread = None


def load_sim(path: str, engine: SimEngine):
    """Restore the optional SimState against a restored engine
    (checksum-verified; a save without `sim` leaves no stale
    sim_state.npz behind — the directory swap is wholesale). None when
    the checkpoint carries no sim state or no checkpoint exists;
    corruption and unsupported formats raise."""
    try:
        dirpath, manifest = _resolve_dir(os.path.abspath(path))
    except CheckpointMissingError:
        return None
    if not os.path.exists(os.path.join(dirpath, "sim_state.npz")):
        return None
    with _load_npz(dirpath, manifest, "sim_state.npz") as z:
        try:
            return unflatten_sim_arrays(z, edges=engine.state)
        except CheckpointCorruptError:
            raise
        except Exception as e:
            raise CheckpointCorruptError(
                f"damaged sim_state.npz in {dirpath}: {e}") from e
