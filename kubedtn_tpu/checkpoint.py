"""Checkpoint / resume + elastic recovery.

The reference has no snapshot files: its durable state is the Topology CR
(Status.Links = last applied, Status.SrcIP/NetNs = placement) plus the
kernel devices themselves, and crash recovery is *reconstruction* — a
restarted daemon re-lists topologies and rescans pod netnses to rebuild its
managers (reference daemon/kubedtn/kubedtn.go:107-121,
daemon/vxlan/manager.go:25-55; SURVEY.md §5.3-5.4). This module provides
both halves for the TPU build:

- `rebuild_engine` — the reconstruction path: given only the store (the CR
  source of truth), re-derive the whole device-array realization, exactly
  like a daemon restart. Device arrays are rebuildable projections.
- `save` / `load` — a real checkpoint: store contents + engine registries
  as JSON, device arrays as npz. Restoring short-circuits reconstruction
  (no re-plumbing) and preserves mutable shaping state (token buckets,
  correlation memory, counters) that reconstruction would reset — the same
  distinction as kernel qdiscs surviving a daemon restart vs being
  reinstalled.

Layout of a checkpoint directory:
  manifest.json   — versioned metadata + engine registries + store records
  edge_state.npz  — EdgeState arrays
  sim_state.npz   — optional SimState arrays (inflight/counters/traffic)
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax.numpy as jnp
import numpy as np

from kubedtn_tpu.api.types import Topology
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.topology.engine import SimEngine
from kubedtn_tpu.topology.store import TopologyStore

FORMAT_VERSION = 1


# -- store serialization ----------------------------------------------

def store_records(store: TopologyStore) -> list[dict]:
    """Full topology records incl. the metadata to_manifest omits."""
    out = []
    for t in store.list():
        out.append({
            "manifest": t.to_manifest(),
            "finalizers": list(t.finalizers),
            "resource_version": t.resource_version,
            "deletion_requested": t.deletion_requested,
        })
    return out


def restore_store(records: list[dict]) -> TopologyStore:
    store = TopologyStore()
    # Bypass create(): it resets resourceVersion/deletionRequested, but a
    # restore must preserve the optimistic-concurrency clocks so in-flight
    # clients conflict correctly against pre-checkpoint versions.
    with store._lock:
        for r in records:
            t = Topology.from_manifest(r["manifest"])
            t.finalizers = list(r.get("finalizers", []))
            t.deletion_requested = bool(r.get("deletion_requested", False))
            t.resource_version = int(r.get("resource_version", 1))
            store._objects[t.key] = t
            store._rv = max(store._rv, t.resource_version)
    return store


# -- elastic recovery (reconstruction) --------------------------------

def rebuild_engine(store: TopologyStore, capacity: int = 1024,
                   node_ip: str = "10.0.0.1") -> SimEngine:
    """Daemon-restart reconstruction: rebuild the full device-array
    realization from the store alone.

    Mirrors the reference's startup resync (kubedtn.go:107-121): list all
    topologies, seed the managers, and re-plumb every alive pod's links.
    add_links is idempotent per (pod, uid) like SetupVeth
    (common/veth.go:65-93), so plumbing both endpoint topologies converges
    to one realization. Mutable shaping state comes back fresh, exactly as
    reinstalled qdiscs would.
    """
    engine = SimEngine(store, capacity=capacity, node_ip=node_ip)
    for topo in store.list():
        if topo.is_alive():
            engine.set_alive(topo.name, topo.namespace, topo.status.src_ip,
                             topo.status.net_ns)
    # second pass so peer-aliveness checks see every pod's restored status
    for topo in store.list():
        if topo.is_alive():
            engine.add_links(topo, topo.spec.links)
    return engine


# -- checkpoint save/load ---------------------------------------------

def _arrays_to_npz(path: str, obj) -> None:
    fields = {f.name: np.asarray(getattr(obj, f.name))
              for f in dataclasses.fields(obj)}
    np.savez_compressed(path, **fields)


def save(path: str, store: TopologyStore, engine: SimEngine,
         sim=None, dataplane=None) -> None:
    """Write a checkpoint directory (created if needed). With
    `dataplane`, in-flight delay-line frames are persisted too
    (save_pending) so a restarted daemon completes their remaining
    delays."""
    os.makedirs(path, exist_ok=True)
    if dataplane is not None:
        if getattr(dataplane, "running", False):
            # a live runner can release exported frames (duplicate on
            # restore) or shape new ones after the export (lost): the
            # checkpoint must be a consistent point-in-time cut
            raise RuntimeError(
                "stop() the data plane before checkpointing its pending "
                "frames")
        save_pending(path, dataplane)
    else:
        # a reused checkpoint directory must not keep an earlier save's
        # pending file: restoring it would re-deliver long-gone frames
        stale = os.path.join(path, "pending_frames.npz")
        if os.path.exists(stale):
            os.remove(stale)
    manifest = {
        "format_version": FORMAT_VERSION,
        "node_ip": engine.node_ip,
        "capacity": engine.state.capacity,
        "store": store_records(store),
        "engine": {
            "pod_ids": engine._pod_ids,
            "rows": [[k[0], k[1], v] for k, v in engine._rows.items()],
            "peer": [[k[0], k[1], v[0], v[1]]
                     for k, v in engine._peer.items()],
            "free": engine._free,
            "alive": sorted(engine._topology_manager),
        },
        "has_sim": sim is not None,
    }
    with open(os.path.join(path, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    _arrays_to_npz(os.path.join(path, "edge_state.npz"), engine.state)
    if sim is not None:
        flat = {}
        for name in ("inflight", "counters", "traffic"):
            sub = getattr(sim, name)
            for fld in dataclasses.fields(sub):
                flat[f"{name}.{fld.name}"] = np.asarray(getattr(sub, fld.name))
        flat["clock_us"] = np.asarray(sim.clock_us)
        np.savez_compressed(os.path.join(path, "sim_state.npz"), **flat)


def load(path: str) -> tuple[TopologyStore, SimEngine]:
    """Restore (store, engine) from a checkpoint directory."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    if manifest["format_version"] != FORMAT_VERSION:
        raise ValueError(
            f"unsupported checkpoint version {manifest['format_version']}")

    store = restore_store(manifest["store"])
    engine = SimEngine(store, capacity=manifest["capacity"],
                       node_ip=manifest["node_ip"])

    with np.load(os.path.join(path, "edge_state.npz")) as z:
        engine.state = es.EdgeState(
            **{name: jnp.asarray(z[name]) for name in z.files})
        # rebuild the host mirror the bypass guard consults: a restored
        # shaped link must NOT read as unshaped (that would let same-node
        # TCP flows skip its netem/TBF chain entirely)
        shaped = np.flatnonzero(
            z["active"] & np.asarray(z["props"]).any(axis=1))
        engine._shaped_rows = set(int(r) for r in shaped)

    eng = manifest["engine"]
    engine._pod_ids = dict(eng["pod_ids"])
    engine._rows = {(p, int(u)): int(r) for p, u, r in eng["rows"]}
    engine._row_owner = {r: k for k, r in engine._rows.items()}
    engine._peer = {(p, int(u)): (pp, int(pu))
                    for p, u, pp, pu in eng["peer"]}
    engine._free = [int(x) for x in eng["free"]]
    engine._topology_manager = set(eng["alive"])
    return store, engine


def save_pending(path: str, dataplane) -> int:
    """Persist the data plane's in-flight frames (pickle-free npz) —
    the delay-line analogue of kernel qdisc queues surviving a daemon
    restart in the reference. Returns the frame count."""
    entries = dataplane.export_pending()
    blob = b"".join(frame for _, _, frame, _ in entries)
    offs, lens, pos = [], [], 0
    for _, _, frame, _ in entries:
        offs.append(pos)
        lens.append(len(frame))
        pos += len(frame)
    np.savez_compressed(
        os.path.join(path, "pending_frames.npz"),
        pod_keys=np.frombuffer(
            "\n".join(pk for pk, _, _, _ in entries).encode(), np.uint8),
        uids=np.array([u for _, u, _, _ in entries], np.int64),
        remaining_us=np.array([r for _, _, _, r in entries], np.float64),
        offsets=np.array(offs, np.int64),
        lengths=np.array(lens, np.int64),
        blob=np.frombuffer(blob, np.uint8),
    )
    return len(entries)


def load_pending(path: str, dataplane, now_s: float | None = None) -> int:
    """Re-schedule checkpointed in-flight frames with their remaining
    delays. Returns the restored count (0 when the checkpoint carried
    no pending file)."""
    p = os.path.join(path, "pending_frames.npz")
    if not os.path.exists(p):
        return 0
    with np.load(p) as z:
        keys = bytes(z["pod_keys"]).decode().split("\n") if len(
            z["pod_keys"]) else []
        blob = bytes(z["blob"])
        entries = [
            (keys[i], int(z["uids"][i]),
             blob[int(z["offsets"][i]):int(z["offsets"][i])
                  + int(z["lengths"][i])],
             float(z["remaining_us"][i]))
            for i in range(len(z["uids"]))
        ]
    return dataplane.restore_pending(entries, now_s=now_s)


def load_sim(path: str, engine: SimEngine):
    """Restore the optional SimState against a restored engine."""
    from kubedtn_tpu.models.traffic import TrafficState
    from kubedtn_tpu.ops.queues import EdgeCounters, InFlight
    from kubedtn_tpu.sim import SimState

    p = os.path.join(path, "sim_state.npz")
    if not os.path.exists(p):
        return None
    with np.load(p) as z:
        def sub(cls, prefix):
            return cls(**{
                f.name: jnp.asarray(z[f"{prefix}.{f.name}"])
                for f in dataclasses.fields(cls)
            })

        return SimState(
            edges=engine.state,
            inflight=sub(InFlight, "inflight"),
            counters=sub(EdgeCounters, "counters"),
            traffic=sub(TrafficState, "traffic"),
            clock_us=jnp.asarray(z["clock_us"]),
        )
