"""Federated planes — zero-loss live tenant migration.

Many daemons, each a (sharded, multi-tenant) serving plane; a
placement layer moves TENANTS between them while both planes keep
serving. The headline is the crash-safe migration state machine:

    MIGRATE(tenant, src → dst) =
        THROTTLE → FORK → RESTORE → CUTOVER → RECONCILE → RELEASE

- **THROTTLE** — clamp the tenant's admission on src (a migration
  hold: its wires drain budget 0, frames queue — never dropped — and
  the daemon's ingress high-water backpressure bounds the backlog).
- **FORK** — capture the tenant's slice at a src tick-lock flush
  barrier (the `twin/snapshot` consistency contract: every in-flight
  dispatch lands first, the runner pauses one barrier, zero live-frame
  loss): per-row edge state bit-exact, link identities, peer map,
  topology records, wire definitions, quotas/QoS/block entitlement.
- **RESTORE** — replay onto dst at a dst barrier: tenant registered
  with its quotas and `block_rows` entitlement (rows carve into the
  tenant's contiguous block via `partition.tenant_blocks`), rows
  adopted bit-exact (identity-keyed PRNG streams — `link_key_id` —
  migrate with the link, not the row number), wires re-created (a
  cross-node wire whose peer IS dst becomes a local wire), store
  records moved. The tenant stays HELD on dst until cutover commits.
- **CUTOVER** — make-before-break at a src barrier: every queued
  tenant ingress entry transfers to the dst wire in FIFO order, then a
  redirect is installed on each src wire (late producers' frames
  forward the moment they land) — new frames land on dst while src's
  in-flight frames (delay line, holdback, `_PeerSender` outage
  buffers) drain through src.
- **RECONCILE** — release the dst hold, drain src residuals to zero
  (ingress, holdback, delay line, peer egress buffers — breaker-aware:
  an OPEN src→peer breaker extends the wait to its next half-open
  probe instead of failing the migration), then snapshot the
  byte-exact accounting split: delivered_src from the src counter
  slice (and the telemetry window rings), delivered_dst live on dst;
  `fed == delivered_src + delivered_dst` is the invariant
  `check_accounting` (and kubedtn_migration_accounting_mismatch) pins.
- **RELEASE** — free the src block: rows abandoned, wires deleted,
  store records dropped, tenant deregistered (`TenantRegistry.delete`).

Crash contract (journal.py persists the record after each step with
checkpoint-grade atomicity): **before CUTOVER commits, src is
authoritative** — resume discards the partial dst state bit-exactly
(rows abandoned, transferred frames moved back to the FRONT of the src
queues in order) and re-runs from a fresh FORK, so the tenant's stream
is byte-identical to a never-migrated plane; **after CUTOVER commits,
the migration rolls forward** — RECONCILE and RELEASE are idempotent
and re-run to completion. Either way `frames_lost == 0`.

Byte-identity scope: the delivered stream equals the never-migrated
reference when the federation's planes share a PRNG seed and tick in
lockstep (same dispatch schedule — the same alignment the cohabited ≡
solo tenancy contract already requires; tests/test_federation.py pins
it at pipeline depths 1 and 2). Unaligned planes still get zero loss
and exact accounting; the streams are then statistically, not
bitwise, identical.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

import numpy as np

from kubedtn_tpu import fault
from kubedtn_tpu.contracts import guarded_by
from kubedtn_tpu.federation import journal
from kubedtn_tpu.utils.logging import fields as _fields
from kubedtn_tpu.utils.logging import get_logger

STEPS = ("throttle", "fork", "restore", "cutover", "reconcile",
         "release")


class MigrationError(RuntimeError):
    """A migration step could not complete (resumable via `resume`)."""


@guarded_by("_lock", "attempts", "completed", "rolled_back", "resumed",
            "bytes_reconciled", "accounting_mismatch", "step_seconds")
class MigrationStats:
    """Cumulative migration counters for the kubedtn_migration_*
    Prometheus series (metrics.MigrationStatsCollector)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.attempts = 0
        self.completed = 0
        self.rolled_back = 0
        self.resumed = 0
        self.bytes_reconciled = 0.0
        # GAUGE: |fed - (delivered_src + delivered_dst)| of the latest
        # accounting check — the alert-worthy number; stays 0 in every
        # scenario
        self.accounting_mismatch = 0.0
        self.step_seconds = {s: 0.0 for s in STEPS}

    def add(self, **kw) -> None:
        with self._lock:
            for k, v in kw.items():
                setattr(self, k, getattr(self, k) + v)

    def add_step_seconds(self, step: str, s: float) -> None:
        with self._lock:
            self.step_seconds[step] = self.step_seconds.get(step, 0.0) + s

    def set_mismatch(self, v: float) -> None:
        with self._lock:
            self.accounting_mismatch = float(v)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "attempts": self.attempts,
                "completed": self.completed,
                "rolled_back": self.rolled_back,
                "resumed": self.resumed,
                "bytes_reconciled": self.bytes_reconciled,
                "accounting_mismatch": self.accounting_mismatch,
                "step_seconds": dict(self.step_seconds),
            }


def stats_for(daemon) -> MigrationStats:
    """The per-daemon MigrationStats sink (created on first use) —
    the pattern updates.stager.stats_for set."""
    stats = getattr(daemon, "_migration_stats", None)
    if stats is None:
        stats = daemon._migration_stats = MigrationStats()
    return stats


@dataclasses.dataclass
class PlaneHandle:
    """One federation member: a daemon with its live plane and tenant
    registry. `addr` is the daemon's wire address (used to turn a
    cross-node wire whose peer IS the destination into a local wire
    at restore).

    The three optional fields are the fleet supervisor's hooks
    (federation.supervisor): `checkpoint_dir` names the plane's
    crash-consistent checkpoint (the cold-restore source when the
    plane dies), `probe` overrides the health probe (default: the
    in-process `daemon.health_snapshot()`; a gRPC Local.Health dial
    for planes in other processes), and `restarter` performs the
    plane's binary restart for `kdt fleet upgrade` (checkpoint →
    teardown → rebuild → new server) and returns the REPLACEMENT
    handle."""

    name: str
    daemon: object        # wire.server.Daemon
    plane: object         # runtime.WireDataPlane
    registry: object      # tenancy.TenantRegistry
    checkpoint_dir: str | None = None
    probe: object = None       # () -> health dict; raises when dead
    restarter: object = None   # () -> PlaneHandle (the replacement)

    @property
    def engine(self):
        return self.daemon.engine

    @property
    def store(self):
        return self.engine.store

    @property
    def addr(self) -> str:
        return self.engine.node_ip


def restore_tenant_slice(dst: PlaneHandle, tenant: str, fork: dict,
                         arrays: dict, src_addr: str,
                         hold: bool = True):
    """Replay a captured tenant slice onto `dst` at ONE stage barrier:
    tenant registered with its quotas and block entitlement, topologies
    recreated with placement moved to dst, rows adopted bit-exact
    (identity-keyed PRNG streams ride the link identity), dynamic
    shaping columns scattered with the clock columns rebased by the
    capture→dst shaped-gap, wires re-created (a cross-node wire whose
    peer IS dst becomes local). The ONE restore implementation — the
    migration RESTORE step replays a live fork through it (tenant HELD
    until cutover commits), and the fleet supervisor's evacuation
    replays a checkpoint/journal slice through it (hold=False: the dead
    plane cannot cut over, the survivor serves immediately).

    The slice's cumulative delivery counters do NOT scatter in: the
    failover accounting freezes them as the src half of the record
    (federation.supervisor) exactly like RECONCILE freezes the src
    counter slice — the survivor's live counters stay purely its own,
    so `frozen + live` explains the feed without double counting.
    Takes dst's stage barrier itself (re-entrant under the tick lock,
    so the coordinator's own barrier composes). Returns the adopted
    row list."""
    return dst.plane.stage_update_round(
        lambda: _restore_slice_locked(dst, tenant, fork, arrays,
                                      src_addr, hold))


def _restore_slice_locked(dst: PlaneHandle, tenant: str, fork: dict,
                          arrays: dict, src_addr: str, hold: bool):
    cfg = fork["registry"]
    reg_d = dst.registry
    reg_d.create(tenant, qos=cfg["qos"],
                 frame_budget_per_s=cfg["frame_budget_per_s"],
                 byte_budget_per_s=cfg["byte_budget_per_s"],
                 block_edges=int(cfg["block_rows"]),
                 namespaces=cfg["namespaces"])
    if hold:
        # held until CUTOVER commits: dst must not shape a single
        # tenant frame while a pre-cutover rollback is still legal
        reg_d.hold(tenant)
    from kubedtn_tpu.api.types import Topology
    from kubedtn_tpu.topology.store import NotFoundError

    for rec in fork["topologies"]:
        meta = rec["manifest"]["metadata"]
        ns = meta.get("namespace", "default")
        name = meta["name"]
        try:
            dst.store.get(ns, name)
        except NotFoundError:
            topo = Topology.from_manifest(rec["manifest"])
            # placement moves with the tenant: the pod now lives on
            # dst (link ops realized here from now on)
            if topo.status.src_ip == src_addr:
                topo.status.src_ip = dst.addr
            dst.store.create(topo)
            dst.engine.set_alive(name, ns, dst.addr,
                                 topo.status.net_ns
                                 or f"/run/netns/{name}")
    entries = []
    props = np.asarray(arrays["props"], np.float32)
    for i, (pod_key, uid, sname, dname, shaped) in enumerate(
            fork["identities"]):
        entries.append((pod_key, int(uid), sname, dname,
                        props[i], bool(shaped)))
    peers = [((a, int(b)), (c, int(d)))
             for a, b, c, d in fork["peers"]]
    rows = dst.engine.adopt_rows(entries, peers=peers)
    # dynamic shaping state lands bit-exact; the clock columns are
    # rebased by the wall gap between the capture barrier and dst's
    # newest shaped tick (exactly the rolls dst's own dispatches did
    # NOT apply to these rows — 0, hence verbatim bits, when the
    # planes tick in lockstep). The floored max composes with
    # _roll_clocks' sequential maxes: max(max(x-a,f)-b,f) ==
    # max(x-(a+b),f).
    import jax.numpy as jnp

    fork_shaped = fork.get("fork_shaped_s")
    dst_shaped = dst.plane._last_shaped_s
    delta_us = np.float32(0.0)
    if fork_shaped is not None and dst_shaped is not None:
        delta_us = np.float32(
            max(0.0, (dst_shaped - fork_shaped) * 1e6))
    floor = np.float32(-1e7)
    t_last = np.maximum(
        np.asarray(arrays["t_last"], np.float32) - delta_us, floor)
    backlog = np.maximum(
        np.asarray(arrays["backlog_until"], np.float32) - delta_us,
        floor)
    engine = dst.engine
    with engine._lock:
        engine._flush_device_locked()
        st = engine._state
        rj = jnp.asarray(np.asarray(rows, np.int32))
        engine._state = dataclasses.replace(
            st,
            tokens=st.tokens.at[rj].set(
                jnp.asarray(arrays["tokens"])),
            t_last=st.t_last.at[rj].set(jnp.asarray(t_last)),
            corr=st.corr.at[rj].set(jnp.asarray(arrays["corr"])),
            pkt_count=st.pkt_count.at[rj].set(
                jnp.asarray(arrays["pkt_count"])),
            backlog_until=st.backlog_until.at[rj].set(
                jnp.asarray(backlog)))
    # the adopted rows' plane counters start from ZERO here: a reused
    # row must not leak its previous occupant's history into the
    # tenant's slice (migration RECONCILE and failover accounting both
    # sum the frozen src slice + this plane's live slice, so residue
    # would read as phantom delivery)
    plane = dst.plane
    cnt = plane.counters
    cap = int(np.asarray(cnt.tx_packets).shape[0])
    rz = [r for r in rows if r < cap]
    if rz:
        # columns may be np (post-compact permute) or jnp — normalize
        ri = jnp.asarray(np.asarray(rz, np.int32))
        plane.counters = type(cnt)(**{
            f.name: jnp.asarray(getattr(cnt, f.name)).at[ri].set(0.0)
            for f in dataclasses.fields(type(cnt))})
    # wires: a cross-node wire whose peer IS dst becomes local (the
    # frames that used to ride the src→dst gRPC hop now deliver on
    # dst directly); third-party peers are kept
    from kubedtn_tpu.wire.server import Wire

    for pod_key, uid, peer_ip, peer_intf_id, ifname in fork["wires"]:
        peer = "" if peer_ip == dst.addr else peer_ip

        def build(wire_id: int, _pk=pod_key, _uid=uid,
                  _peer=peer, _pid=peer_intf_id, _if=ifname):
            return Wire(wire_id=wire_id, uid=int(_uid),
                        pod_key=_pk, node_iface_name=_if,
                        peer_intf_id=int(_pid), peer_ip=_peer)

        dst.daemon.wires.get_or_create(pod_key, int(uid), build)
    return rows


def discard_partial_restore(dst: PlaneHandle, tenant: str,
                            fork: dict) -> None:
    """Remove everything a RESTORE may have left on `dst` for this
    fork: exactly the fork-captured rows / wires / store records and
    the tenant registration — never a neighbor wire that merely shares
    the namespace. Safe however little actually landed (every sub-step
    checks). The dst half of the pre-cutover crash contract, shared by
    the coordinator's `_undo_partial` and the fleet supervisor's
    resolution of a migration whose SRC died (the partial dst state is
    discarded before the evacuation re-restores from the journal
    fork)."""
    from kubedtn_tpu.topology.store import NotFoundError

    keys = [(pk, int(uid)) for pk, uid, *_rest in fork["identities"]]

    def _drop():
        return dst.engine.abandon_rows(keys)

    dst.plane.stage_update_round(_drop)
    # exactly the wires RESTORE creates (the fork capture) — never a
    # neighbor wire that merely shares the namespace on dst (e.g. the
    # peer-side wires of the tenant's cross-node links)
    for pod_key, uid, _peer_ip, _pid, _if in fork["wires"]:
        dst.daemon.wires.delete_by_key(pod_key, int(uid))
    for rec in fork["topologies"]:
        ns = rec["manifest"]["metadata"].get("namespace", "default")
        name = rec["manifest"]["metadata"]["name"]
        try:
            dst.store.get(ns, name)
        except NotFoundError:
            continue
        try:
            # clears placement + our finalizer so delete() completes
            dst.engine.set_alive(name, ns, "", "")
            dst.store.delete(ns, name)
        except NotFoundError:
            pass
    dst.registry.release_hold(tenant)
    dst.registry.delete(tenant)


@guarded_by("_lock", "_record")
class MigrationCoordinator:
    """One tenant's migration src → dst, journaled step by step.

    Single-writer: one thread drives migrate()/resume()/rollback();
    `_lock` guards the record against concurrent status() readers
    (`_fork_arrays` is deliberately unannotated — written only by the
    single driving thread, never read concurrently). Everything that
    touches a live plane goes through that plane's
    `stage_update_round` barrier (the PR 7 staging discipline), so no
    tick ever shapes against a half-applied migration step."""

    def __init__(self, tenant: str, src: PlaneHandle, dst: PlaneHandle,
                 journal_root: str, migration_id: str,
                 stats: MigrationStats | None = None, chaos=None,
                 settle=None, reconcile_timeout_s: float = 30.0) -> None:
        self.tenant = tenant
        self.src = src
        self.dst = dst
        self.journal_root = journal_root
        self.migration_id = migration_id
        self.stats = stats if stats is not None else MigrationStats()
        self.chaos = chaos
        # called between RECONCILE polls: explicit-clock embedders tick
        # their planes here; default is a real-time sleep
        self.settle = settle
        self.reconcile_timeout_s = reconcile_timeout_s
        self.log = get_logger("federation")
        self._lock = threading.Lock()
        self._record: dict = {
            "migration_id": migration_id,
            "tenant": tenant,
            "src": src.name,
            "dst": dst.name,
            "state": "running",      # running | done | rolled_back
            "steps_done": [],
            "resumed": 0,
            "rollbacks": 0,
            "step_seconds": {},
            "started_s": time.time(),
        }
        self._fork_arrays: dict | None = None

    # -- record plumbing ----------------------------------------------

    @classmethod
    def from_journal(cls, journal_root: str, migration_id: str,
                     handles: dict, **kw) -> "MigrationCoordinator":
        """Rebuild a coordinator from a committed record (daemon
        restart). `handles` maps plane name → PlaneHandle."""
        record, arrays = journal.load_record(journal_root, migration_id)
        src = handles[record["src"]]
        dst = handles[record["dst"]]
        co = cls(record["tenant"], src, dst, journal_root, migration_id,
                 **kw)
        co._record = record
        co._fork_arrays = arrays
        return co

    def record(self) -> dict:
        with self._lock:
            rec = dict(self._record)
            rec["steps_done"] = list(self._record["steps_done"])
            return rec

    def _commit(self, step: str | None = None, arrays: dict | None = None,
                **payload) -> None:
        """Update the record (marking `step` done when given) and
        journal it atomically — the step is committed only once this
        returns."""
        with self._lock:
            self._record.update(payload)
            if step is not None and step not in \
                    self._record["steps_done"]:
                self._record["steps_done"].append(step)
            record = dict(self._record)
            record["steps_done"] = list(self._record["steps_done"])
        journal.save_record(self.journal_root, self.migration_id,
                            record, arrays=arrays)

    def _chaos_step(self, step: str) -> None:
        if self.chaos is not None:
            self.chaos.on_migration_step(step)

    # -- drive --------------------------------------------------------

    def migrate(self) -> dict:
        """Run the state machine to completion from its current
        journaled position. Raises on an injected/real failure; the
        journal then resumes via `resume()`."""
        self.stats.add(attempts=1)
        return self._run_steps()

    def _run_steps(self) -> dict:
        fns = {"throttle": self._step_throttle, "fork": self._step_fork,
               "restore": self._step_restore,
               "cutover": self._step_cutover,
               "reconcile": self._step_reconcile,
               "release": self._step_release}
        for step in STEPS:
            with self._lock:
                done = step in self._record["steps_done"]
            if done:
                continue
            t0 = time.perf_counter()
            fns[step]()
            dt = time.perf_counter() - t0
            self.stats.add_step_seconds(step, dt)
            with self._lock:
                ss = self._record["step_seconds"]
                ss[step] = ss.get(step, 0.0) + dt
        self._commit(state="done", finished_s=time.time())
        self.stats.add(completed=1)
        out = self.record()
        self.log.info("migration done %s", _fields(
            id=self.migration_id, tenant=self.tenant,
            src=self.src.name, dst=self.dst.name,
            resumed=out["resumed"]))
        return out

    def resume(self) -> dict:
        """Continue after a crash/failure at any step. Before CUTOVER
        committed, src is still authoritative: the partial dst state is
        discarded bit-exactly and the migration re-runs from a fresh
        FORK. From CUTOVER on, the migration rolls forward (the
        remaining steps are idempotent)."""
        with self._lock:
            state = self._record["state"]
            done = list(self._record["steps_done"])
        if state == "done":
            return self.record()
        if state == "rolled_back":
            # an explicit abort is final: the tenant is serving on src
            # and must not be silently re-throttled and re-migrated by
            # a retry loop — start a NEW migration instead
            raise MigrationError(
                f"migration {self.migration_id} was rolled back; "
                f"start a new migration to retry")
        self.stats.add(resumed=1)
        with self._lock:
            self._record["resumed"] += 1
        if "cutover" not in done:
            self._undo_partial()
            self._commit(state="running", steps_done=[])
        return self._run_steps()

    def rollback(self) -> dict:
        """Abort back to src (only legal before CUTOVER commits —
        afterwards the make-before-break contract says roll forward).
        The tenant's stream continues on src byte-identical to a plane
        that never attempted the migration."""
        with self._lock:
            if "cutover" in self._record["steps_done"]:
                raise MigrationError(
                    "cutover already committed; resume() rolls forward")
        self._undo_partial()
        self.src.registry.release_hold(self.tenant)
        with self._lock:
            self._record["rollbacks"] += 1
        self._commit(state="rolled_back", steps_done=[],
                     finished_s=time.time())
        self.stats.add(rolled_back=1)
        self.log.info("migration rolled back %s", _fields(
            id=self.migration_id, tenant=self.tenant))
        return self.record()

    def _undo_partial(self) -> None:
        """Discard everything a pre-cutover crash may have left on dst
        (and return any transferred frames to src, in order). Safe to
        run however little actually happened: every sub-step checks
        before acting. The src hold stays — migrate() re-applies it
        anyway and rollback() releases it explicitly."""
        with self._lock:
            fork = self._record.get("fork")
        src_d = self.src.daemon
        if fork is None:
            return
        pairs = self._wire_pairs(fork, require_dst=False)
        # 1. redirects off first: arrivals stay on src from here on
        for ws, _wd in pairs:
            if ws is not None:
                src_d.wires._install_notify(ws)
        # 2. transferred frames back to the FRONT of src queues, FIFO
        for ws, wd in pairs:
            if ws is None or wd is None:
                continue
            moved = []
            while True:
                try:
                    moved.append(wd.ingress.popleft())
                except IndexError:
                    break
            if moved:
                ws.ingress.extendleft(reversed(moved))
        # 3. dst partial state: rows, wires, store records, tenant —
        # the shared dst half of the crash contract
        discard_partial_restore(self.dst, self.tenant, fork)

    # -- steps ---------------------------------------------------------

    def _step_throttle(self) -> None:
        reg = self.src.registry
        t = reg.get(self.tenant)
        if t is None:
            raise MigrationError(
                f"unknown tenant {self.tenant!r} on {self.src.name}")
        reg.hold(self.tenant)
        self._chaos_step("throttle")
        self._commit("throttle", throttle={
            "qos": t.qos,
            "frame_budget_per_s": t.frame_budget_per_s,
            "byte_budget_per_s": t.byte_budget_per_s,
        })

    def _step_fork(self) -> None:
        src = self.src
        reg = src.registry
        engine = src.engine

        def _capture():
            t = reg.get(self.tenant)
            spaces = sorted(t.namespaces)
            rows = reg.rows_of(self.tenant)
            with engine._lock:
                engine._flush_device_locked()
                st = engine._state
                # incrementally-maintained inverse of _pod_ids: the
                # fork barrier must be O(tenant rows), and rebuilding
                # the whole inverse map here was an O(all pods) walk
                # inside the tick-lock barrier (dtnscale scost)
                id_to_name = engine._pod_names
                src_col = np.asarray(st.src)
                dst_col = np.asarray(st.dst)
                identities = []
                keyset = set()
                for r in rows.tolist():
                    pod_key, uid = engine._row_owner[r]
                    keyset.add((pod_key, uid))
                    identities.append([
                        pod_key, int(uid),
                        id_to_name.get(int(src_col[r]), pod_key),
                        id_to_name.get(int(dst_col[r]), pod_key),
                        bool(r in engine._shaped_rows)])
                # walk the TENANT's keys, not the whole peer registry
                # (sorted for a deterministic fork record)
                peers = []
                for k in sorted(keyset):
                    p = engine._peer.get(k)
                    if p is not None and p in keyset:
                        peers.append([k[0], k[1], p[0], p[1]])
                arrays = {
                    "rows": rows.astype(np.int64),
                    "props": np.asarray(st.props)[rows],
                    "tokens": np.asarray(st.tokens)[rows],
                    "t_last": np.asarray(st.t_last)[rows],
                    "corr": np.asarray(st.corr)[rows],
                    "pkt_count": np.asarray(st.pkt_count)[rows],
                    "backlog_until": np.asarray(st.backlog_until)[rows],
                }
            topologies = []
            for ns in spaces:
                for topo in src.store.list(ns):
                    topologies.append({
                        "manifest": topo.to_manifest(),
                        "finalizers": list(topo.finalizers),
                    })
            wires = [[w.pod_key, int(w.uid), w.peer_ip,
                      int(w.peer_intf_id), w.node_iface_name]
                     for w in src.daemon.wires.in_namespaces(spaces)]
            fork = {
                "identities": identities,
                "peers": peers,
                "topologies": topologies,
                "wires": wires,
                "registry": {
                    "qos": t.qos,
                    "frame_budget_per_s": t.frame_budget_per_s,
                    "byte_budget_per_s": t.byte_budget_per_s,
                    "block_rows": int(t.block_rows),
                    "namespaces": spaces,
                },
                "fork_shaped_s": src.plane._last_shaped_s,
                "counters_at_fork": reg.tenant_counters(src.plane,
                                                        self.tenant),
            }
            return fork, arrays

        fork, arrays = src.plane.stage_update_round(
            _capture, cause="migration_fork",
            migration=self.migration_id, tenant=self.tenant,
            rows=int(reg.rows_of(self.tenant).size))
        self._fork_arrays = arrays
        self._chaos_step("fork")
        self._commit("fork", arrays=arrays, fork=fork)

    def _step_restore(self) -> None:
        dst = self.dst
        with self._lock:
            fork = self._record["fork"]
        arrays = self._fork_arrays
        if arrays is None:
            _rec, arrays = journal.load_record(self.journal_root,
                                               self.migration_id)
            self._fork_arrays = arrays

        def _apply():
            return len(restore_tenant_slice(
                dst, self.tenant, fork, arrays, self.src.addr,
                hold=True))

        n_rows = dst.plane.stage_update_round(
            _apply, cause="migration_restore",
            migration=self.migration_id, tenant=self.tenant,
            rows=int(len(arrays["rows"])))
        self._chaos_step("restore")
        self._commit("restore", restored_rows=int(n_rows))

    def _wire_pairs(self, fork: dict, require_dst: bool = True):
        pairs = []
        for pod_key, uid, _peer_ip, _pid, _if in fork["wires"]:
            ws = self.src.daemon.wires.get_by_key(pod_key, int(uid))
            wd = self.dst.daemon.wires.get_by_key(pod_key, int(uid))
            if require_dst and (ws is None or wd is None):
                continue
            pairs.append((ws, wd))
        return pairs

    @staticmethod
    def _transfer(ws, wd) -> int:
        """Move every queued ingress entry src→dst wire, FIFO, counting
        frames (a bulk FrameSeg entry counts its window)."""
        from kubedtn_tpu.wire.server import _entry_frames

        moved = 0
        while True:
            try:
                e = ws.ingress.popleft()
            except IndexError:
                return moved
            wd.ingress.append(e)
            moved += _entry_frames(e)

    def _step_cutover(self) -> None:
        with self._lock:
            fork = self._record["fork"]
        dst_d = self.dst.daemon

        def _cut():
            pairs = self._wire_pairs(fork)
            moved = 0
            for ws, wd in pairs:
                moved += self._transfer(ws, wd)
            # make-before-break: dst is fully able to serve (RESTORE
            # committed) before the redirect breaks the src path. A
            # producer still holding the src wire forwards through the
            # redirect from its very next append.
            for ws, wd in pairs:
                ing = ws.ingress
                if hasattr(ing, "_notify"):
                    def redirect(_ws=ws, _wd=wd):
                        self._transfer(_ws, _wd)

                    ing._notify = redirect
            # close the race: entries landed between the sweep and the
            # redirect install sit unnotified on src — one more sweep
            for ws, wd in pairs:
                moved += self._transfer(ws, wd)
            return moved

        moved = self.src.plane.stage_update_round(
            _cut, cause="migration_cutover",
            migration=self.migration_id, tenant=self.tenant)
        self._chaos_step("cutover")
        prev = 0
        with self._lock:
            prev = self._record.get("cutover", {}).get(
                "transferred_frames", 0)
        self._commit("cutover",
                     cutover={"transferred_frames": int(moved) + prev})

    # -- reconcile helpers --------------------------------------------

    def _src_residue(self, spaces: set[str], wire_ids: set[int],
                     peer_addrs: set[str]) -> dict:
        """Tenant frames still owed by src: queued ingress (swept to
        dst as a side effect), holdback entries, delay-line frames,
        peer egress buffers."""
        src = self.src
        plane = src.plane
        swept = 0
        with self._lock:
            fork = self._record["fork"]
        for ws, wd in self._wire_pairs(fork):
            swept += self._transfer(ws, wd)
        hold = pend = 0
        with plane._tick_lock:
            for wid in plane._holdback:
                if wid in wire_ids:
                    hold += 1
            for entry in plane._pending.values():
                if entry[0].partition("/")[0] in spaces:
                    pend += int(entry[4])
            for item in plane._heap:
                if item[2].partition("/")[0] in spaces:
                    pend += 1
        peer_buffered = 0
        breaker_open = False
        for addr in peer_addrs:
            sender = plane._peer_senders.get(addr)
            if sender is None:
                continue
            peer_buffered += sender.buffered
            if sender.breaker.state != fault.CLOSED:
                breaker_open = True
        return {"swept": swept, "holdback": hold, "pending": pend,
                "peer_buffered": peer_buffered,
                "breaker_open": breaker_open}

    def _step_reconcile(self) -> None:
        src, dst = self.src, self.dst
        with self._lock:
            fork = self._record["fork"]
        spaces = set(fork["registry"]["namespaces"])
        wire_ids = {ws.wire_id for ws, _ in
                    self._wire_pairs(fork, require_dst=False)
                    if ws is not None}
        peer_addrs = {w[2] for w in fork["wires"] if w[2]}
        # cutover committed: dst may serve — release its hold first so
        # the transferred backlog starts draining while src residuals
        # finish
        dst.registry.release_hold(self.tenant)
        deadline = time.monotonic() + self.reconcile_timeout_s
        while True:
            res = self._src_residue(spaces, wire_ids, peer_addrs)
            if (res["holdback"] == 0 and res["pending"] == 0
                    and res["peer_buffered"] == 0):
                break
            now = time.monotonic()
            if now >= deadline:
                if res["breaker_open"]:
                    # breaker-aware: an OPEN src→peer breaker means the
                    # outage buffer is still legitimately parked —
                    # extend to the next half-open probe instead of
                    # failing a migration the fault layer will finish
                    probe = max((src.plane._peer_senders[a].breaker
                                 .time_to_probe()
                                 for a in peer_addrs
                                 if a in src.plane._peer_senders),
                                default=0.0)
                    deadline = now + max(probe, 0.05) + 1.0
                else:
                    raise MigrationError(
                        f"reconcile: src residuals did not drain: {res}")
            if self.settle is not None:
                self.settle()
            else:
                time.sleep(0.01)
        counters_src = src.registry.tenant_counters(src.plane,
                                                    self.tenant)
        counters_dst = dst.registry.tenant_counters(dst.plane,
                                                    self.tenant)
        win_src = src.registry.tenant_window(src.plane, self.tenant)
        win_dst = dst.registry.tenant_window(dst.plane, self.tenant)
        self._chaos_step("reconcile")
        self.stats.add(bytes_reconciled=(
            counters_src["delivered_bytes"]
            + counters_dst["delivered_bytes"]))
        self._commit("reconcile", reconcile={
            # the src slice is FROZEN here — RELEASE frees the rows and
            # deregisters the tenant, after which the slice is gone
            "counters_src": counters_src,
            "counters_dst_at_reconcile": counters_dst,
            "delivered_src_frames": counters_src["delivered_packets"],
            "delivered_src_bytes": counters_src["delivered_bytes"],
            "window_src": win_src,
            "window_dst": win_dst,
            "peer_fault_stats": src.plane.peer_fault_stats(),
        })

    def _drop_store_record(self, handle: PlaneHandle, ns: str,
                           name: str) -> None:
        from kubedtn_tpu.topology.store import NotFoundError

        try:
            handle.store.get(ns, name)
        except NotFoundError:
            return
        try:
            # clears placement + our finalizer so delete() completes
            handle.engine.set_alive(name, ns, "", "")
            handle.store.delete(ns, name)
        except NotFoundError:
            pass

    def _step_release(self) -> None:
        src = self.src
        with self._lock:
            fork = self._record["fork"]
        keys = [(pk, int(uid)) for pk, uid, *_rest in fork["identities"]]
        spaces = set(fork["registry"]["namespaces"])

        def _free():
            return src.engine.abandon_rows(keys)

        freed = src.plane.stage_update_round(_free)
        # delivered-but-unconsumed EGRESS frames ride to dst before the
        # wires go: egress is the consumer's delivery buffer, and the
        # consumer re-attaches to the dst wire — deleting a src wire
        # must never delete deliveries the consumer has not picked up
        # yet (found by the fleet_rolling_upgrade zero-loss drive: a
        # consumer that polls slower than the migration completes lost
        # every frame delivered during the move). Idempotent: a resumed
        # RELEASE finds the already-moved egress empty.
        handed_off = 0
        for ws in src.daemon.wires.in_namespaces(spaces):
            wd = self.dst.daemon.wires.get_by_key(ws.pod_key, ws.uid)
            if wd is None:
                continue
            moved = []
            while True:
                try:
                    moved.append(ws.egress.popleft())
                except IndexError:
                    break
            if moved:
                # PREPEND, order preserved: src delivered these before
                # dst's post-cutover deliveries, and a consumer slower
                # than the migration must still read the wire FIFO
                # (same discipline as the rollback path's
                # ingress.extendleft)
                wd.egress.extendleft(reversed(moved))
                handed_off += len(moved)
        pod_keys = {w.pod_key
                    for w in src.daemon.wires.in_namespaces(spaces)}
        for pk in pod_keys:
            src.daemon.wires.delete_by_pod(pk)
        for rec in fork["topologies"]:
            meta = rec["manifest"]["metadata"]
            self._drop_store_record(src, meta.get("namespace",
                                                  "default"),
                                    meta["name"])
        src.registry.release_hold(self.tenant)
        src.registry.delete(self.tenant)
        self._chaos_step("release")
        self._commit("release", released_rows=int(freed),
                     egress_handed_off=int(handed_off))

    # -- accounting ----------------------------------------------------

    @staticmethod
    def _accounted(counters: dict) -> float:
        """Frames with a TERMINAL outcome in one plane's counter
        slice: delivered, or dropped with a recorded cause (netem
        loss / TBF queue / egress ring). Every fed frame must reach
        exactly one terminal outcome on exactly one plane."""
        return (counters.get("delivered_packets", 0.0)
                + counters.get("dropped_loss", 0.0)
                + counters.get("dropped_queue", 0.0)
                + counters.get("dropped_ring", 0.0))

    def check_accounting(self, fed_frames: int) -> dict:
        """The byte-exact reconciliation rule: every fed frame reached
        a terminal outcome (delivered, or dropped with cause) on
        exactly one plane — `fed == accounted_src + accounted_dst`
        (which on lossless links is exactly fed == delivered_src +
        delivered_dst). The src slice is frozen at RECONCILE (gone
        after RELEASE); dst is read live. Updates the
        kubedtn_migration_accounting_mismatch gauge."""
        with self._lock:
            rec = self._record.get("reconcile")
        if rec is None:
            raise MigrationError("reconcile has not run")
        a_src = self._accounted(rec["counters_src"])
        d_src = float(rec["delivered_src_frames"])
        t = self.dst.registry.get(self.tenant)
        counters_dst = (self.dst.registry.tenant_counters(
            self.dst.plane, self.tenant) if t is not None
            else rec["counters_dst_at_reconcile"])
        a_dst = self._accounted(counters_dst)
        d_dst = float(counters_dst.get("delivered_packets", 0.0))
        mismatch = float(fed_frames) - (a_src + a_dst)
        self.stats.set_mismatch(abs(mismatch))
        out = {"fed": int(fed_frames),
               "accounted_src": a_src, "accounted_dst": a_dst,
               "delivered_src": d_src, "delivered_dst": d_dst,
               "mismatch": mismatch}
        with self._lock:
            self._record["accounting"] = out
        return out


@guarded_by("_lock", "_handles", "_coords", "_seq", "_active")
class FederationController:
    """The placement layer's migration surface for one process's
    member planes: register PlaneHandles, run/resume migrations, and
    answer `Local.MigrateTenant` / `Local.MigrationStatus` for every
    registered daemon. Extensible to N planes — a migration only ever
    involves the (src, dst) pair it names."""

    def __init__(self, journal_root: str,
                 stats: MigrationStats | None = None,
                 chaos=None) -> None:
        self.journal_root = journal_root
        self.stats = stats if stats is not None else MigrationStats()
        self.chaos = chaos
        # set by the fleet supervisor (federation.supervisor.attach):
        # called with (tenant, dst_plane, qos) after every COMPLETED
        # migration so the placement ledger tracks manual `kdt migrate`
        # moves too, not only supervisor-driven ones
        self.placement_hook = None
        self._lock = threading.Lock()
        self._handles: dict[str, PlaneHandle] = {}
        self._coords: dict[str, MigrationCoordinator] = {}
        # tenants with a migrate()/resume() currently RUNNING: the
        # state machine is single-writer per tenant — a concurrent
        # second RPC refuses loudly instead of interleaving barriers
        self._active: set[str] = set()
        self._seq = 0

    def register(self, handle: PlaneHandle) -> PlaneHandle:
        with self._lock:
            self._handles[handle.name] = handle
        handle.daemon.federation = self
        return handle

    def handle(self, name: str) -> PlaneHandle:
        with self._lock:
            h = self._handles.get(name)
        if h is None:
            raise MigrationError(f"unknown federation plane {name!r}")
        return h

    def plane_names(self) -> list[str]:
        with self._lock:
            return sorted(self._handles)

    def _notify_placement(self, tenant: str, dst: str) -> None:
        hook = self.placement_hook
        if hook is None:
            return
        try:
            t = self.handle(dst).registry.get(tenant)
            hook(tenant, dst, t.qos if t is not None else None)
        except Exception:
            from kubedtn_tpu.utils.logging import fields, get_logger

            # the move itself succeeded; a lagging ledger is the
            # supervisor's to reconcile on its next attach/sweep
            get_logger("federation").exception(
                "placement hook failed (ledger may lag) %s",
                fields(tenant=tenant, dst=dst))

    def plane_name_of(self, daemon) -> str:
        """The registered plane name serving `daemon` (the RPC surface
        defaults a MigrateRequest's empty src to the serving plane)."""
        with self._lock:
            for name, h in self._handles.items():
                if h.daemon is daemon:
                    return name
        raise MigrationError("daemon is not a registered plane")

    def _begin(self, tenant: str) -> None:
        with self._lock:
            if tenant in self._active:
                raise MigrationError(
                    f"a migration of tenant {tenant!r} is already "
                    f"running")
            self._active.add(tenant)

    def _end(self, tenant: str) -> None:
        with self._lock:
            self._active.discard(tenant)

    def _new_migration_id(self, tenant: str,
                          requested: str | None) -> str:
        """Allocate an id that names NO existing journal record: the
        in-memory sequence resets on restart, and silently reusing an
        id would rename a committed record's history away (and attach
        its carried-forward fork.npz to the new migration)."""
        with self._lock:
            if requested:
                if os.path.isdir(journal.record_dir(self.journal_root,
                                                    requested)):
                    raise MigrationError(
                        f"migration id {requested!r} already has a "
                        f"journal record; resume it or pick a new id")
                return requested
            while True:
                self._seq += 1
                mid = f"{tenant}-{self._seq:04d}"
                if not os.path.isdir(journal.record_dir(
                        self.journal_root, mid)):
                    return mid

    def migrate(self, tenant: str, src: str, dst: str,
                migration_id: str | None = None, settle=None,
                reconcile_timeout_s: float = 30.0) -> dict:
        if src == dst:
            raise MigrationError("src and dst are the same plane")
        hs, hd = self.handle(src), self.handle(dst)
        mid = self._new_migration_id(tenant, migration_id)
        co = MigrationCoordinator(
            tenant, hs, hd, self.journal_root, mid, stats=self.stats,
            chaos=self.chaos, settle=settle,
            reconcile_timeout_s=reconcile_timeout_s)
        with self._lock:
            self._coords[mid] = co
        self._begin(tenant)
        try:
            rec = co.migrate()
        finally:
            self._end(tenant)
        if rec.get("state") == "done":
            self._notify_placement(tenant, dst)
        return rec

    def coordinator(self, migration_id: str) -> MigrationCoordinator:
        with self._lock:
            co = self._coords.get(migration_id)
            handles = dict(self._handles)
        if co is None:
            co = MigrationCoordinator.from_journal(
                self.journal_root, migration_id, handles,
                stats=self.stats, chaos=self.chaos)
            with self._lock:
                # two racing rebuilds: first publish wins, both callers
                # get the SAME coordinator (never two state machines
                # over one journal record)
                co = self._coords.setdefault(migration_id, co)
        return co

    def resume(self, migration_id: str) -> dict:
        co = self.coordinator(migration_id)
        self._begin(co.tenant)
        try:
            rec = co.resume()
        finally:
            self._end(co.tenant)
        if rec.get("state") == "done":
            self._notify_placement(co.tenant, co.dst.name)
        return rec

    # how long a completed migration's frozen src window slice keeps
    # stitching into the fleet SLO view. The slice exists to make the
    # view CONTINUOUS across the move; burn rates and budgets are
    # WINDOWED, so a fixed pre-move slice must age out once the dst's
    # own ring covers the alerting windows — without a bound, a loss
    # in the pre-move window would depress the tenant's fleet budget
    # forever while every live plane reads clean. The default is
    # sized an order of magnitude above the default slow alerting
    # window (12 × 1s telemetry windows): wide enough that the view
    # is continuous while the dst ring fills, narrow enough that a
    # stale pre-move loss ages out promptly.
    FROZEN_WINDOW_MAX_AGE_S = 120.0

    def frozen_windows(self, tenant: str = "", src: str = "",
                       max_age_s: float | None = None) -> list[tuple]:
        """The SLO plane's migration stitch input: every RECENTLY
        completed record's RECONCILE-frozen src window slice, as
        (src_plane, tenant, window_src, qos) tuples (slo.fleet merges
        them with the live planes' verdicts so a migrated tenant's
        fleet view is continuous across the move). One pass over the
        journal metas; records predating the window `hist` field are
        skipped — the merge cannot stitch what was never frozen — and
        records older than `max_age_s` (default
        FROZEN_WINDOW_MAX_AGE_S) have aged out of the windowed view."""
        horizon = (self.FROZEN_WINDOW_MAX_AGE_S
                   if max_age_s is None else float(max_age_s))
        now = time.time()
        out = []
        for rec in self.status(tenant=tenant):
            if rec.get("state") != "done":
                continue
            if src and rec.get("src") != src:
                continue
            done_s = rec.get("finished_s")
            if done_s is not None and now - float(done_s) > horizon:
                continue
            win = (rec.get("reconcile") or {}).get("window_src")
            if not win or not win.get("hist"):
                continue
            qos = ((rec.get("fork") or {}).get("registry")
                   or {}).get("qos")
            out.append((rec.get("src", ""), rec.get("tenant", ""),
                        win, qos))
        return out

    def status(self, migration_id: str = "",
               tenant: str = "") -> list[dict]:
        with self._lock:
            coords = dict(self._coords)
        known = {mid: co.record() for mid, co in coords.items()}
        for mid in journal.list_records(self.journal_root):
            if mid not in known:
                try:
                    known[mid] = journal.load_record_meta(
                        self.journal_root, mid)
                except journal.JournalError:
                    continue
        out = [r for mid, r in sorted(known.items())
               if (not migration_id or mid == migration_id)
               and (not tenant or r.get("tenant") == tenant)]
        return out
