"""Federated serving planes with zero-loss live tenant migration.

Many daemons — each a multi-tenant (optionally sharded) plane — under
a placement layer that moves tenants between them without losing a
frame. See federation.migrate for the crash-safe migration state
machine and federation.journal for its checkpoint-grade record
persistence; ARCHITECTURE.md "Federation & live migration" documents
the per-step crash contract and the accounting-reconciliation rule.
"""

from kubedtn_tpu.federation.migrate import (STEPS, FederationController,
                                            MigrationCoordinator,
                                            MigrationError,
                                            MigrationStats, PlaneHandle,
                                            restore_tenant_slice,
                                            stats_for)

__all__ = ["STEPS", "FederationController", "MigrationCoordinator",
           "MigrationError", "MigrationStats", "PlaneHandle",
           "restore_tenant_slice", "stats_for"]
