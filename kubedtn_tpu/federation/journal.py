"""Migration journal — crash-safe persistence for one live migration.

Every step of the migration state machine (federation.migrate) writes a
**migration record** here before the step counts as committed: a JSON
manifest (state machine position, captured identities, accounting
snapshots) plus an optional npz of captured device-row arrays, staged
in a temp directory with a per-file sha256 in the manifest and swapped
into place with atomic renames — the exact double-crash discipline of
`checkpoint.save` (old → `.prev`, tmp → path, `.prev` pruned only after
the new generation lands). A daemon killed at ANY instant leaves either
the new complete record, the previous complete one, or nothing — never
a torn mix — so a restarted coordinator resumes from the last COMMITTED
step, and the resume rules in federation.migrate make that safe.

Layout of one record directory (`<root>/<migration_id>/`):
  manifest.json — the record dict + per-file sha256 checksums
  fork.npz      — captured tenant row arrays (present once FORK commits)
"""

from __future__ import annotations

import json
import os
import re
import shutil

import numpy as np

# one discipline, one implementation: the checkpoint module's staging /
# checksum / pid-sweep helpers are the audited originals
from kubedtn_tpu.checkpoint import _fsync_path, _pid_alive, _sha256_file

_PREV_SUFFIX = ".prev"
_TMP_PREFIX = ".mig-tmp-"


class JournalError(Exception):
    """A migration record could not be read or written."""


class JournalMissingError(JournalError):
    """No record exists for the migration id (nothing to resume)."""


class JournalCorruptError(JournalError):
    """A record exists but neither generation passes validation."""


def record_dir(root: str, migration_id: str) -> str:
    return os.path.join(os.path.abspath(root), migration_id)


def list_records(root: str) -> list[str]:
    """Migration ids with a (possibly only-`.prev`) record under root."""
    root = os.path.abspath(root)
    try:
        entries = os.listdir(root)
    except OSError:
        return []
    out = set()
    for e in entries:
        if e.startswith(_TMP_PREFIX):
            continue
        name = e[:-len(_PREV_SUFFIX)] if e.endswith(_PREV_SUFFIX) else e
        if os.path.isdir(os.path.join(root, e)):
            out.add(name)
    return sorted(out)


def _read_manifest(dirpath: str) -> dict:
    mpath = os.path.join(dirpath, "manifest.json")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except FileNotFoundError as e:
        raise JournalMissingError(f"no migration manifest at {mpath}") \
            from e
    except (json.JSONDecodeError, UnicodeDecodeError, OSError) as e:
        raise JournalCorruptError(
            f"unreadable migration manifest {mpath}: {e}") from e
    if not isinstance(manifest, dict) or "record" not in manifest:
        raise JournalCorruptError(
            f"migration manifest {mpath} lacks a record section")
    return manifest


def _resolve(dirpath: str) -> tuple[str, dict]:
    """The directory holding the newest COMMITTED record generation:
    the path itself when valid, else the `.prev` a crash between save's
    two renames left behind (same resolution rule as checkpoint)."""
    try:
        return dirpath, _read_manifest(dirpath)
    except JournalError as primary:
        prev = dirpath + _PREV_SUFFIX
        try:
            return prev, _read_manifest(prev)
        except JournalError:
            raise primary from None


def save_record(root: str, migration_id: str, record: dict,
                arrays: dict | None = None) -> None:
    """Commit one record generation atomically. `record` must be
    JSON-serializable; `arrays` (optional) lands in fork.npz. When
    `arrays` is None and the current committed generation carries a
    fork.npz, that file is CARRIED FORWARD into the new generation —
    a later step's journal write never drops the fork capture."""
    dirpath = record_dir(root, migration_id)
    parent = os.path.dirname(dirpath)
    os.makedirs(parent, exist_ok=True)
    # sweep staging leaked by crashed saves (exact <prefix><id>-<pid>,
    # live pids spared — the checkpoint.save sweep discipline)
    pat = re.compile(
        re.escape(f"{_TMP_PREFIX}{migration_id}-") + r"(\d+)$")
    for entry in os.listdir(parent):
        m = pat.fullmatch(entry)
        if m is None:
            continue
        pid = int(m.group(1))
        if pid != os.getpid() and _pid_alive(pid):
            continue
        shutil.rmtree(os.path.join(parent, entry), ignore_errors=True)
    tmp = os.path.join(parent,
                       f"{_TMP_PREFIX}{migration_id}-{os.getpid()}")
    os.makedirs(tmp)
    try:
        if arrays is not None:
            np.savez_compressed(os.path.join(tmp, "fork.npz"), **arrays)
        else:
            try:
                cur, cur_manifest = _resolve(dirpath)
            except JournalError:
                cur, cur_manifest = None, None
            if cur is not None and os.path.exists(
                    os.path.join(cur, "fork.npz")):
                _verify(cur, cur_manifest, "fork.npz")
                shutil.copy2(os.path.join(cur, "fork.npz"),
                             os.path.join(tmp, "fork.npz"))
        checksums = {
            fname: _sha256_file(os.path.join(tmp, fname))
            for fname in sorted(os.listdir(tmp))
        }
        manifest = {"record": record, "checksums": checksums}
        mpath = os.path.join(tmp, "manifest.json")
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            try:
                os.fsync(f.fileno())
            except OSError:
                pass
        for fname in checksums:
            _fsync_path(os.path.join(tmp, fname))
        _fsync_path(tmp)
        prev = dirpath + _PREV_SUFFIX
        if os.path.isdir(dirpath):
            shutil.rmtree(prev, ignore_errors=True)
            os.rename(dirpath, prev)
        os.rename(tmp, dirpath)
        _fsync_path(parent)
        shutil.rmtree(prev, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def _verify(dirpath: str, manifest: dict, fname: str) -> None:
    want = manifest.get("checksums", {}).get(fname)
    if want is None:
        raise JournalCorruptError(
            f"{fname} in {dirpath} has no recorded checksum")
    try:
        got = _sha256_file(os.path.join(dirpath, fname))
    except OSError as e:
        raise JournalCorruptError(
            f"unreadable migration file {dirpath}/{fname}: {e}") from e
    if got != want:
        raise JournalCorruptError(
            f"checksum mismatch for {dirpath}/{fname}: "
            f"manifest {want[:12]}…, file {got[:12]}…")


def load_record(root: str, migration_id: str
                ) -> tuple[dict, dict | None]:
    """(record, fork arrays or None) from the newest committed
    generation, checksum-verified. Raises JournalMissingError when no
    generation exists, JournalCorruptError on damage."""
    dirpath, manifest = _resolve(record_dir(root, migration_id))
    record = manifest["record"]
    arrays = None
    fpath = os.path.join(dirpath, "fork.npz")
    if os.path.exists(fpath):
        _verify(dirpath, manifest, "fork.npz")
        try:
            with np.load(fpath) as z:
                arrays = {k: z[k] for k in z.files}
        except Exception as e:
            raise JournalCorruptError(
                f"damaged fork.npz in {dirpath}: {e}") from e
    return record, arrays


def load_record_meta(root: str, migration_id: str) -> dict:
    """The record dict alone — no fork.npz read, no array checksum.
    The status/poll path: a MigrationStatus scrape over N historical
    records must not re-read and re-hash N fork captures it is going
    to discard."""
    _dirpath, manifest = _resolve(record_dir(root, migration_id))
    return manifest["record"]


def drop_record(root: str, migration_id: str) -> None:
    """Remove a finished migration's record (both generations)."""
    dirpath = record_dir(root, migration_id)
    shutil.rmtree(dirpath, ignore_errors=True)
    shutil.rmtree(dirpath + _PREV_SUFFIX, ignore_errors=True)


__all__ = ["JournalError", "JournalMissingError", "JournalCorruptError",
           "record_dir", "list_records", "save_record", "load_record",
           "load_record_meta", "drop_record"]
