"""Fleet supervisor — plane health, automated tenant evacuation, and
zero-loss rolling upgrades.

Turns N independent daemons into ONE operable fleet:

- **Health watching.** Every registered plane is probed through the
  rich `Local.Health` surface (heartbeat age, watchdog stalls,
  degradation-ladder rung, tick errors, backlog, tenant count,
  capacity headroom — signals that until now only the Prometheus
  endpoint exported) and run through a suspicion state machine with
  hysteresis:

      healthy → suspect   after `suspect_after` consecutive probe
                          failures, OR `suspect_after` consecutive
                          degraded answers (serving=False: bottom
                          ladder rung / watchdog stall)
      suspect → dead      only via HARD failures (the probe itself
                          raising) — `dead_after` consecutive; a plane
                          that still answers is sick, never dead
      suspect → healthy   after `healthy_after` consecutive clean
                          probes (hysteresis: one good answer never
                          clears suspicion)
      dead    → (final)   until `mark_restarted` — a zombie coming
                          back must not silently double-serve tenants
                          that were evacuated off it

- **Placement.** A crash-safe journaled ledger (federation.placement —
  tenant→plane, the checkpoint `.prev` double-crash discipline) plus a
  deterministic score policy (QoS pressure, admitted load, capacity
  headroom). Rebalance decisions execute as PR 11 live migrations.

- **Evacuation.** A plane declared DEAD has its tenants cold-restored
  onto survivors with NO operator action: in-flight migrations
  touching the dead plane resolve per the PR 11 crash contract
  (pre-cutover → rollback / re-fork elsewhere from the journal's fork
  capture; post-cutover → roll forward), then every placed tenant is
  sliced out of the dead plane's last crash-consistent checkpoint
  (bounded by the `--checkpoint-interval` autosave — the RPO) and
  replayed through the ONE restore implementation
  (migrate.restore_tenant_slice), cumulative delivery counters riding
  with the rows. The checkpoint-to-death gap is REPORTED as
  exactly-accounted loss per tenant, never hidden:

      fed == delivered_src + delivered_dst + reported_lost

  with `delivered_src` the durable checkpoint counters,
  `delivered_dst` the survivor's live counters past them, and the
  `kubedtn_migration_accounting_mismatch` gauge extended to failover
  (nonzero ⇔ the internal accounting over-explains the feed — a
  duplicate-delivery bug, the thing the discipline exists to catch).

- **Rolling upgrade** (`kdt fleet upgrade`): cordon → drain every
  tenant via live migration → restart the daemon binary (the handle's
  `restarter` hook: checkpoint → teardown → rebuild → new server) →
  health-verify (consecutive clean probes) → refill → next plane.
  Zero frame loss for every live-migrated tenant — each move is a full
  PR 11 migration with byte-exact accounting.

- **Orphan resume.** On (re)start the supervisor resumes every
  journaled migration left `running` by a crash — an interrupted
  migration no longer waits for an operator to run
  `kdt migrate --resume --id`. Rolled-back records stay refused.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from kubedtn_tpu import checkpoint as ckpt
from kubedtn_tpu.contracts import guarded_by
from kubedtn_tpu.federation import journal
from kubedtn_tpu.federation.migrate import (MigrationCoordinator,
                                            MigrationError,
                                            discard_partial_restore,
                                            restore_tenant_slice)
from kubedtn_tpu.federation.placement import (PlacementError,
                                              PlacementLedger,
                                              choose_plane)
from kubedtn_tpu.utils.logging import fields as _fields
from kubedtn_tpu.utils.logging import get_logger

HEALTHY = "healthy"
SUSPECT = "suspect"
DEAD = "dead"
RESTARTING = "restarting"   # intentional (upgrade): sweep skips it


class FleetError(RuntimeError):
    """A fleet-supervision operation could not complete."""


@guarded_by("_lock", "probes", "probe_failures", "sweeps", "evacuations",
            "evacuated_tenants", "evacuated_rows", "pending_restored",
            "orphans_resumed", "upgrades", "upgrade_migrations",
            "reported_lost", "transitions")
class FleetStats:
    """Cumulative fleet counters for the kubedtn_fleet_* Prometheus
    series (metrics.FleetStatsCollector)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.probes = 0
        self.probe_failures = 0
        self.sweeps = 0
        self.evacuations = 0
        self.evacuated_tenants = 0
        self.evacuated_rows = 0
        self.pending_restored = 0
        self.orphans_resumed = 0
        self.upgrades = 0
        self.upgrade_migrations = 0
        # GAUGE: reported_lost of the latest failover accounting check
        # — honest loss is REPORTED here, never hidden (the mismatch
        # gauge stays 0; this one carries the RPO gap)
        self.reported_lost = 0.0
        self.transitions: dict[str, int] = {}

    def add(self, **kw) -> None:
        with self._lock:
            for k, v in kw.items():
                setattr(self, k, getattr(self, k) + v)

    def add_transition(self, to_state: str) -> None:
        with self._lock:
            self.transitions[to_state] = \
                self.transitions.get(to_state, 0) + 1

    def set_reported_lost(self, v: float) -> None:
        with self._lock:
            self.reported_lost = float(v)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "probes": self.probes,
                "probe_failures": self.probe_failures,
                "sweeps": self.sweeps,
                "evacuations": self.evacuations,
                "evacuated_tenants": self.evacuated_tenants,
                "evacuated_rows": self.evacuated_rows,
                "pending_restored": self.pending_restored,
                "orphans_resumed": self.orphans_resumed,
                "upgrades": self.upgrades,
                "upgrade_migrations": self.upgrade_migrations,
                "reported_lost": self.reported_lost,
                "transitions": dict(self.transitions),
            }


def grpc_probe(addr: str, timeout_s: float = 2.0):
    """A `PlaneHandle.probe` hook that dials the plane's Local.Health
    RPC — the out-of-process probe (a dead daemon fails the dial, the
    hard-failure signal the suspicion machine wants). Each probe opens
    and closes its own channel: a cached channel to a dead peer can
    report stale readiness."""
    def probe() -> dict:
        from kubedtn_tpu.wire import proto as pb
        from kubedtn_tpu.wire.client import DaemonClient

        client = DaemonClient(addr)
        try:
            r = client.Health(pb.HealthRequest(), timeout=timeout_s)
        finally:
            client.close()
        if not r.ok:
            raise FleetError(f"health probe of {addr}: {r.error}")
        return {
            "node": r.node,
            "running": bool(r.running),
            "serving": bool(r.serving),
            "heartbeat_age_s": (None if r.heartbeat_age_s < 0
                                else float(r.heartbeat_age_s)),
            "watchdog_stalls": int(r.watchdog_stalls),
            "watchdog_stalled": bool(r.watchdog_stalled),
            "degrade_level": int(r.degrade_level),
            "tick_errors": int(r.tick_errors),
            "ticks": int(r.ticks),
            "backlog": int(r.backlog),
            "holdback_wires": int(r.holdback_wires),
            "inflight": int(r.inflight),
            "pipeline_depth": int(r.pipeline_depth),
            "effective_depth": int(r.effective_depth),
            "tenants": int(r.tenants),
            "capacity": int(r.capacity),
            "active_rows": int(r.active_rows),
            "headroom_rows": int(r.headroom_rows),
        }

    return probe


class _PlaneWatch:
    """One plane's suspicion-machine state (mutated only under the
    supervisor's lock)."""

    __slots__ = ("state", "consec_fail", "consec_soft", "consec_ok",
                 "last_error", "last_ok_s", "last_health")

    def __init__(self) -> None:
        self.state = HEALTHY
        self.consec_fail = 0   # hard: the probe itself raised
        self.consec_soft = 0   # soft: answered, but serving=False
        self.consec_ok = 0
        self.last_error: str | None = None
        self.last_ok_s: float | None = None
        self.last_health: dict | None = None


def fork_from_checkpoint(ckpt_dir: str, tenant: str):
    """Slice ONE tenant out of a (dead) plane's last crash-consistent
    checkpoint generation, in the migration fork schema — the
    cold-restore source `restore_tenant_slice` replays. Returns
    (fork, arrays, counters, pending, src_addr):

    - fork/arrays — identities, peers, topologies, wires, registry
      config and the per-row dynamic columns, exactly as a live FORK
      would have captured them (shaped = active & any-props, the
      checkpoint-load rule);
    - counters — the tenant rows' slice of the checkpointed cumulative
      plane counters (the durable `delivered_src` half of the failover
      accounting), or None when the checkpoint predates the counters
      file;
    - pending — the tenant's checkpointed in-flight delay-line frames;
    - ingress — the tenant's checkpointed queued-but-undrained ingress
      frames (accepted by the dead plane, not yet shaped — they drain
      on the survivor's first tick);
    - src_addr — the dead plane's node_ip (placement rewrite anchor).

    Deliberately linear in the checkpoint (one pass over the row
    registry and one npz gather) — a cold evacuation path, budgeted
    like checkpoint_load. Raises FleetError when the checkpoint has no
    trace of the tenant."""
    path = os.path.abspath(ckpt_dir)
    dirpath, manifest = ckpt._resolve_dir(path)
    section = manifest.get("tenancy") or {}
    cfg = next((t for t in section.get("tenants", ())
                if t["name"] == tenant), None)
    if cfg is None:
        raise FleetError(
            f"tenant {tenant!r} has no durable state in checkpoint "
            f"{ckpt_dir} (nothing to evacuate)")
    spaces = set(cfg.get("namespaces", ()))
    topologies = [
        {"manifest": r["manifest"],
         "finalizers": list(r.get("finalizers", ()))}
        for r in manifest.get("store", ())
        if r["manifest"]["metadata"].get("namespace", "default")
        in spaces]
    eng = manifest["engine"]
    pod_names = {v: k for k, v in eng["pod_ids"].items()}
    rows_list = sorted(
        ((pk, int(uid), int(row)) for pk, uid, row in eng["rows"]
         if pk.partition("/")[0] in spaces),
        key=lambda x: x[2])
    rows = np.asarray([r for _, _, r in rows_list], np.int64)
    with ckpt._load_npz(dirpath, manifest, "edge_state.npz") as z:
        src_col = np.asarray(z["src"])
        dst_col = np.asarray(z["dst"])
        props = np.asarray(z["props"])
        shaped_mask = np.asarray(z["active"]) & props.any(axis=1)
        identities = [
            [pk, uid, pod_names.get(int(src_col[r]), pk),
             pod_names.get(int(dst_col[r]), pk), bool(shaped_mask[r])]
            for pk, uid, r in rows_list]
        arrays = {
            "rows": rows,
            "props": props[rows],
            "tokens": np.asarray(z["tokens"])[rows],
            "t_last": np.asarray(z["t_last"])[rows],
            "corr": np.asarray(z["corr"])[rows],
            "pkt_count": np.asarray(z["pkt_count"])[rows],
            "backlog_until": np.asarray(z["backlog_until"])[rows],
        }
    keyset = {(pk, uid) for pk, uid, _r in rows_list}
    peers = sorted([a, int(b), c, int(d)]
                   for a, b, c, d in eng.get("peer", ())
                   if (a, int(b)) in keyset and (c, int(d)) in keyset)
    wires = [w for w in manifest.get("wires", ())
             if w[0].partition("/")[0] in spaces]
    counters = None
    all_counters = ckpt.load_plane_counters(path)
    if all_counters is not None:
        counters = {k: v[rows] for k, v in all_counters.items()}
    pending = [e for e in ckpt.read_pending_entries(path)
               if e[0].partition("/")[0] in spaces]
    ingress = [e for e in ckpt.read_ingress_entries(path)
               if e[0].partition("/")[0] in spaces]
    fork = {
        "identities": identities,
        "peers": peers,
        "topologies": topologies,
        "wires": wires,
        "registry": {
            "qos": cfg.get("qos", "gold"),
            "frame_budget_per_s": cfg.get("frame_budget_per_s"),
            "byte_budget_per_s": cfg.get("byte_budget_per_s"),
            "block_rows": int(cfg.get("block_rows", 0)),
            "namespaces": sorted(spaces),
        },
        "fork_shaped_s": (manifest.get("plane") or {}).get(
            "last_shaped_s"),
    }
    return (fork, arrays, counters, pending, ingress,
            manifest["node_ip"])


def _counters_summary(counters: dict | None, n_rows: int) -> dict:
    """Aggregate a per-row counter slice into the tenant_counters
    schema (the frozen `counters_at_restore` half of the failover
    accounting record)."""
    if counters is None:
        z = {k: 0.0 for k in
             ("tx_packets", "tx_bytes", "delivered_packets",
              "delivered_bytes", "dropped_loss", "dropped_queue",
              "dropped_ring", "corrupted")}
        z["links"] = n_rows
        return z

    def s(name: str) -> float:
        a = counters.get(name)
        return 0.0 if a is None else float(np.asarray(a).sum())

    return {
        "links": n_rows,
        "tx_packets": s("tx_packets"),
        "tx_bytes": s("tx_bytes"),
        "delivered_packets": s("rx_packets"),
        "delivered_bytes": s("rx_bytes"),
        "dropped_loss": s("dropped_loss"),
        "dropped_queue": s("dropped_queue"),
        "dropped_ring": s("dropped_ring"),
        "corrupted": s("rx_corrupted"),
    }


@guarded_by("_lock", "_watch", "_evacuations", "_evac_complete",
            "_fleet_slo")
class FleetSupervisor:
    """Health watcher + placement brain + failover/upgrade driver over
    one FederationController's registered planes. One supervisor per
    fleet; `attach()` wires it to every handle (and installs itself as
    `daemon.fleet`, the Local.FleetStatus / FleetUpgrade surface)."""

    def __init__(self, controller, ledger_root: str,
                 stats: FleetStats | None = None, chaos=None,
                 clock=time.monotonic,
                 suspect_after: int = 2, dead_after: int = 5,
                 healthy_after: int = 2) -> None:
        self.controller = controller
        self.ledger = PlacementLedger(ledger_root)
        self.stats = stats if stats is not None else FleetStats()
        self.chaos = chaos
        self.clock = clock
        self.suspect_after = int(suspect_after)
        self.dead_after = int(dead_after)
        self.healthy_after = int(healthy_after)
        self.log = get_logger("fleet")
        self._lock = threading.Lock()
        self._watch: dict[str, _PlaneWatch] = {}
        # newest sweep's fleet-merged SLO view (fleet_slo())
        self._fleet_slo: dict = {}
        self._evacuations: list[dict] = []
        # dead planes whose evacuation fully resolved (every tenant
        # restored, or unrecoverable for a PERMANENT reason): the
        # sweep loop retries the others until they land
        self._evac_complete: set[str] = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- wiring --------------------------------------------------------

    def attach(self, resume_orphans: bool = True) -> "FleetSupervisor":
        """Adopt the controller's current planes: create watches,
        install the `daemon.fleet` back-reference (the RPC surface),
        adopt ledger entries for tenants the ledger has never seen
        (registry is the live truth, the ledger its durable mirror),
        hook migration completions into the ledger, and resume any
        orphaned migration journals."""
        self.controller.placement_hook = self._on_migrated
        for name in self.controller.plane_names():
            self.watch_plane(name)
        if resume_orphans:
            self.resume_orphans()
        return self

    def watch_plane(self, name: str) -> None:
        """Start (or reset) watching one registered plane; adopts its
        registry's tenants into the ledger."""
        handle = self.controller.handle(name)
        handle.daemon.fleet = self
        with self._lock:
            self._watch.setdefault(name, _PlaneWatch())
        for t in handle.registry.list():
            if self.ledger.get(t.name) is None:
                self.ledger.assign(t.name, name, qos=t.qos)

    def mark_restarted(self, name: str) -> None:
        """Explicitly re-admit a plane (fresh process / upgrade): its
        watch resets to HEALTHY with clean counters. DEAD is final
        without this — a zombie must never silently resume serving
        tenants that were evacuated off it."""
        with self._lock:
            self._watch[name] = _PlaneWatch()
            self._evac_complete.discard(name)

    def _on_migrated(self, tenant: str, dst: str,
                     qos: str | None) -> None:
        self.ledger.assign(tenant, dst, qos=qos)

    # -- probing + suspicion state machine -----------------------------

    def probe(self, name: str) -> dict:
        """One health probe of a registered plane — the handle's
        `probe` hook (a gRPC Local.Health dial for out-of-process
        planes) or the in-process `daemon.health_snapshot()`. Raises
        on a dead plane; that raise IS the hard-failure signal."""
        if self.chaos is not None:
            self.chaos.on_probe(name)
        handle = self.controller.handle(name)
        self.stats.add(probes=1)
        if handle.probe is not None:
            return handle.probe()
        if getattr(handle.daemon, "chaos_dead", False):
            raise FleetError(f"plane {name} is not answering (killed)")
        return handle.daemon.health_snapshot()

    def _observe(self, name: str, health: dict | None,
                 error: str | None) -> str | None:
        """Feed one probe outcome into the suspicion machine. Returns
        the new state on a TRANSITION, else None."""
        with self._lock:
            w = self._watch[name]
            before = w.state
            if error is not None:
                w.consec_ok = 0
                w.consec_fail += 1
                w.last_error = error
                if (w.state == HEALTHY
                        and w.consec_fail >= self.suspect_after):
                    w.state = SUSPECT
                if (w.state == SUSPECT
                        and w.consec_fail >= self.dead_after):
                    w.state = DEAD
            elif health is not None and not health.get("serving", True):
                # soft: the plane ANSWERS but is degraded (bottom
                # ladder rung / watchdog stall) — suspicion yes, death
                # never: a responding plane still owns its state
                w.consec_fail = 0
                w.consec_ok = 0
                w.consec_soft += 1
                w.last_health = health
                w.last_error = "degraded (not serving)"
                if (w.state == HEALTHY
                        and w.consec_soft >= self.suspect_after):
                    w.state = SUSPECT
            else:
                w.consec_fail = 0
                w.consec_soft = 0
                w.consec_ok += 1
                w.last_ok_s = self.clock()
                w.last_health = health
                if (w.state == SUSPECT
                        and w.consec_ok >= self.healthy_after):
                    w.state = HEALTHY
                    w.last_error = None
            after = w.state
        if after != before:
            self.stats.add_transition(after)
            self.log.warning("plane state %s", _fields(
                plane=name, from_state=before, to_state=after,
                error=error))
            return after
        return None

    def sweep(self) -> dict:
        """One supervision pass: probe every watched plane, step the
        suspicion machine, and AUTOMATICALLY evacuate a plane the
        machine declares dead. O(planes) Python work + one probe per
        plane. Returns {plane: new_state} for this sweep's
        transitions."""
        self.stats.add(sweeps=1)
        with self._lock:
            names = sorted(self._watch)
        transitions: dict[str, str] = {}
        for name in names:
            with self._lock:
                state = self._watch[name].state
                evac_done = name in self._evac_complete
            if state == RESTARTING:
                continue
            if state == DEAD:
                # retry an evacuation that did not fully resolve
                # (transient failure, or a survivor that was itself
                # suspect at death time) — a DEAD plane is otherwise
                # never probed again, so the retry lives here
                if not evac_done:
                    self._try_evacuate(name)
                continue
            try:
                health = self.probe(name)
                tr = self._observe(name, health, None)
            except Exception as e:
                self.stats.add(probe_failures=1)
                tr = self._observe(name, None,
                                   f"{type(e).__name__}: {e}")
            if tr is not None:
                transitions[name] = tr
                if tr == DEAD:
                    self._try_evacuate(name)
        # refresh the fleet-merged SLO view (kubedtn_tpu.slo.fleet):
        # per-plane verdicts + the migration journal's frozen window
        # slices, merged exactly on the shared bucket ladder — a
        # tenant migrated or evacuated mid-window keeps a CONTINUOUS
        # fleet-level attainment/budget series. O(planes·tenants);
        # failures never kill the sweep (a plane without telemetry or
        # tenancy simply contributes nothing).
        try:
            merged = self.fleet_slo()
            with self._lock:
                self._fleet_slo = merged
        except Exception:
            self.log.exception("fleet slo merge failed (continuing)")
        return transitions

    # -- fleet SLO view ------------------------------------------------

    def fleet_slo(self, tenant: str = "") -> dict:
        """The fleet-merged SLO verdicts: {tenant: merged verdict
        dict}. Live halves come from each non-dead plane's SLO
        evaluator (lazily attached when the plane has tenancy +
        telemetry); frozen halves from the migration journal's
        RECONCILE-frozen src window slices, so pre-move and post-move
        observation stitch into one continuous view. Served by
        Local.ObserveSLO(fleet=true) and refreshed every sweep."""
        from kubedtn_tpu.slo import evaluator_for
        from kubedtn_tpu.slo.fleet import fleet_slo as _merge

        with self._lock:
            names = [n for n, w in sorted(self._watch.items())
                     if w.state != DEAD]
        payloads: dict[str, list] = {}
        for name in names:
            try:
                handle = self.controller.handle(name)
            except MigrationError:
                continue
            ev = evaluator_for(handle.daemon)
            if ev is None:
                continue
            try:
                payloads[name] = ev.verdict_payloads(tenant=tenant)
            except Exception:
                self.log.exception("slo payload failed %s",
                                   _fields(plane=name))
        frozen = self.controller.frozen_windows(tenant=tenant)
        return _merge(payloads, frozen, tenant=tenant)

    def last_fleet_slo(self) -> dict:
        """The newest sweep's cached merge (empty before the first)."""
        with self._lock:
            return dict(self._fleet_slo)

    def _try_evacuate(self, name: str) -> None:
        try:
            self.evacuate(name)
        except Exception:
            # an evacuation failure must not kill the sweep loop;
            # the next sweep retries tenants still on the dead plane
            self.log.exception("evacuation failed (will retry) %s",
                               _fields(plane=name))

    def start(self, interval_s: float = 1.0) -> None:
        """Background sweep loop (the daemon's sidecar)."""
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.sweep()
                except Exception:
                    self.log.exception("fleet sweep failed (continuing)")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="kdt-fleet-sweep")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        self._thread = None

    # -- status --------------------------------------------------------

    def status(self) -> dict:
        placements = self.ledger.placements()
        placed_count: dict[str, int] = {}
        for t, p in placements.items():
            placed_count[p] = placed_count.get(p, 0) + 1
        cordoned = self.ledger.cordoned()
        snap = self.stats.snapshot()
        with self._lock:
            planes = []
            for name in sorted(self._watch):
                w = self._watch[name]
                planes.append({
                    "name": name,
                    "state": ("cordoned" if name in cordoned
                              and w.state == HEALTHY else w.state),
                    "consecutive_failures": w.consec_fail,
                    "last_error": w.last_error,
                    "tenants_placed": placed_count.get(name, 0),
                    "health": w.last_health,
                })
        return {
            "planes": planes,
            "placements": placements,
            "sweeps": snap["sweeps"],
            "evacuations": snap["evacuations"],
        }

    # -- orphaned migration journals -----------------------------------

    def resume_orphans(self) -> list[str]:
        """Resume every journaled migration left `running` by a crash
        or restart — no operator `kdt migrate --resume` needed. Records
        in `rolled_back` (an explicit abort) stay refused, per the
        PR 11 contract; `done` records are finished. Returns the
        migration ids resumed."""
        root = self.controller.journal_root
        resumed = []
        for mid in journal.list_records(root):
            try:
                meta = journal.load_record_meta(root, mid)
            except journal.JournalError:
                continue
            if meta.get("state") != "running":
                continue
            try:
                self.controller.resume(mid)
            except (MigrationError, journal.JournalError) as e:
                self.log.warning("orphan resume failed %s", _fields(
                    migration=mid, error=f"{type(e).__name__}: {e}"))
                continue
            resumed.append(mid)
            self.stats.add(orphans_resumed=1)
            self.log.info("orphaned migration resumed %s",
                          _fields(migration=mid))
        return resumed

    # -- evacuation ----------------------------------------------------

    def _live_candidates(self, exclude: set[str]) -> tuple[dict, dict]:
        """(healths, placed) over currently-HEALTHY planes outside
        `exclude` — the placement inputs."""
        cordoned = self.ledger.cordoned()
        healths: dict[str, dict] = {}
        with self._lock:
            names = [n for n, w in self._watch.items()
                     if w.state == HEALTHY]
        for name in names:
            if name in exclude or name in cordoned:
                continue
            try:
                healths[name] = self.probe(name)
            except Exception:
                self.stats.add(probe_failures=1)
                continue
        placements = self.ledger.placements()
        placed: dict[str, list[str]] = {}
        for t, p in placements.items():
            placed.setdefault(p, []).append(t)
        return healths, placed

    def _resolve_migrations(self, dead: str,
                            record: dict) -> tuple[dict, dict]:
        """Resolve every in-flight migration touching the dead plane
        per the PR 11 crash contract. Returns (overrides, fallbacks):
        tenant → (fork, arrays, counters, pending, ingress, src_addr)
        restore sources. `overrides` WIN over the checkpoint (a
        pre-cutover fork of a held tenant is the authoritative
        capture); `fallbacks` are consulted only when the dead plane's
        checkpoint has no trace of the tenant (post-cutover dst death
        where the dst checkpoint predates the restore — a NEWER dst
        checkpoint carries post-cutover state the stale fork does
        not)."""
        root = self.controller.journal_root
        overrides: dict[str, tuple] = {}
        fallbacks: dict[str, tuple] = {}
        for mid in journal.list_records(root):
            try:
                meta = journal.load_record_meta(root, mid)
            except journal.JournalError:
                continue
            if meta.get("state") != "running":
                continue
            if dead not in (meta.get("src"), meta.get("dst")):
                continue
            tenant = meta["tenant"]
            steps = meta.get("steps_done", [])
            if "cutover" not in steps:
                # pre-cutover: src is authoritative
                if meta["dst"] == dead:
                    # dst died mid-restore: nothing on dst survives a
                    # SIGKILL anyway; src keeps serving — release the
                    # throttle hold and abort the record
                    action = ("rolled back: dst died pre-cutover; "
                              "src stays authoritative")
                    try:
                        self.controller.handle(meta["src"]) \
                            .registry.release_hold(tenant)
                    except MigrationError:
                        pass
                else:
                    # src died: the journal's FORK capture (if it
                    # committed) is the newest crash-consistent state
                    # — re-fork elsewhere; partial dst state from an
                    # interrupted RESTORE is discarded first
                    if "fork" in steps:
                        try:
                            full, arrays = journal.load_record(root,
                                                               mid)
                        except journal.JournalError:
                            full, arrays = None, None
                        if full is not None:
                            fork = full["fork"]
                            try:
                                dst_h = self.controller.handle(
                                    meta["dst"])
                                discard_partial_restore(dst_h, tenant,
                                                        fork)
                            except MigrationError:
                                pass
                            try:
                                src_addr = self.controller.handle(
                                    dead).addr
                            except Exception:
                                src_addr = ""
                            overrides[tenant] = (
                                fork, arrays,
                                fork.get("counters_at_fork"), [], [],
                                src_addr)
                    action = ("rolled back: src died pre-cutover; "
                              "re-forking from the journal capture "
                              "onto a survivor")
                meta["state"] = "rolled_back"
                meta["failover"] = dead
                journal.save_record(root, mid, meta)
            else:
                # post-cutover: roll forward — dst owns the tenant
                if meta["dst"] == dead:
                    # dst died owning the tenant: the journal fork is
                    # the roll-forward source when dst's checkpoint
                    # predates the restore (tenant absent there); the
                    # alive src still holds the released-but-unfreed
                    # slice — finish RELEASE on it
                    try:
                        co = self.controller.coordinator(mid)
                        co._step_release()
                        # the release committed a new journal
                        # generation: re-read so the terminal write
                        # below keeps its steps_done entry
                        meta = journal.load_record_meta(root, mid)
                    except Exception:
                        self.log.exception(
                            "src release during failover failed %s",
                            _fields(migration=mid))
                    try:
                        full, arrays = journal.load_record(root, mid)
                        try:
                            src_addr = self.controller.handle(
                                dead).addr
                        except Exception:
                            src_addr = ""
                        fallbacks.setdefault(
                            tenant,
                            (full["fork"], arrays,
                             full["fork"].get("counters_at_fork"),
                             [], [], src_addr))
                    except journal.JournalError:
                        pass
                    # the tenant was placed on the (dead) dst from
                    # cutover on — make the ledger agree so the
                    # evacuation pass picks it up
                    self.ledger.assign(tenant, dead)
                    action = ("rolled forward: dst died post-cutover; "
                              "evacuating the cut-over slice")
                else:
                    # src died post-cutover: dst serves; release its
                    # hold (reconcile would have) and close the record
                    # — the src accounting slice died with src, which
                    # the record states instead of hiding
                    try:
                        dst_h = self.controller.handle(meta["dst"])
                        dst_h.registry.release_hold(tenant)
                        self.ledger.assign(tenant, meta["dst"])
                    except MigrationError:
                        pass
                    action = ("rolled forward: src died post-cutover; "
                              "dst serves (src accounting slice lost "
                              "with the plane)")
                meta["state"] = "done"
                meta["failover"] = dead
                journal.save_record(root, mid, meta)
            record["migrations_resolved"].append(
                {"id": mid, "tenant": tenant, "action": action})
            self.log.warning("migration resolved by failover %s",
                             _fields(migration=mid, action=action))
        return overrides, fallbacks

    def evacuate(self, dead: str) -> dict:
        """Cold-restore every tenant of a DEAD plane onto survivors —
        the no-operator failover path. Restore source per tenant: an
        in-flight migration's journal fork when the crash contract says
        so, else the dead plane's last crash-consistent checkpoint.
        Rows land byte-identical to the source generation (the
        restore-slice contract), cumulative counters ride with them,
        and checkpointed in-flight frames complete their remaining
        delays on the survivor. Returns the evacuation record."""
        with self._lock:
            w = self._watch.setdefault(dead, _PlaneWatch())
            w.state = DEAD
        record: dict = {"plane": dead, "at_s": time.time(),
                        "tenants": {}, "migrations_resolved": []}
        overrides, fallbacks = self._resolve_migrations(dead, record)
        handle = self.controller.handle(dead)
        names = set(self.ledger.on_plane(dead))
        ckpt_dir = handle.checkpoint_dir
        if ckpt_dir:
            try:
                _dir, manifest = ckpt._resolve_dir(
                    os.path.abspath(ckpt_dir))
                for t in (manifest.get("tenancy") or {}).get(
                        "tenants", ()):
                    names.add(t["name"])
            except ckpt.CheckpointError:
                pass
        healths, placed = self._live_candidates(exclude={dead})
        complete = True
        for tenant in sorted(names):
            placed_on = self.ledger.get(tenant)
            if placed_on is not None and placed_on != dead:
                # already living elsewhere — a tenant an earlier
                # (partial) evacuation pass restored, or one the
                # checkpoint remembers but a later migration moved
                # off; re-restoring would double-serve it
                continue
            entry: dict = {"source": None, "survivor": None}
            try:
                src = overrides.get(tenant)
                if src is not None:
                    entry["source"] = "journal-fork"
                elif ckpt_dir:
                    # the checkpoint wins when it knows the tenant (it
                    # may be NEWER than a fallback fork — post-cutover
                    # state); the journal fork covers the gap where it
                    # predates the restore
                    try:
                        src = fork_from_checkpoint(ckpt_dir, tenant)
                        entry["source"] = "checkpoint"
                    except (FleetError, ckpt.CheckpointError):
                        src = fallbacks.get(tenant)
                        if src is None:
                            raise
                        entry["source"] = "journal-fork"
                else:
                    src = fallbacks.get(tenant)
                    if src is not None:
                        entry["source"] = "journal-fork"
                if src is None:
                    raise FleetError(
                        f"no durable state for tenant {tenant!r} "
                        f"(no checkpoint dir configured)")
                fork, arrays, counters, pending, ingress, src_addr = \
                    src
                survivor = choose_plane(
                    healths, placed, self.ledger.qos_of,
                    exclude={dead})
                sh = self.controller.handle(survivor)
                rows = restore_tenant_slice(
                    sh, tenant, fork, arrays, src_addr, hold=False)
                n_pending = 0
                if pending:
                    now_s = (sh.plane.last_now_s
                             if sh.plane._clock_ext else None)
                    if sh.plane._clock_ext and now_s is None:
                        self.log.warning(
                            "pending frames skipped (no clock) %s",
                            _fields(tenant=tenant))
                    else:
                        n_pending = sh.plane.restore_pending(
                            pending, now_s=now_s)
                n_ingress = 0
                for pk, uid, frame in ingress:
                    w = sh.daemon.wires.get_by_key(pk, int(uid))
                    if w is not None:
                        w.ingress.append(frame)
                        n_ingress += 1
                self.ledger.assign(tenant, survivor,
                                   qos=fork["registry"].get("qos"))
                placed.setdefault(survivor, []).append(tenant)
                # the src half of the failover accounting, FROZEN here
                # exactly like RECONCILE freezes the src counter slice:
                # the durable checkpoint counters (per-row slice), or
                # the fork's captured tenant_counters for a
                # journal-fork source
                if isinstance(counters, dict) and \
                        "delivered_packets" in counters:
                    at_restore = dict(counters)
                else:
                    at_restore = _counters_summary(counters, len(rows))
                entry.update({
                    "survivor": survivor,
                    "rows": len(rows),
                    "pending_restored": n_pending,
                    "ingress_restored": n_ingress,
                    "counters_at_restore": at_restore,
                })
                self.stats.add(evacuated_tenants=1,
                               evacuated_rows=len(rows),
                               pending_restored=n_pending + n_ingress)
                self.log.warning("tenant evacuated %s", _fields(
                    tenant=tenant, from_plane=dead, to_plane=survivor,
                    rows=len(rows), source=entry["source"]))
            except (FleetError, PlacementError, MigrationError,
                    ckpt.CheckpointError) as e:
                # NEVER hidden: a tenant that could not be restored is
                # recorded with the reason (its whole slice is the
                # reported loss)
                entry["error"] = f"{type(e).__name__}: {e}"
                self.log.error("tenant evacuation failed %s", _fields(
                    tenant=tenant, plane=dead, error=entry["error"]))
                # PERMANENT: no durable state can ever appear for this
                # incarnation. Everything else (no survivor yet, a
                # transient restore failure) is retried next sweep.
                if "no durable state" not in str(e):
                    complete = False
            record["tenants"][tenant] = entry
        self.stats.add(evacuations=1)
        with self._lock:
            self._evacuations.append(record)
            if complete:
                self._evac_complete.add(dead)
        return record

    def evacuations(self) -> list[dict]:
        with self._lock:
            return list(self._evacuations)

    def check_failover_accounting(self, tenant: str,
                                  fed_frames: int) -> dict:
        """The failover extension of the PR 11 accounting rule: every
        fed frame is delivered by the dead plane BEFORE its last
        checkpoint (durable counters, restored with the rows),
        delivered by the survivor after it, or REPORTED lost:

            fed == delivered_src + delivered_dst + reported_lost

        `reported_lost` is derived (fed − accounted-terminal) and the
        mismatch gauge carries any OVER-accounting — internal counters
        explaining more frames than were fed means a duplicate-
        delivery bug, which must read 0 in every scenario. Extends the
        `kubedtn_migration_accounting_mismatch` discipline to
        failover (the same gauge is updated). The src half is the
        FROZEN `counters_at_restore` slice; the dst half is the
        survivor's live counters (its restored rows started at zero,
        so frozen + live never double-counts)."""
        ev = None
        with self._lock:
            for rec in reversed(self._evacuations):
                e = rec["tenants"].get(tenant)
                if e is not None and e.get("survivor"):
                    ev = e
                    break
        if ev is None:
            raise FleetError(
                f"no completed evacuation covers tenant {tenant!r}")
        sh = self.controller.handle(ev["survivor"])
        live = sh.registry.tenant_counters(sh.plane, tenant)
        at_restore = ev["counters_at_restore"]
        accounted = (MigrationCoordinator._accounted(live)
                     + MigrationCoordinator._accounted(at_restore))
        delivered_src = float(at_restore["delivered_packets"])
        delivered_dst = float(live["delivered_packets"])
        raw = float(fed_frames) - accounted
        reported_lost = max(0.0, raw)
        mismatch = max(0.0, -raw)
        self.controller.stats.set_mismatch(mismatch)
        self.stats.set_reported_lost(reported_lost)
        return {
            "fed": int(fed_frames),
            "accounted": accounted,
            "delivered_src": delivered_src,
            "delivered_dst": delivered_dst,
            "reported_lost": reported_lost,
            "mismatch": mismatch,
        }

    # -- rebalance + rolling upgrade -----------------------------------

    def rebalance(self, settle=None) -> list[dict]:
        """Execute the placement policy's rebalance plan as live
        migrations (each one the full PR 11 zero-loss state
        machine)."""
        from kubedtn_tpu.federation.placement import rebalance_plan

        healths, placed = self._live_candidates(exclude=set())
        moves = rebalance_plan(healths, placed, self.ledger.qos_of,
                               exclude=self.ledger.cordoned())
        out = []
        for tenant, src, dst in moves:
            rec = self.controller.migrate(tenant, src, dst,
                                          settle=settle)
            self.ledger.assign(tenant, dst)
            out.append({"tenant": tenant, "src": src, "dst": dst,
                        "state": rec["state"]})
        return out

    def rolling_upgrade(self, planes: list[str] | None = None,
                        verify_probes: int | None = None,
                        verify_timeout_s: float = 30.0,
                        settle=None) -> dict:
        """Upgrade the fleet one plane at a time with zero frame loss:
        cordon → drain every tenant via live migration → restart the
        daemon binary (the handle's `restarter` hook) → health-verify
        (`verify_probes` consecutive clean probes) → refill the
        drained tenants → uncordon → next plane. A plane with no
        restarter, or no healthy survivor to drain to, is reported and
        skipped — never half-drained."""
        need = int(verify_probes or self.healthy_after)
        if planes is None:
            with self._lock:
                planes = [n for n in sorted(self._watch)
                          if self._watch[n].state == HEALTHY]
        reports = []
        migrations = 0
        for name in planes:
            report = {"plane": name, "drained_tenants": [],
                      "refilled_tenants": [], "restarted": False,
                      "healthy": False, "error": ""}
            reports.append(report)
            try:
                handle = self.controller.handle(name)
            except MigrationError as e:
                report["error"] = str(e)
                continue
            if handle.restarter is None:
                report["error"] = (f"plane {name} has no restarter "
                                   f"configured")
                continue
            healths, placed = self._live_candidates(exclude={name})
            if not healths:
                report["error"] = (f"no healthy survivor to drain "
                                   f"{name} into")
                continue
            self.ledger.cordon(name)
            with self._lock:
                self._watch[name].state = RESTARTING
            try:
                # drain: every tenant moves off via live migration
                moved: dict[str, str] = {}
                for t in sorted(t.name for t in
                                handle.registry.list()):
                    dst = choose_plane(healths, placed,
                                       self.ledger.qos_of,
                                       exclude={name})
                    self.controller.migrate(t, name, dst,
                                            settle=settle)
                    self.ledger.assign(t, dst)
                    placed.setdefault(dst, []).append(t)
                    moved[t] = dst
                    migrations += 1
                    report["drained_tenants"].append(t)
                # restart the daemon binary
                new_handle = handle.restarter()
                self.controller.register(new_handle)
                new_handle.daemon.fleet = self
                report["restarted"] = True
                # health-verify BEFORE refill: `need` consecutive
                # clean serving probes
                ok = 0
                deadline = self.clock() + verify_timeout_s
                while ok < need:
                    try:
                        h = self.probe(name)
                        ok = ok + 1 if h.get("serving", False) else 0
                    except Exception:
                        self.stats.add(probe_failures=1)
                        ok = 0
                    if ok >= need:
                        break
                    if self.clock() > deadline:
                        raise FleetError(
                            f"plane {name} failed health "
                            f"verification after restart")
                    if settle is not None:
                        settle()
                    else:
                        time.sleep(0.05)
                report["healthy"] = True
                self.mark_restarted(name)
                self.ledger.uncordon(name)
                # refill: the drained tenants come home, each again a
                # zero-loss live migration
                for t in sorted(moved):
                    self.controller.migrate(t, moved[t], name,
                                            settle=settle)
                    self.ledger.assign(t, name)
                    migrations += 1
                    report["refilled_tenants"].append(t)
            except (FleetError, PlacementError, MigrationError) as e:
                report["error"] = f"{type(e).__name__}: {e}"
                # cordon stays if the plane never verified healthy —
                # placement must not target a plane in an unknown
                # state. The WATCH must not stay parked in
                # `restarting` either, or the suspicion machine would
                # never probe the plane again (a later real death
                # would go undetected): a plane that never restarted
                # is still the old serving process (back to healthy
                # watching), a restarted-but-unverified one is suspect
                # until clean probes clear it.
                with self._lock:
                    self._watch[name].state = (
                        SUSPECT if report["restarted"] else HEALTHY)
                continue
        self.stats.add(upgrades=1, upgrade_migrations=migrations)
        self.log.info("rolling upgrade finished %s", _fields(
            planes=len(reports), migrations=migrations,
            errors=sum(1 for r in reports if r["error"])))
        return {"reports": reports, "migrations": migrations,
                "frames_lost_known": True}
