"""Placement — the fleet's tenant→plane ledger and scoring policy.

The ledger is the supervisor's durable memory of WHERE every tenant
lives: a single journaled record (`federation.journal`'s staged-save /
sha256 / `.prev` double-crash discipline — the checkpoint atomicity
contract) that survives a supervisor restart, so an evacuation after a
crash knows exactly which tenants the dead plane owed without trusting
the dead plane's own state. Every mutation commits before it returns;
a kill at any instant leaves the previous complete generation
readable.

The policy is deliberately simple and fully deterministic: a plane's
placement score blends capacity headroom (the dominant term — a plane
that cannot hold the tenant's rows must lose), current placement
pressure (admitted tenants weighted by their QoS drain share, so a
bronze tenant crowds a plane less than a gold one), and health
penalties (degradation-ladder rung, standing backlog). Rebalance
decisions come out as (tenant, src, dst) moves for the supervisor to
execute as PR 11 live migrations — the ledger itself never touches a
plane.
"""

from __future__ import annotations

import threading

from kubedtn_tpu.contracts import guarded_by, requires_lock
from kubedtn_tpu.federation import journal
from kubedtn_tpu.utils.logging import fields as _fields
from kubedtn_tpu.utils.logging import get_logger

# the one record id inside the ledger root (journal layout: one record
# directory per id; the ledger is a single logical record)
LEDGER_RECORD = "placement"

# QoS class → placement pressure (the drain-weight ladder of
# tenancy.registry: how much of a plane's drain budget the tenant can
# claim — the policy packs light tenants denser)
QOS_PRESSURE = {"gold": 1.0, "silver": 0.5, "bronze": 0.25}


class PlacementError(RuntimeError):
    """No legal placement exists (all planes dead/cordoned/full)."""


@guarded_by("_lock", "_placements", "_cordoned", "_qos")
class PlacementLedger:
    """Crash-safe tenant→plane ledger. Mutations journal BEFORE they
    return (`assign`/`remove`/`cordon`/`uncordon` are each one
    committed generation); readers get torn-free snapshots under the
    lock. Ledger ops are O(1) in-memory plus one O(placements) record
    serialization per commit."""

    def __init__(self, root: str) -> None:
        self.root = root
        self.log = get_logger("fleet")
        self._lock = threading.Lock()
        self._placements: dict[str, str] = {}
        self._qos: dict[str, str] = {}
        self._cordoned: set[str] = set()
        try:
            rec = journal.load_record_meta(root, LEDGER_RECORD)
        except journal.JournalMissingError:
            rec = None
        except journal.JournalCorruptError:
            # both generations damaged: surface loudly but start empty
            # (the supervisor re-adopts placements from the live
            # registries on attach) rather than refusing to supervise
            self.log.exception("placement ledger unreadable; starting "
                               "empty %s", _fields(root=root))
            rec = None
        if rec is not None:
            self._placements = dict(rec.get("placements", {}))
            self._qos = dict(rec.get("qos", {}))
            self._cordoned = set(rec.get("cordoned", ()))

    @requires_lock("_lock")
    def _commit_locked(self) -> None:
        journal.save_record(self.root, LEDGER_RECORD, {
            "placements": dict(self._placements),
            "qos": dict(self._qos),
            "cordoned": sorted(self._cordoned),
        })

    def assign(self, tenant: str, plane: str,
               qos: str | None = None) -> None:
        with self._lock:
            self._placements[tenant] = plane
            if qos is not None:
                self._qos[tenant] = qos
            self._commit_locked()

    def remove(self, tenant: str) -> None:
        with self._lock:
            self._placements.pop(tenant, None)
            self._qos.pop(tenant, None)
            self._commit_locked()

    def get(self, tenant: str) -> str | None:
        with self._lock:
            return self._placements.get(tenant)

    def qos_of(self, tenant: str) -> str:
        with self._lock:
            return self._qos.get(tenant, "gold")

    def placements(self) -> dict[str, str]:
        with self._lock:
            return dict(self._placements)

    def on_plane(self, plane: str) -> list[str]:
        with self._lock:
            return sorted(t for t, p in self._placements.items()
                          if p == plane)

    def cordon(self, plane: str) -> None:
        """Mark a plane closed to NEW placements (upgrade/maintenance);
        existing tenants keep serving."""
        with self._lock:
            self._cordoned.add(plane)
            self._commit_locked()

    def uncordon(self, plane: str) -> None:
        with self._lock:
            self._cordoned.discard(plane)
            self._commit_locked()

    def cordoned(self) -> set[str]:
        with self._lock:
            return set(self._cordoned)


def plane_score(health: dict, pressure: float) -> float:
    """Placement desirability of one plane: capacity headroom fraction
    dominates, minus the QoS-weighted pressure already placed there,
    minus health penalties (a degraded rung or a standing backlog make
    a plane a worse target long before it turns suspect). Pure and
    deterministic — same inputs, same score."""
    cap = max(1, int(health.get("capacity", 0) or 0))
    headroom = float(health.get("headroom_rows", 0)) / cap
    degrade = float(health.get("degrade_level", 0) or 0)
    backlog = float(health.get("backlog", 0) or 0)
    score = headroom
    score -= 0.10 * pressure          # QoS-weighted tenants placed
    score -= 0.30 * degrade           # each ladder rung down
    score -= min(0.5, backlog / 65536.0)  # standing ingress backlog
    if not health.get("serving", True):
        score -= 1.0
    return score


def pressure_of(tenants: list[str], qos_of) -> float:
    """Sum of QOS_PRESSURE over `tenants` (`qos_of(tenant)` → class)."""
    return sum(QOS_PRESSURE.get(qos_of(t), 1.0) for t in tenants)


def choose_plane(healths: dict[str, dict],
                 placed: dict[str, list[str]], qos_of,
                 exclude=()) -> str:
    """The best placement target: highest `plane_score`, name as the
    deterministic tiebreak. `healths` maps candidate plane → health
    dict (dead/cordoned planes must already be excluded or listed in
    `exclude`); `placed` maps plane → tenants currently there."""
    best_name, best_score = None, None
    for name in sorted(healths):
        if name in exclude:
            continue
        score = plane_score(
            healths[name], pressure_of(placed.get(name, []), qos_of))
        if best_score is None or score > best_score:
            best_name, best_score = name, score
    if best_name is None:
        raise PlacementError(
            f"no placement candidate (excluded: {sorted(exclude)})")
    return best_name


def rebalance_plan(healths: dict[str, dict],
                   placed: dict[str, list[str]], qos_of,
                   exclude=(), min_gain: float = 0.25
                   ) -> list[tuple[str, str, str]]:
    """Score-driven moves (tenant, src, dst), greedy one-tenant-at-a-
    time: move a tenant when the destination's score exceeds its
    current plane's by at least `min_gain` AFTER accounting for the
    tenant's own pressure landing there (no oscillation: the gain
    threshold plus the self-pressure term make the reverse move
    strictly worse). Executed by the supervisor as live migrations."""
    placed = {p: list(ts) for p, ts in placed.items()}
    moves: list[tuple[str, str, str]] = []
    for src in sorted(placed):
        if src in exclude or src not in healths:
            continue
        for tenant in list(placed[src]):
            pressure = QOS_PRESSURE.get(qos_of(tenant), 1.0)
            src_score = plane_score(
                healths[src], pressure_of(placed[src], qos_of))
            best, best_score = None, None
            for dst in sorted(healths):
                if dst == src or dst in exclude:
                    continue
                # score as if the tenant already landed there
                dst_score = plane_score(
                    healths[dst],
                    pressure_of(placed.get(dst, []), qos_of)
                    + pressure)
                if best_score is None or dst_score > best_score:
                    best, best_score = dst, dst_score
            if best is not None and best_score >= src_score + min_gain:
                moves.append((tenant, src, best))
                placed[src].remove(tenant)
                placed.setdefault(best, []).append(tenant)
    return moves
