"""K8s bridge — sync Topology CRs between a real cluster and the store.

The reference talks to the Kubernetes API in two ways: a hand-rolled typed
clientset (reference api/clientset/v1beta1/topology.go:32-188 — List/Get/
Watch/Update/UpdateStatus against group y-young.github.io) and a shared
informer feeding the daemon's cache (reference daemon/kubedtn/kubedtn.go:
128-142). Here the in-process :class:`TopologyStore` plays the apiserver
role for standalone runs; this module is the optional bridge that keeps the
store in sync with a REAL cluster when one exists, so the same reconciler/
engine stack runs unmodified either way:

- cluster → store: initial LIST then a WATCH pump applies ADDED/MODIFIED/
  DELETED spec changes into the store (the informer direction);
- store → cluster: status written locally by the daemon/reconciler (the
  placement + applied-links subresource, reference handler.go:90-147) is
  pushed back via the status subresource endpoint (the clientset
  UpdateStatus direction, topology.go:171-184); a vanished object reads
  as False, transient API errors propagate to the caller's retry loop.

The real-cluster transport is duck-typed (`list_topologies`,
`watch_topologies`, `patch_status`, `patch_finalizers`): production wraps
the `kubernetes` package's CustomObjectsApi (gated import — raises
:class:`K8sUnavailable` when the package is missing, which it is in this
image), and the test suite drives the same bridge with an in-memory fake
cluster, mirroring how the reference tests controllers against envtest
(reference controllers/suite_test.go:44-80).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable

from kubedtn_tpu import GROUP, VERSION
from kubedtn_tpu.api.types import Topology
from kubedtn_tpu.topology.store import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    TopologyStore,
    retry_on_conflict,
)

PLURAL = "topologies"


class K8sUnavailable(RuntimeError):
    """The kubernetes client package is not importable."""


class WatchExpiredError(RuntimeError):
    """The watch's resourceVersion fell out of the apiserver's retained
    window (HTTP 410 Gone / an ERROR event with code 410). Recovery is a
    fresh LIST — NOT a backoff-resume, which would 410 forever."""

    status = 410


class ApiHttpError(RuntimeError):
    """Non-2xx from the HTTP transport; `.status` carries the code so the
    bridge's 404/409/410 handling works like the kubernetes client's
    ApiException."""

    def __init__(self, status: int, message: str) -> None:
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class HttpKubeApi:
    """The bridge transport over the RAW Kubernetes REST protocol — no
    `kubernetes` package needed. Point it at any endpoint speaking the
    CustomObjects surface: `kubectl proxy` (http://127.0.0.1:8001) in
    production, the test suite's protocol-level fake apiserver
    (tests/fake_apiserver.py, the envtest role of reference
    controllers/suite_test.go:44-80) in CI.

    Implements the duck-typed surface K8sBridge expects
    (list_topologies / watch_topologies / patch_status /
    patch_finalizers). Watch streams JSON-lines events; an ERROR event
    carrying code 410 raises WatchExpiredError so the informer loop
    re-lists instead of resuming.
    """

    def __init__(self, base_url: str, namespace: str | None = None,
                 timeout_s: float = 30.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.namespace = namespace
        self.timeout_s = timeout_s

    # -- plumbing ------------------------------------------------------

    def _collection_path(self) -> str:
        if self.namespace is None:
            return f"/apis/{GROUP}/{VERSION}/{PLURAL}"
        return (f"/apis/{GROUP}/{VERSION}/namespaces/"
                f"{self.namespace}/{PLURAL}")

    def _object_path(self, ns: str, name: str, sub: str = "") -> str:
        p = f"/apis/{GROUP}/{VERSION}/namespaces/{ns}/{PLURAL}/{name}"
        return p + (f"/{sub}" if sub else "")

    def _request(self, method: str, path: str, body: dict | None = None,
                 content_type: str = "application/json") -> dict:
        import json as _json
        import urllib.error
        import urllib.request

        data = _json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers={"Content-Type": content_type} if data else {})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return _json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            raise ApiHttpError(e.code, detail) from e

    # -- bridge surface ------------------------------------------------

    def list_topologies(self) -> tuple[list[dict], str]:
        r = self._request("GET", self._collection_path())
        return r.get("items", []), r["metadata"]["resourceVersion"]

    def watch_topologies(self, resource_version: str):
        import json as _json
        import socket
        import urllib.request

        url = (f"{self.base_url}{self._collection_path()}"
               f"?watch=true&resourceVersion={resource_version}")
        req = urllib.request.Request(url)
        # a connect failure IS a transient error and propagates; but once
        # the stream is up, a read timeout just means the cluster was
        # idle for timeout_s — that's an orderly end of stream (client-go
        # re-watches immediately), NOT a failure to back off from
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            while True:
                try:
                    raw = resp.readline()
                except (TimeoutError, socket.timeout):
                    return  # idle stream: caller re-watches from last RV
                if not raw:
                    return  # server closed the stream
                raw = raw.strip()
                if not raw:
                    continue
                ev = _json.loads(raw)
                if ev.get("type") == "ERROR":
                    code = ev.get("object", {}).get("code")
                    if code == 410:
                        raise WatchExpiredError(
                            ev["object"].get("message", "expired"))
                    raise ApiHttpError(code or 500,
                                       ev["object"].get("message", ""))
                yield ev["type"], ev["object"]

    def patch_status(self, ns: str, name: str, status: dict) -> None:
        self._request("PATCH", self._object_path(ns, name, "status"),
                      {"status": status},
                      content_type="application/merge-patch+json")

    def patch_finalizers(self, ns: str, name: str,
                         finalizers: list[str]) -> None:
        self._request("PATCH", self._object_path(ns, name),
                      {"metadata": {"finalizers": finalizers}},
                      content_type="application/merge-patch+json")


class HttpLeaseApi:
    """coordination.k8s.io/v1 Leases over raw HTTP, shaped like the
    kubernetes CoordinationV1Api surface KubeLeaseStore injects
    (read/create/replace_namespaced_lease returning dict manifests) —
    real cross-pod leader election through `kubectl proxy` or the test
    fake, with the apiserver's resourceVersion CAS intact (a PUT with a
    stale RV answers 409, which KubeLeaseStore reads as a lost
    election)."""

    def __init__(self, base_url: str, timeout_s: float = 10.0) -> None:
        self._api = HttpKubeApi(base_url, timeout_s=timeout_s)

    @staticmethod
    def _path(ns: str, name: str = "") -> str:
        p = f"/apis/coordination.k8s.io/v1/namespaces/{ns}/leases"
        return p + (f"/{name}" if name else "")

    def read_namespaced_lease(self, name: str, namespace: str) -> dict:
        return self._api._request("GET", self._path(namespace, name))

    def create_namespaced_lease(self, namespace: str, body: dict) -> dict:
        return self._api._request("POST", self._path(namespace), body)

    def replace_namespaced_lease(self, name: str, namespace: str,
                                 body: dict) -> dict:
        return self._api._request("PUT", self._path(namespace, name),
                                  body)


def make_kube_api(namespace: str | None = None):
    """Wrap the real `kubernetes` package into the bridge's transport
    surface. Raises K8sUnavailable when the package is absent (it is not
    baked into this image; standalone mode needs no cluster)."""
    try:
        import kubernetes  # type: ignore
    except ImportError as e:
        raise K8sUnavailable(
            "the 'kubernetes' package is not installed; run standalone "
            "(TopologyStore) or install the client") from e

    try:
        kubernetes.config.load_incluster_config()
    except kubernetes.config.config_exception.ConfigException:
        try:  # out-of-cluster operator: fall back to kubeconfig
            kubernetes.config.load_kube_config()
        except kubernetes.config.config_exception.ConfigException as e:
            raise K8sUnavailable(
                "no in-cluster service account and no kubeconfig") from e
    api = kubernetes.client.CustomObjectsApi()

    class _Api:
        def list_topologies(self) -> tuple[list[dict], str]:
            r = api.list_cluster_custom_object(GROUP, VERSION, PLURAL) \
                if namespace is None else api.list_namespaced_custom_object(
                    GROUP, VERSION, namespace, PLURAL)
            return r.get("items", []), r["metadata"]["resourceVersion"]

        def watch_topologies(self, resource_version: str):
            w = kubernetes.watch.Watch()
            kwargs = dict(resource_version=resource_version)
            if namespace is None:
                stream = w.stream(api.list_cluster_custom_object, GROUP,
                                  VERSION, PLURAL, **kwargs)
            else:
                stream = w.stream(api.list_namespaced_custom_object, GROUP,
                                  VERSION, namespace, PLURAL, **kwargs)
            for ev in stream:
                yield ev["type"], ev["object"]

        def patch_status(self, ns: str, name: str, status: dict) -> None:
            api.patch_namespaced_custom_object_status(
                GROUP, VERSION, ns, PLURAL, name, {"status": status})

        def patch_finalizers(self, ns: str, name: str,
                             finalizers: list[str]) -> None:
            api.patch_namespaced_custom_object(
                GROUP, VERSION, ns, PLURAL, name,
                {"metadata": {"finalizers": finalizers}})

    wrapped = _Api()
    # advertise the scope so K8sBridge.sync_once GCs only inside it
    wrapped.namespace = namespace
    return wrapped


class K8sBridge:
    """Bidirectional sync between a cluster transport and a TopologyStore."""

    def __init__(self, store: TopologyStore, api: Any) -> None:
        self.store = store
        self.api = api
        self.cluster_rv: str = "0"
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # last status manifest pushed per key, to break the push→watch echo
        self._pushed_status: dict[str, dict] = {}
        self.stats = {"applied": 0, "deleted": 0, "status_pushed": 0,
                      "echoes_skipped": 0, "conflicts": 0}

    # -- cluster → store ----------------------------------------------

    def sync_once(self) -> int:
        """Initial LIST: seed/refresh every cluster object into the store
        (the informer's initial sync). Returns the object count."""
        items, rv = self.api.list_topologies()
        self.cluster_rv = rv
        seen = set()
        for manifest in items:
            self._apply(manifest)
            t = Topology.from_manifest(manifest)
            seen.add(t.key)
        # Objects gone from the cluster while we were away. GC only within
        # the transport's visibility: a namespace-scoped LIST says nothing
        # about other namespaces, so deleting store objects outside its
        # scope would wrongly wipe them on every resync.
        scope = getattr(self.api, "namespace", None)
        for t in self.store.list(scope):
            if t.key not in seen:
                self._delete(t.namespace, t.name)
        return len(items)

    def pump(self, events: Iterable[tuple[str, dict]]) -> int:
        """Apply a batch of (type, manifest) watch events. Returns the
        number applied."""
        n = 0
        for ev_type, manifest in events:
            if ev_type in ("ADDED", "MODIFIED"):
                self._apply(manifest)
            elif ev_type == "DELETED":
                meta = manifest.get("metadata", {})
                self._delete(meta.get("namespace", "default"), meta["name"])
            rv = manifest.get("metadata", {}).get("resourceVersion")
            if rv is not None:
                self.cluster_rv = rv
            n += 1
        return n

    def _apply(self, manifest: dict) -> None:
        incoming = Topology.from_manifest(manifest)

        # echo of our own status push? spec-identical + status we just
        # wrote ⇒ nothing to fold back into the store
        pushed = self._pushed_status.get(incoming.key)
        if pushed is not None and manifest.get("status") == pushed:
            try:
                current = self.store.get(incoming.namespace, incoming.name)
            except NotFoundError:
                current = None
            if current is not None and \
                    current.to_manifest().get("spec") == \
                    manifest.get("spec"):
                self.stats["echoes_skipped"] += 1
                return

        def txn():
            try:
                current = self.store.get(incoming.namespace, incoming.name)
            except NotFoundError:
                try:
                    self.store.create(incoming)
                except AlreadyExistsError:
                    raise ConflictError(incoming.key)
                return
            # status-only change by another writer: nothing to fold in —
            # bumping the store rv here would re-trigger reconciliation
            # cluster-wide on every peer's status write
            if current.spec == incoming.spec:
                return
            # cluster owns the spec; local owners keep writing status
            current.spec = incoming.spec
            self.store.update(current)

        try:
            retry_on_conflict(txn)
            self.stats["applied"] += 1
        except ConflictError:
            self.stats["conflicts"] += 1

    def _delete(self, ns: str, name: str) -> None:
        try:
            self.store.delete(ns, name)
            self.stats["deleted"] += 1
        except NotFoundError:
            pass
        self._pushed_status.pop(f"{ns}/{name}", None)

    # -- store → cluster ----------------------------------------------

    @staticmethod
    def _is_not_found(e: Exception) -> bool:
        return isinstance(e, NotFoundError) or \
            getattr(e, "status", None) == 404

    def push_status(self, topology: Topology) -> bool:
        """Write a locally-updated status (placement/applied links) to the
        cluster's status subresource — the clientset UpdateStatus
        direction. Returns False when the object vanished upstream (404);
        any other API error propagates so the caller's loop can retry —
        a transient failure must not read as deletion."""
        manifest = topology.to_manifest()
        status = manifest.get("status", {})
        if self._pushed_status.get(topology.key) == status:
            return True
        try:
            self.api.patch_status(topology.namespace, topology.name, status)
        except Exception as e:
            if self._is_not_found(e):
                return False
            raise
        # record as soon as the status landed: a later finalizer-patch
        # failure must not break suppression of this patch's echo
        self._pushed_status[topology.key] = status
        self.stats["status_pushed"] += 1
        if hasattr(self.api, "patch_finalizers"):
            try:
                self.api.patch_finalizers(topology.namespace, topology.name,
                                          list(topology.finalizers))
            except Exception as e:
                if not self._is_not_found(e):
                    raise
        return True

    # -- background informer ------------------------------------------

    # transient-failure backoff bounds (client-go reflector shape)
    BACKOFF_INITIAL_S = 1.0
    BACKOFF_MAX_S = 30.0

    @staticmethod
    def _is_expired(e: Exception) -> bool:
        return isinstance(e, WatchExpiredError) or \
            getattr(e, "status", None) == 410

    def run(self, on_error: Callable[[Exception], None] | None = None,
            stop: threading.Event | None = None) -> None:
        """Blocking informer loop: LIST once, then WATCH forever.

        Failure handling distinguishes the two reflector cases instead
        of treating every exception as "sleep 1s, full re-list":

        - **410 Gone / WatchExpiredError** (our resourceVersion fell out
          of the apiserver's retained window): a fresh LIST is the
          correct and ONLY recovery — taken immediately, no backoff
          (waiting cannot un-expire the version).
        - **transient errors** (network blips, apiserver restarts,
          5xx): resume the WATCH from the last seen resourceVersion
          after an exponential backoff (1s → 30s), WITHOUT re-listing —
          at 100k CRs a full LIST per blip is the difference between a
          hiccup and an outage.

        A successful watch event resets the backoff.
        """
        stop = stop if stop is not None else self._stop
        backoff = self.BACKOFF_INITIAL_S
        need_list = True
        while not stop.is_set():
            try:
                if need_list:
                    self.sync_once()
                    need_list = False
                    backoff = self.BACKOFF_INITIAL_S
                for ev in self.api.watch_topologies(self.cluster_rv):
                    if stop.is_set():
                        return
                    self.pump([ev])
                    backoff = self.BACKOFF_INITIAL_S
                # orderly end of stream (server-side watch timeout):
                # immediately re-watch from the last seen version
            except Exception as e:
                if on_error is not None:
                    on_error(e)
                if self._is_expired(e):
                    need_list = True  # re-list NOW; no sleep
                    continue
                stop.wait(backoff)
                backoff = min(backoff * 2.0, self.BACKOFF_MAX_S)

    def start(self) -> None:
        if self._thread is not None:
            return
        # each informer thread owns its own stop event: a predecessor
        # blocked in a never-yielding watch stays permanently stopped and
        # can never revive as a second pump against the same store
        self._stop = threading.Event()
        stop = self._stop
        self._thread = threading.Thread(target=lambda: self.run(stop=stop),
                                        daemon=True, name="k8s-bridge")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
