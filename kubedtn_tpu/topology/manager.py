"""ControllerManager — the controller-runtime manager equivalent.

The reference wraps its reconciler in a controller-runtime Manager with
leader election, health/ready probes, and a signal-driven run loop
(reference main.go:80-126: NewManager with LeaderElection +
LeaderElectionID "ac2ba29f.y-young.github.io", HealthProbeBindAddress,
AddHealthzCheck/AddReadyzCheck, mgr.Start). This module provides the same
operational surface for the in-process stack:

- **Leader election** over a coordination.k8s.io/Lease-shaped record with
  the store's optimistic concurrency as the CAS: candidates try to
  acquire/renew `{holder, acquired_at, renew_at, lease_duration}`; a
  stale lease (renew older than the lease duration) is taken over. Only
  the leader runs reconcile drains — exactly what LeaderElection=true
  buys the reference in an HA deployment.
- **healthz/readyz** on a tiny HTTP server: healthz answers 200 whenever
  the manager thread is alive (healthz.Ping parity); readyz answers 200
  only once the manager completed its initial full resync AND — with
  leader election on — reflects this instance's ability to serve.
- **Run loop**: a background thread pumping watch events through the
  (optionally concurrent) Reconciler until stopped.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubedtn_tpu.topology.reconciler import Reconciler
from kubedtn_tpu.utils.logging import fields as _fields
from kubedtn_tpu.utils.logging import get_logger

# parity with the reference's LeaderElectionID (main.go:87)
LEADER_ELECTION_ID = "ac2ba29f.y-young.github.io"


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease essentials."""

    name: str
    holder: str = ""
    acquired_at: float = 0.0
    renewed_at: float = 0.0
    lease_duration_s: float = 15.0
    transitions: int = 0


class LeaseStore:
    """Minimal lease registry with compare-and-swap semantics — the role
    the apiserver's resourceVersion CAS plays for client-go's
    leaderelection package. Thread-safe; shared by all candidates of one
    in-process 'cluster' (in a real cluster this is the Lease CR)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._leases: dict[str, Lease] = {}

    def try_acquire(self, name: str, identity: str, now: float,
                    lease_duration_s: float) -> bool:
        """Acquire if unheld/expired/ours; renew if ours. Atomic."""
        with self._lock:
            lease = self._leases.get(name)
            if lease is None:
                self._leases[name] = Lease(
                    name=name, holder=identity, acquired_at=now,
                    renewed_at=now, lease_duration_s=lease_duration_s)
                return True
            if lease.holder == identity:
                lease.renewed_at = now
                return True
            if now - lease.renewed_at > lease.lease_duration_s:
                # stale: take over (leader transition)
                lease.holder = identity
                lease.acquired_at = now
                lease.renewed_at = now
                lease.lease_duration_s = lease_duration_s
                lease.transitions += 1
                return True
            return False

    def release(self, name: str, identity: str) -> None:
        """Voluntary step-down (LeaderElectionReleaseOnCancel semantics —
        the next candidate need not wait out the lease)."""
        with self._lock:
            lease = self._leases.get(name)
            if lease is not None and lease.holder == identity:
                lease.holder = ""
                lease.renewed_at = 0.0

    def holder(self, name: str) -> str:
        with self._lock:
            lease = self._leases.get(name)
            return lease.holder if lease else ""


class KubeLeaseStore:
    """LeaseStore over real coordination.k8s.io/v1 Lease objects — the
    backend that makes leader election work ACROSS manager pods (the
    in-process LeaseStore only arbitrates within one process). Uses the
    apiserver's resourceVersion CAS exactly like client-go's
    leaderelection resourcelock.

    Time domain: the caller's `now` (the manager passes time.monotonic())
    is IGNORED — per-process monotonic clocks are meaningless between
    pods. Freshness is judged on the wall clock (`clock`, default
    time.time; the same NTP assumption client-go's lease durations make),
    and renewTime round-trips as an RFC3339 MicroTime so leases written
    by client-go interoperate.

    Duck-typed to the LeaseStore try_acquire/release/holder surface;
    construction requires the `kubernetes` package (gated, like
    topology.k8s.make_kube_api) or an injected api object with
    read/create/replace_namespaced_lease methods."""

    def __init__(self, namespace: str = "kubedtn-tpu", api=None,
                 clock=None) -> None:
        if api is None:
            try:
                import kubernetes  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "KubeLeaseStore needs the 'kubernetes' package (or an "
                    "injected api); in-process managers can share a plain "
                    "LeaseStore instead") from e
            api = kubernetes.client.CoordinationV1Api()
        self.api = api
        self.namespace = namespace
        self.clock = clock if clock is not None else time.time

    # -- field normalization ------------------------------------------

    @staticmethod
    def _field(obj, camel: str, snake: str, default=None):
        """One accessor for dict manifests (camelCase) and kubernetes
        client models (snake_case attributes)."""
        if obj is None:
            return default
        if isinstance(obj, dict):
            v = obj.get(camel, default)
        else:
            v = getattr(obj, snake, default)
        return default if v is None else v

    @staticmethod
    def _epoch(renew) -> float:
        """renewTime → epoch seconds: accepts datetime (real client),
        RFC3339 string (dict manifests), or a number (test fakes)."""
        import datetime as dt

        if renew is None or renew == "":
            return 0.0
        if isinstance(renew, (int, float)):
            return float(renew)
        if isinstance(renew, str):
            renew = dt.datetime.fromisoformat(renew.replace("Z", "+00:00"))
        if renew.tzinfo is None:
            renew = renew.replace(tzinfo=dt.timezone.utc)
        return renew.timestamp()

    @staticmethod
    def _rfc3339(epoch: float) -> str:
        import datetime as dt

        return dt.datetime.fromtimestamp(
            epoch, dt.timezone.utc).isoformat().replace("+00:00", "Z")

    @staticmethod
    def _is_conflict_or_missing(e: Exception) -> tuple[bool, bool]:
        status = getattr(e, "status", None)
        return status == 409, status == 404

    def _read(self, name: str):
        lease = self.api.read_namespaced_lease(name, self.namespace)
        spec = self._field(lease, "spec", "spec", {})
        meta = self._field(lease, "metadata", "metadata", {})
        return {
            "holder": self._field(spec, "holderIdentity",
                                  "holder_identity", "") or "",
            "renew_epoch": self._epoch(self._field(spec, "renewTime",
                                                   "renew_time", 0.0)),
            "duration": float(self._field(spec, "leaseDurationSeconds",
                                          "lease_duration_seconds", 0)
                              or 0),
            "transitions": int(self._field(spec, "leaseTransitions",
                                           "lease_transitions", 0) or 0),
            "rv": self._field(meta, "resourceVersion", "resource_version"),
        }

    def _body(self, name: str, identity: str, lease_duration_s: float,
              transitions: int, rv=None) -> dict:
        body = {
            "metadata": {"name": name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": identity,
                # apiserver validation requires a positive duration
                "leaseDurationSeconds": max(1, int(lease_duration_s)),
                "renewTime": self._rfc3339(self.clock()),
                "leaseTransitions": transitions,
            },
        }
        if rv is not None:
            body["metadata"]["resourceVersion"] = rv
        return body

    def try_acquire(self, name: str, identity: str, now: float,
                    lease_duration_s: float) -> bool:
        del now  # cross-pod freshness uses self.clock, not caller time
        try:
            cur = self._read(name)
        except Exception as e:
            _, missing = self._is_conflict_or_missing(e)
            if not missing:
                raise
            try:
                self.api.create_namespaced_lease(
                    self.namespace,
                    self._body(name, identity, lease_duration_s, 0))
                return True
            except Exception as e2:
                conflict, _ = self._is_conflict_or_missing(e2)
                if conflict:
                    return False  # racer created it first
                raise
        fresh = cur["holder"] and (
            self.clock() - cur["renew_epoch"] <= (cur["duration"]
                                                  or lease_duration_s))
        if cur["holder"] != identity and fresh:
            return False
        transitions = cur["transitions"] + (
            1 if cur["holder"] and cur["holder"] != identity else 0)
        try:
            self.api.replace_namespaced_lease(
                name, self.namespace,
                self._body(name, identity, lease_duration_s, transitions,
                           rv=cur["rv"]))
            return True
        except Exception as e:
            conflict, _ = self._is_conflict_or_missing(e)
            if conflict:
                return False  # lost the CAS to another candidate
            raise

    def release(self, name: str, identity: str) -> None:
        try:
            cur = self._read(name)
        except Exception:
            return
        if cur["holder"] != identity:
            return
        # empty holder + ancient renewTime: validation-legal and instantly
        # stale, so the next candidate takes over without waiting
        body = self._body(name, "", 1, cur["transitions"], rv=cur["rv"])
        body["spec"]["renewTime"] = self._rfc3339(0.0)
        try:
            self.api.replace_namespaced_lease(name, self.namespace, body)
        except Exception:
            pass  # a failed release just expires naturally

    def holder(self, name: str) -> str:
        try:
            return self._read(name)["holder"]
        except Exception:
            return ""


@dataclass
class ManagerStatus:
    alive: bool = False
    synced: bool = False     # initial full resync completed
    is_leader: bool = False
    reconciles: int = 0
    errors: int = 0
    checks: dict = field(default_factory=dict)


class ManagerCollector:
    """Prometheus collector for the controller side — the role of
    controller-runtime's metrics endpoint (reference main.go:82
    MetricsBindAddress): reconcile totals/errors, leadership gauge,
    sync state. Register with a prometheus_client CollectorRegistry
    (metrics.make_registry-style) and serve via metrics.MetricsServer."""

    def __init__(self, manager: "ControllerManager") -> None:
        self._mgr = manager

    def collect(self):
        from prometheus_client.core import (CounterMetricFamily,
                                            GaugeMetricFamily)

        st = self._mgr.status
        labels = ["identity"]
        values = [self._mgr.identity]

        c = CounterMetricFamily(
            "controller_runtime_reconcile_total",
            "Total number of reconciliations", labels=labels)
        c.add_metric(values, float(st.reconciles))
        yield c
        e = CounterMetricFamily(
            "controller_runtime_reconcile_errors_total",
            "Total number of reconciliation errors", labels=labels)
        e.add_metric(values, float(st.errors))
        yield e
        g = GaugeMetricFamily(
            "leader_election_master_status",
            "1 when this instance holds the leader lease",
            labels=labels)
        g.add_metric(values, 1.0 if st.is_leader else 0.0)
        yield g
        s = GaugeMetricFamily(
            "controller_synced", "1 once the initial resync completed",
            labels=labels)
        s.add_metric(values, 1.0 if st.synced else 0.0)
        yield s


class _ProbeHandler(BaseHTTPRequestHandler):
    manager: "ControllerManager" = None  # set per server

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        mgr = self.manager
        if self.path.startswith("/healthz"):
            ok, body = mgr.healthz()
        elif self.path.startswith("/readyz"):
            ok, body = mgr.readyz()
        else:
            self.send_response(404)
            self.end_headers()
            return
        payload = json.dumps(body).encode()
        self.send_response(200 if ok else 503)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, *args):  # probes are too chatty for stdout
        pass


class ControllerManager:
    """Runs a Reconciler continuously with optional leader election and
    health/ready probes (reference main.go:80-126)."""

    def __init__(self, store, engine, identity: str = "manager-0",
                 workers: int = 1,
                 leader_election: bool = False,
                 lease_store: LeaseStore | None = None,
                 lease_duration_s: float = 2.0,
                 renew_interval_s: float = 0.5,
                 probe_port: int | None = None,
                 probe_host: str = "0.0.0.0",
                 metrics_port: int | None = None,
                 poll_interval_s: float = 0.02) -> None:
        self.store = store
        self.engine = engine
        self.identity = identity
        self.workers = workers
        self.leader_election = leader_election
        self.leases = lease_store if lease_store is not None else LeaseStore()
        self.lease_duration_s = lease_duration_s
        self.renew_interval_s = renew_interval_s
        self.poll_interval_s = poll_interval_s
        self.status = ManagerStatus()
        self.log = get_logger("manager")
        self.reconciler: Reconciler | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._http: ThreadingHTTPServer | None = None
        self._probe_host = probe_host
        self._probe_port_req = probe_port
        self.probe_port: int | None = None
        self.metrics = None
        self._metrics_port_req = metrics_port
        self.metrics_port: int | None = None
        if metrics_port is not None:
            self._start_metrics()
        if probe_port is not None:
            # probes answer from construction (503 until started), like a
            # pod whose kubelet probes begin before the process is ready
            self._start_probes()

    def _start_metrics(self) -> None:
        """Controller metrics endpoint (reference MetricsBindAddress
        :8080, main.go:82). Same never-raise port policy as the probes:
        preferred → requested → any free port."""
        if self.metrics is not None or self._metrics_port_req is None:
            return
        from prometheus_client import CollectorRegistry

        from kubedtn_tpu.metrics.metrics import MetricsServer

        registry = CollectorRegistry()
        registry.register(ManagerCollector(self))
        preferred = self.metrics_port if self.metrics_port is not None \
            else self._metrics_port_req
        for port in dict.fromkeys((preferred, self._metrics_port_req, 0)):
            try:
                self.metrics = MetricsServer(registry, port=port)
                break
            except OSError:
                self.log.warning("metrics port %s unavailable; trying next",
                                 port)
        else:  # pragma: no cover — port 0 cannot fail to bind
            return
        self.metrics.start()
        self.metrics_port = self.metrics.port

    def _start_probes(self) -> None:
        if self._http is not None or self._probe_port_req is None:
            return
        handler = type("Handler", (_ProbeHandler,), {"manager": self})
        # all interfaces by default: kubelet httpGet probes dial the
        # pod IP (reference HealthProbeBindAddress ":8081"); a restart
        # prefers the SAME port the first bind chose, but if someone took
        # it while we were stopped, fall back to the requested port (a
        # fresh ephemeral when that was 0) — start() must never raise
        preferred = self.probe_port if self.probe_port is not None \
            else self._probe_port_req
        # preferred port → requested port → any free port: a restart must
        # come back with probes on SOME port, never raise
        for port in dict.fromkeys((preferred, self._probe_port_req, 0)):
            try:
                self._http = ThreadingHTTPServer(
                    (self._probe_host, port), handler)
                break
            except OSError:
                self.log.warning("probe port %s unavailable; trying next",
                                 port)
        else:  # pragma: no cover — port 0 cannot fail to bind
            return
        self.probe_port = self._http.server_port
        threading.Thread(target=self._http.serve_forever, daemon=True,
                         name=f"probes-{self.identity}").start()

    # -- probes --------------------------------------------------------

    def healthz(self) -> tuple[bool, dict]:
        """healthz.Ping parity: alive ⇔ the manager loop is running."""
        ok = self.status.alive
        return ok, {"status": "ok" if ok else "not started",
                    "checks": {"ping": ok}}

    def readyz(self) -> tuple[bool, dict]:
        """Leader: ready once the initial resync completed. Standby: ready
        by virtue of being able to take over (it has no watch open yet, so
        `synced` cannot be its criterion) — mirroring controller-runtime,
        where readyz does not gate on leadership."""
        standby = (self.leader_election and not self.status.is_leader)
        ok = self.status.alive and (self.status.synced or standby)
        return ok, {
            "status": "ok" if ok else "not ready",
            "checks": {"alive": self.status.alive,
                       "synced": self.status.synced,
                       "standby": standby,
                       "leader": self.status.is_leader},
        }

    # -- leadership ----------------------------------------------------

    def _try_leadership(self) -> bool:
        if not self.leader_election:
            return True
        now = time.monotonic()
        got = self.leases.try_acquire(LEADER_ELECTION_ID, self.identity,
                                      now, self.lease_duration_s)
        if got and not self.status.is_leader:
            self.log.info("became leader %s", _fields(
                identity=self.identity, lease=LEADER_ELECTION_ID))
        elif not got and self.status.is_leader:
            self.log.warning("lost leadership %s", _fields(
                identity=self.identity))
        self.status.is_leader = got
        return got

    def _renew_loop(self) -> None:
        """Dedicated lease renewer: leadership is kept alive INDEPENDENTLY
        of drain duration — a multi-second drain (reconcile_100k measures
        seconds) must not let the lease expire mid-drain and split-brain
        into a second concurrent leader."""
        while not self._stop.is_set():
            if self.status.is_leader:
                self._try_leadership()
            self._stop.wait(self.renew_interval_s)

    # -- run loop ------------------------------------------------------

    def _run(self) -> None:
        self.status.alive = True
        last_acquire = 0.0
        try:
            while not self._stop.is_set():
                now = time.monotonic()
                # acquisition only — once leading, the dedicated renew
                # thread keeps the lease alive (doubling renewals here
                # would just double apiserver traffic)
                if not self.status.is_leader and \
                        now - last_acquire >= self.renew_interval_s:
                    self._try_leadership()
                    last_acquire = now
                if not self.status.is_leader and self.leader_election:
                    # standby: stay synced-false until first leadership
                    self._stop.wait(self.renew_interval_s)
                    continue
                if self.reconciler is None:
                    # the watch opens at leadership start: replay delivers
                    # the full current state (informer initial LIST)
                    self.reconciler = Reconciler(self.store, self.engine)
                try:
                    results = self.reconciler.drain(workers=self.workers)
                    self.status.reconciles += len(results)
                    if not self.status.synced:
                        self.status.synced = True
                        self.log.info("initial resync complete %s", _fields(
                            identity=self.identity,
                            reconciles=self.status.reconciles))
                except Exception:
                    self.status.errors += 1
                    self.log.exception("drain failed (continuing)")
                self._stop.wait(self.poll_interval_s)
        finally:
            self.status.alive = False
            self.status.is_leader = False
            if self.leader_election:
                self.leases.release(LEADER_ELECTION_ID, self.identity)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._start_probes()   # recreate after a stop()
        self._start_metrics()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"manager-{self.identity}")
        self._thread.start()
        if self.leader_election:
            threading.Thread(target=self._renew_loop, daemon=True,
                             name=f"lease-renew-{self.identity}").start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()  # release the listening socket
            self._http = None
        if self.metrics is not None:
            self.metrics.stop()
            self.metrics = None
