"""In-process Topology store with Kubernetes API-server semantics.

The reference's durable state lives entirely in the Topology CR — spec is
desired links, status carries placement (SrcIP/NetNs) and last-applied links
— read and written concurrently by the controller and the CNI daemon with
optimistic concurrency (RetryOnConflict, reference
controllers/topology_controller.go:124-138 and daemon/kubedtn/handler.go:101,125),
plus a finalizer protecting pod teardown (handler.go:125-140).

This store reproduces those semantics in-process so the reconcile/status race
discipline survives intact: per-object resourceVersion, conflict on stale
writes, status-vs-metadata update split, finalizer-gated deletion, and a
watch stream equivalent to the daemon's shared informer
(reference daemon/kubedtn/kubedtn.go:128-142). A K8s-backed implementation
can replace it behind the same interface.
"""

from __future__ import annotations

# (generic deepcopy replaced by Topology.clone — Link immutability makes
# structural sharing safe and ~20x cheaper at 100k-link scale)
import threading
from collections import deque
from dataclasses import dataclass
from typing import Callable, Iterator

from kubedtn_tpu.api.types import Topology


class ConflictError(Exception):
    """Optimistic-concurrency failure (HTTP 409 equivalent)."""


class NotFoundError(KeyError):
    """Object does not exist (HTTP 404 equivalent)."""


class AlreadyExistsError(Exception):
    """Create of an existing object (HTTP 409 AlreadyExists equivalent)."""


@dataclass(frozen=True)
class WatchEvent:
    type: str  # "ADDED" | "MODIFIED" | "DELETED"
    topology: Topology


def _key(namespace: str, name: str) -> str:
    return f"{namespace}/{name}"


class TopologyStore:
    """Thread-safe optimistic-concurrency store for Topology objects."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._objects: dict[str, Topology] = {}
        self._rv = 0
        self._watchers: list[deque[WatchEvent]] = []
        # Bumped only when some object's placement (status.src_ip/net_ns)
        # may have changed — object create/delete or a status write that
        # touches those fields. Lets the engine cache alive/src-ip answers
        # for an entire reconcile drain (status copy-backs don't move
        # placement, so the cache survives them).
        self._placement_gen = 0

    @property
    def placement_generation(self) -> int:
        with self._lock:
            return self._placement_gen

    # -- internal ------------------------------------------------------

    def _emit(self, event: WatchEvent) -> None:
        for q in self._watchers:
            q.append(event)

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    # -- CRUD ----------------------------------------------------------

    def create(self, topology: Topology) -> Topology:
        with self._lock:
            k = topology.key
            if k in self._objects:
                raise AlreadyExistsError(k)
            obj = topology.clone()
            obj.resource_version = self._next_rv()
            obj.deletion_requested = False
            self._objects[k] = obj
            self._placement_gen += 1
            self._emit(WatchEvent("ADDED", obj.clone()))
            return obj.clone()

    def get(self, namespace: str, name: str) -> Topology:
        with self._lock:
            k = _key(namespace or "default", name)
            if k not in self._objects:
                raise NotFoundError(k)
            return self._objects[k].clone()

    def peek_placement(self, namespace: str, name: str) -> tuple[str, str]:
        """Read (src_ip, net_ns) without cloning the object — the alive
        check runs once per (topology, peer) during reconcile and a full
        clone of a 1000-link CR just to read two strings dominated the
        100k-link drain. Raises NotFoundError like get()."""
        with self._lock:
            k = _key(namespace or "default", name)
            obj = self._objects.get(k)
            if obj is None:
                raise NotFoundError(k)
            return obj.status.src_ip, obj.status.net_ns

    def list(self, namespace: str | None = None) -> list[Topology]:
        with self._lock:
            out = [
                o.clone()
                for o in self._objects.values()
                if namespace is None or o.namespace == namespace
            ]
            return sorted(out, key=lambda t: t.key)

    def _check_and_bump(self, incoming: Topology) -> Topology:
        k = incoming.key
        if k not in self._objects:
            raise NotFoundError(k)
        current = self._objects[k]
        if incoming.resource_version != current.resource_version:
            raise ConflictError(
                f"{k}: stale resourceVersion "
                f"{incoming.resource_version} != {current.resource_version}"
            )
        return current

    def update(self, topology: Topology) -> Topology:
        """Update spec + metadata (finalizers). Like the reference's
        clientset Update (api/clientset/v1beta1/topology.go:141-155)."""
        with self._lock:
            current = self._check_and_bump(topology)
            obj = current.clone()
            obj.spec = topology.spec.clone()
            obj.finalizers = list(topology.finalizers)
            obj.resource_version = self._next_rv()
            self._objects[obj.key] = obj
            self._finalize_if_due(obj.key)
            if obj.key in self._objects:
                self._emit(WatchEvent("MODIFIED", obj.clone()))
            return obj.clone()

    def update_status(self, topology: Topology) -> Topology:
        """Update only the status subresource, like the reference's
        UpdateStatus PUT (api/clientset/v1beta1/topology.go:171-184)."""
        with self._lock:
            current = self._check_and_bump(topology)
            if (current.status.src_ip != topology.status.src_ip
                    or current.status.net_ns != topology.status.net_ns):
                self._placement_gen += 1
            obj = current.clone()
            obj.status = topology.status.clone()
            obj.resource_version = self._next_rv()
            self._objects[obj.key] = obj
            self._emit(WatchEvent("MODIFIED", obj.clone()))
            return obj.clone()

    def delete(self, namespace: str, name: str) -> None:
        """Request deletion; the object lingers while finalizers remain,
        matching the CR finalizer flow the reference relies on to keep
        topology data alive until DestroyPod clears it
        (reference daemon/kubedtn/handler.go:125-140, 559-577)."""
        with self._lock:
            k = _key(namespace or "default", name)
            if k not in self._objects:
                raise NotFoundError(k)
            obj = self._objects[k]
            obj.deletion_requested = True
            obj.resource_version = self._next_rv()
            self._finalize_if_due(k)
            if k in self._objects:
                self._emit(WatchEvent("MODIFIED", obj.clone()))

    def _finalize_if_due(self, k: str) -> None:
        obj = self._objects.get(k)
        if obj is not None and obj.deletion_requested and not obj.finalizers:
            del self._objects[k]
            self._placement_gen += 1
            self._emit(WatchEvent("DELETED", obj.clone()))

    # -- watch ---------------------------------------------------------

    def watch(self, replay: bool = True) -> "Watch":
        """Open a watch stream. With replay=True (default) existing objects
        are delivered first as ADDED events — informer list+watch semantics
        (reference daemon/kubedtn/kubedtn.go:128-142)."""
        with self._lock:
            q: deque[WatchEvent] = deque()
            if replay:
                for obj in self._objects.values():
                    q.append(WatchEvent("ADDED", obj.clone()))
            self._watchers.append(q)
            return Watch(self, q)

    def _unwatch(self, q: deque[WatchEvent]) -> None:
        with self._lock:
            if q in self._watchers:
                self._watchers.remove(q)


class Watch:
    """Pull-based watch stream (informer-equivalent)."""

    def __init__(self, store: TopologyStore, q: deque[WatchEvent]) -> None:
        self._store = store
        self._q = q

    def poll(self) -> Iterator[WatchEvent]:
        while True:
            try:
                yield self._q.popleft()
            except IndexError:
                return

    def close(self) -> None:
        self._store._unwatch(self._q)


def retry_on_conflict(fn: Callable[[], None], retries: int = 5) -> None:
    """client-go RetryOnConflict equivalent: re-read + re-apply on 409.

    Mirrors the retry discipline at reference
    controllers/topology_controller.go:125-138 (DefaultRetry is 5 steps).
    """
    last: ConflictError | None = None
    for _ in range(retries):
        try:
            fn()
            return
        except ConflictError as e:
            last = e
    raise last  # type: ignore[misc]
