"""Topology reconciler — the controller equivalent.

Reproduces the reference controller's reconcile contract
(reference controllers/topology_controller.go:61-156) against the in-process
store and the SimEngine:

- no-op when status.links already equals spec.links (:77-79);
- first-seen rule: status.links == None means the CNI path did the initial
  plumbing, so only copy spec → status (:81-85);
- otherwise diff status vs spec into (add, del, properties-changed) sets and
  push them to the engine as DelLinks → AddLinks → UpdateLinks (:88-119);
- finally copy spec → status under RetryOnConflict, because the CNI/daemon
  path also writes status (:124-138).

Differences by design (TPU-first): CalcDiff is O(n) over a hash of the
8-field link identity instead of the reference's O(n²) double loop
(:288-318), and reconciles are batched-serial — batching into single device
scatters replaces the reference's 32 concurrent reconcile workers (:336) as
the scaling mechanism.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from kubedtn_tpu.api.types import Link
from kubedtn_tpu.topology.engine import SimEngine
from kubedtn_tpu.topology.store import (
    NotFoundError,
    TopologyStore,
    retry_on_conflict,
)
from kubedtn_tpu.utils.logging import fields as _fields
from kubedtn_tpu.utils.logging import get_logger


def _identity(link: Link) -> tuple:
    """The 8-field link identity of EqualWithoutProperties
    (reference controllers/topology_controller.go:342-351)."""
    return (
        link.local_intf, link.local_ip, link.local_mac,
        link.peer_intf, link.peer_ip, link.peer_mac,
        link.peer_pod, link.uid,
    )


def calc_diff(old: list[Link], new: list[Link]):
    """O(n) diff: returns (add, delete, properties_changed).

    Same outputs as the reference's CalcDiff (topology_controller.go:288-318)
    computed via hash join instead of the nested scan. Identities are built
    once per link per call — at 100k-link drains the repeated tuple packing
    was itself a profile line. The two degenerate cases (first realize:
    nothing applied yet; teardown: empty spec) skip identity building
    entirely — at 1M links the realize drain otherwise spends ~15% of its
    time packing tuples whose only consumer would say "add everything".
    """
    if not old:
        return list(new), [], []
    if not new:
        return [], list(old), []
    old_ids = [_identity(l) for l in old]
    new_ids = [_identity(l) for l in new]
    old_by_id = dict(zip(old_ids, old))
    new_seen = set(new_ids)
    add: list[Link] = []
    changed: list[Link] = []
    for ident, link in zip(new_ids, new):
        prev = old_by_id.get(ident)
        if prev is None:
            add.append(link)
        elif prev.properties != link.properties:
            changed.append(link)
    delete = [l for i, l in zip(old_ids, old) if i not in new_seen]
    return add, delete, changed


class WorkQueue:
    """client-go-style rate-unlimited workqueue: the dedup discipline that
    lets the reference run 32 concurrent reconcile workers safely
    (reference controllers/topology_controller.go:336 sets
    MaxConcurrentReconciles; the queue semantics are client-go
    util/workqueue's dirty/processing sets).

    Invariants (re-derived, not translated):
    - a key is never handed to two workers at once (per-topology ordering);
    - add() of a key currently being processed marks it dirty, and done()
      re-queues it — an update arriving mid-reconcile is never lost;
    - add() of a key already queued is a no-op (dedup/coalescing).
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._queue: list = []          # FIFO of keys ready for a worker
        self._dirty: set = set()        # keys needing (re)processing
        self._processing: set = set()   # keys a worker holds right now
        self._shutdown = False

    def add(self, key) -> None:
        with self._cond:
            if self._shutdown or key in self._dirty:
                return
            self._dirty.add(key)
            if key in self._processing:
                return  # done() will re-queue it
            self._queue.append(key)
            self._cond.notify()

    def get(self, timeout: float | None = None):
        """Blocking take; returns None on shutdown or timeout."""
        with self._cond:
            while not self._queue and not self._shutdown:
                if not self._cond.wait(timeout):
                    return None
            if not self._queue:
                return None
            key = self._queue.pop(0)
            self._processing.add(key)
            self._dirty.discard(key)
            return key

    def done(self, key) -> None:
        with self._cond:
            self._processing.discard(key)
            if key in self._dirty and not self._shutdown:
                self._queue.append(key)
                self._cond.notify()

    def idle(self) -> bool:
        with self._cond:
            return not self._queue and not self._processing \
                and not self._dirty

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()


@dataclass
class ReconcileResult:
    key: str
    action: str  # "noop" | "first-seen" | "changed" | "deleted"
    added: int = 0
    deleted: int = 0
    updated: int = 0
    # False ⇔ an engine op reported failure (e.g. a cross-node completion
    # RPC); status is NOT copied in that case, so the next pass re-diffs
    # and retries — the reference returns the error to controller-runtime
    # for requeue (topology_controller.go:120-122)
    ok: bool = True
    # False ⇔ the failure is a deterministic VERDICT (the twin gate
    # rejected the plan), not a transient error: drain must not requeue
    # it — retrying re-rejects forever
    retryable: bool = True
    phase_ms: dict[str, float] = field(default_factory=dict)


class Reconciler:
    """Cluster-level reconcile loop over the TopologyStore.

    With `planned=True` and a live data plane attached, topology DELTAS
    (action "changed" on an already-realized topology) route through the
    planned-update engine (kubedtn_tpu.updates): ordered rounds, twin
    verification gate, staged apply with rollback. Direct apply remains
    the bootstrap path (first-seen), the fallback when the planner
    infrastructure errors, and the default (`planned=False`). A plan the
    GATE rejects is a policy verdict, not a transient failure: status
    stays stale, the result carries action "plan-rejected", and the key
    is NOT requeued (retrying a deterministic rejection forever would
    spin); a mid-staging ROLLBACK requeues like any transient failure.
    """

    def __init__(self, store: TopologyStore, engine: SimEngine,
                 plane=None, planned: bool = False, guardrails=None,
                 observe_ticks: int = 2, update_stats=None) -> None:
        self.store = store
        self.engine = engine
        self.plane = plane
        self.planned = bool(planned)
        self.guardrails = guardrails
        self.observe_ticks = observe_ticks
        self.update_stats = update_stats
        self._watch = store.watch()
        # keys whose last reconcile failed, retried on the next drain pass
        # (controller-runtime's requeue-on-error)
        self._requeue: set[tuple[str, str]] = set()
        # controller-side structured logs (the reference controller logs
        # through zap, main.go:61-78)
        self.log = get_logger("reconciler")

    def reconcile(self, namespace: str, name: str) -> ReconcileResult:
        """One reconcile pass for one Topology, mirroring Reconcile
        (topology_controller.go:61-156)."""
        key = f"{namespace or 'default'}/{name}"
        from kubedtn_tpu.utils import tracing

        with tracing.span("reconcile", key=key):
            return self._reconcile_traced(namespace, name, key)

    def _reconcile_traced(self, namespace: str, name: str,
                          key: str) -> ReconcileResult:
        t_start = time.perf_counter()
        tenancy = getattr(self.engine, "tenancy", None)
        if tenancy is not None:
            # namespace → tenant mapping: every reconciled topology is
            # attributable to a tenant from its first link (an unmapped
            # namespace auto-registers a default-QoS unlimited tenant
            # named after it; operators tighten quotas via kdt tenant)
            tenancy.ensure_namespace(namespace or "default")
        try:
            topo = self.store.get(namespace, name)
        except NotFoundError:
            return ReconcileResult(key=key, action="deleted")

        if topo.status.links == topo.spec.links:
            return ReconcileResult(key=key, action="noop")

        result = ReconcileResult(key=key, action="changed")
        if topo.status.links is None:
            # First sight of this topology: assume the CNI/setup path has
            # plumbed the initial links; just copy them to status below.
            result.action = "first-seen"
        else:
            add, delete, changed = calc_diff(topo.status.links,
                                             topo.spec.links)
            result.added = len(add)
            result.deleted = len(delete)
            result.updated = len(changed)
            handled = False
            if self.planned and self.plane is not None:
                handled = self._reconcile_planned(
                    topo, key, result, diff=(add, delete, changed))
                if handled and result.action == "plan-rejected":
                    # a deterministic gate verdict: surface it, leave
                    # status stale, do NOT requeue (see class docstring)
                    result.phase_ms["total"] = (
                        time.perf_counter() - t_start) * 1e3
                    return result
            if not handled:
                failed: dict[str, list[int]] = {}
                t0 = time.perf_counter()
                if not self.engine.del_links(topo, delete):
                    failed["del"] = [l.uid for l in delete]
                result.phase_ms["del"] = (time.perf_counter() - t0) * 1e3
                t0 = time.perf_counter()
                if not self.engine.add_links(topo, add):
                    failed["add"] = [l.uid for l in add]
                result.phase_ms["add"] = (time.perf_counter() - t0) * 1e3
                t0 = time.perf_counter()
                if not self.engine.update_links(topo, changed):
                    failed["update"] = [l.uid for l in changed]
                result.phase_ms["update"] = (
                    time.perf_counter() - t0) * 1e3
                if failed:
                    result.ok = False
                    # partial apply: some phases landed, this one did
                    # not — name the failed link set so the half-applied
                    # delta is diagnosable, not just a boolean
                    self.log.warning("reconcile partial apply %s",
                                     _fields(topology=key,
                                             failed_links=failed))

        if not result.ok:
            # Engine failure (e.g. the peer daemon rejected a cross-node
            # completion): leave status stale so the link is NOT recorded
            # as realized — the next pass re-diffs and retries, exactly
            # like controller-runtime requeueing on a returned error
            # (reference topology_controller.go:120-122). Copying status
            # here would declare a half-realized link done forever.
            # Requeue HERE, not only in drain's result loop: direct
            # reconcile()/reconcile_all() callers (startup resync) must
            # also get the retry, or a half-applied delta sits unfixed
            # until the next unrelated watch event.
            self._requeue.add((namespace, name))
            result.phase_ms["total"] = (time.perf_counter() - t_start) * 1e3
            self.log.warning("reconcile failed %s", _fields(
                topology=key, action=result.action, added=result.added,
                deleted=result.deleted, updated=result.updated,
                requeue=True))
            return result

        t0 = time.perf_counter()

        def txn():
            try:
                fresh = self.store.get(namespace, name)
            except NotFoundError:
                return
            fresh.status.links = list(topo.spec.links)
            self.store.update_status(fresh)

        retry_on_conflict(txn)
        result.phase_ms["retry"] = (time.perf_counter() - t0) * 1e3
        result.phase_ms["total"] = (time.perf_counter() - t_start) * 1e3
        if result.action != "noop":
            self.log.debug("reconcile %s", _fields(
                topology=key, action=result.action, added=result.added,
                deleted=result.deleted, updated=result.updated,
                ms=round(result.phase_ms["total"], 2)))
        return result

    def _reconcile_planned(self, topo, key: str,
                           result: ReconcileResult,
                           diff=None) -> bool:
        """Route one delta through the planned-update engine. Returns
        True when handled (result carries the verdict: action
        "planned" on success, "plan-rejected" on a gate veto,
        "plan-rolled-back" on a staging rollback); False to fall back
        to the direct path (planner infrastructure error — the delta
        must still land)."""
        from kubedtn_tpu.updates import (PlanError, plan_update,
                                         verify_plan_live)

        t0 = time.perf_counter()
        try:
            plan = plan_update(topo.status.links, topo.spec.links,
                               namespace=topo.namespace, name=topo.name,
                               diff=diff)
        except PlanError:
            self.log.exception("planner failed; direct apply %s",
                               _fields(topology=key))
            if self.update_stats is not None:
                self.update_stats.record_plan_error()
            return False
        if not plan.rounds:
            return True  # empty diff (identity-only churn): nothing to do
        try:
            verdict = verify_plan_live(self.plane, plan,
                                       guardrails=self.guardrails)
        except Exception:
            # gate infrastructure failure (not a verdict): the delta
            # must still land — fall back to the direct path, loudly
            self.log.exception("update gate failed; direct apply %s",
                               _fields(topology=key))
            if self.update_stats is not None:
                self.update_stats.record_plan_error()
            return False
        if self.update_stats is not None:
            self.update_stats.record_plan(verdict)
        result.phase_ms["gate"] = (time.perf_counter() - t0) * 1e3
        if not verdict.ok:
            result.ok = False
            result.retryable = False
            result.action = "plan-rejected"
            self.log.warning("plan rejected by twin gate %s", _fields(
                topology=key, reason=verdict.reason,
                gate_ms=round(verdict.gate_s * 1e3, 1)))
            return True
        t0 = time.perf_counter()
        from kubedtn_tpu.updates.stager import StagingBusyError

        stager = self.plane.update_stager(stats=self.update_stats)
        try:
            stage = stager.stage(plan, topo,
                                 observe_ticks=self.observe_ticks,
                                 guardrails=self.guardrails)
        except StagingBusyError as e:
            # another staging in progress: a transient condition — fail
            # the pass so the key requeues and retries next drain.
            # (Deliberately NOT `except RuntimeError`: device errors
            # subclass RuntimeError and belong to the failure branch.)
            result.ok = False
            result.action = "plan-busy"
            self.log.warning("staging busy %s", _fields(
                topology=key, error=str(e)))
            return True
        except Exception:
            # unexpected staging failure: the stager already rolled the
            # applied rounds back before re-raising — swallow it HERE so
            # one topology's failure cannot abort a serial drain() pass
            # mid-loop (stranding every other pending delta after the
            # watch events were consumed); fail the pass and requeue
            result.ok = False
            result.action = "plan-failed"
            if self.update_stats is not None:
                self.update_stats.record_plan_error()
            self.log.exception("staged update failed %s",
                               _fields(topology=key))
            return True
        result.phase_ms["stage"] = (time.perf_counter() - t0) * 1e3
        if stage.ok:
            result.action = "planned"
            return True
        result.ok = False
        result.action = "plan-rolled-back"
        self.log.warning("staged update rolled back %s", _fields(
            topology=key, reason=stage.reason))
        return True

    def drain(self, max_passes: int = 64,
              workers: int = 1) -> list[ReconcileResult]:
        """Process watch events until the store is steady — the loop the
        controller-runtime manager provides in the reference
        (reference main.go:104-110). workers>1 runs the reference's
        concurrent-reconciler shape (MaxConcurrentReconciles=32,
        topology_controller.go:336) over a WorkQueue, preserving
        per-topology ordering."""
        if workers > 1:
            return self._drain_concurrent(max_passes, workers)
        results: list[ReconcileResult] = []
        for _ in range(max_passes):
            events = list(self._watch.poll())
            retries, self._requeue = self._requeue, set()
            if not events and not retries:
                return results
            seen: set[tuple[str, str]] = set()
            for nk in [(ev.topology.namespace, ev.topology.name)
                       for ev in events] + sorted(retries):
                if nk in seen:
                    continue
                seen.add(nk)
                res = self.reconcile(*nk)
                if not res.ok and res.retryable:
                    self._requeue.add(nk)
                results.append(res)
        return results

    def _drain_concurrent(self, max_passes: int,
                          workers: int) -> list[ReconcileResult]:
        q = WorkQueue()
        results: list[ReconcileResult] = []
        lock = threading.Lock()
        attempts: dict[tuple[str, str], int] = {}
        stop = threading.Event()

        errors: list[Exception] = []

        def work() -> None:
            while True:
                key = q.get(timeout=0.02)
                if key is None:
                    if stop.is_set():
                        return
                    continue
                try:
                    res = self.reconcile(*key)
                    with lock:
                        results.append(res)
                        if not res.ok and res.retryable:
                            attempts[key] = attempts.get(key, 0) + 1
                            if attempts[key] < max_passes:
                                q.add(key)  # bounded in-drain retry
                            else:
                                self._requeue.add(key)  # next drain
                except Exception as e:  # e.g. retry_on_conflict exhausted
                    # surface it like the serial path would (re-raised by
                    # the pump loop below); the key re-queues so a later
                    # drain can still converge
                    with lock:
                        errors.append(e)
                        self._requeue.add(key)
                finally:
                    # ALWAYS release the key: a skipped done() would pin
                    # it in _processing and hang the drain forever
                    q.done(key)

        threads = [threading.Thread(target=work, daemon=True,
                                    name=f"reconcile-{i}")
                   for i in range(workers)]
        for t in threads:
            t.start()
        retries, self._requeue = self._requeue, set()
        for nk in sorted(retries):
            q.add(nk)
        try:
            while True:
                with lock:
                    if errors:
                        raise errors[0]
                pumped = 0
                for ev in self._watch.poll():
                    q.add((ev.topology.namespace, ev.topology.name))
                    pumped += 1
                if pumped == 0 and q.idle():
                    # workers emit status-copy events BEFORE q.done(), so
                    # with the queue idle any stragglers are already in
                    # the watch deque — one more empty poll means steady
                    stragglers = list(self._watch.poll())
                    if not stragglers:
                        break
                    for ev in stragglers:
                        q.add((ev.topology.namespace, ev.topology.name))
                time.sleep(0.001)
        finally:
            stop.set()
            q.shut_down()
            for t in threads:
                t.join(timeout=5)
        return results

    def reconcile_all(self) -> list[ReconcileResult]:
        """Full-cluster pass (startup resync)."""
        return [
            self.reconcile(t.namespace, t.name) for t in self.store.list()
        ]
