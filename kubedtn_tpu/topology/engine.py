"""SimEngine — the node-daemon equivalent, in front of device arrays.

The reference's per-node daemon (reference daemon/kubedtn/handler.go) turns
pod lifecycle and link batches into kernel plumbing: veth pairs, VXLAN
tunnels, qdisc chains. This engine turns the same calls into row operations
on the batched EdgeState device arrays (kubedtn_tpu.ops.edge_state) — one
row per directed link endpoint.

Reference behaviors reproduced exactly:
- SetupPod (handler.go:495-535): unknown pod → "not in topology" and
  delegate; otherwise mark alive (status.src_ip/net_ns + finalizer) and add
  every spec link.
- addLink dispatch (handler.go:316-459): macvlan for peer "localhost" (the
  reference applies NO qdiscs on macvlan links — handler.go:335-345);
  "physical/<ip>" links realized immediately on behalf of the physical
  host; pod-to-pod links gated on peer aliveness — "whoever comes up last
  does the plumbing" (handler.go:386-395), and the plumbing pod's declared
  properties are applied to BOTH ends (common/veth.go:44-62 applies
  link.Properties to self and peer; common/utils.go:39-68 ships the same
  properties to the remote end).
- UpdateLinks (handler.go:634-671): rebuilds only the LOCAL end's qdiscs.
- DestroyPod (handler.go:538-590): clear alive status + finalizers, then
  delete the pod's link rows; deleting a local veth end kills the pair, so
  both directions of its links are deactivated.

Batched device ops are padded to power-of-two bucket sizes so the jitted
scatters compile O(log n) distinct shapes, never per batch.
"""

from __future__ import annotations

import functools
import threading
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from kubedtn_tpu.api.types import (LOCALHOST, PHYSICAL_PREFIX,
                                   Link, Topology)
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.utils.logging import fields as _fields
from kubedtn_tpu.utils.logging import get_logger
from kubedtn_tpu.topology.freelist import FreeStack
from kubedtn_tpu.topology.store import (
    NotFoundError,
    TopologyStore,
    retry_on_conflict,
)

# VXLAN VNI base kept for wire-level parity (reference common/constants.go:8,
# common/utils.go:29-36: vni = 5000 + uid).
VXLAN_BASE = 5000

# Non-donating re-jits of the batched link kernels for the engine's flush.
# The stock kernels donate their state argument; donation here would
# invalidate buffers a concurrent data-plane tick still references in its
# lock-free snapshot (runtime.py shapes OUTSIDE the engine lock) — the
# donated-buffer crash would kill the dataplane thread. One extra output
# allocation per flush is the price of that safety.
_apply_links_nd = jax.jit(es.apply_links.__wrapped__)
_delete_links_nd = jax.jit(es.delete_links.__wrapped__)
_update_links_nd = jax.jit(es.update_links.__wrapped__,
                           static_argnums=(4,))


_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x00000100000001B3


def link_key_id(pod_key: str, uid: int) -> int:
    """Stable 64-bit key id for one directed link end — FNV-1a over the
    (pod_key, uid) identity. This is the per-row fold_in constant the
    shaping kernels mix into the tick key (ops/netem.row_keys, folded
    as two 32-bit words): it depends only on the link's declared
    identity, never on which SoA row realized it, so a tenant's random
    streams are identical in a cohabited plane and in a solo plane of
    just its topology. 64 bits put the birthday bound for an
    accidental id collision — two links sharing one PRNG stream, with
    perfectly correlated loss/jitter/reorder draws, possibly across
    tenants — near 2^32 links, past the roadmap's scale ambition; a
    31-bit id expects one around 65k links."""
    h = _FNV64_OFFSET
    for b in pod_key.encode():
        h = ((h ^ b) * _FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    for b in int(uid).to_bytes(8, "big", signed=True):
        h = ((h ^ b) * _FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def vni_from_uid(uid: int) -> int:
    return VXLAN_BASE + uid


def uid_from_vni(vni: int) -> int:
    return vni - VXLAN_BASE


def _locked(fn):
    """Serialize a public engine method on the engine lock."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    return wrapper


def _next_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


@dataclass
class EngineStats:
    """Per-op latency records — feeds the parity histogram
    kubedtnd_request_duration_milliseconds (reference
    daemon/metrics/latency_histograms.go:5-30)."""

    adds: int = 0
    dels: int = 0
    updates: int = 0
    device_calls: int = 0
    remote_errors: int = 0  # failed peer-daemon completion RPCs
    op_ms: dict[str, list[float]] = field(default_factory=dict)
    observer: object = None  # optional LatencyHistograms

    def observe(self, method: str, ms: float) -> None:
        self.op_ms.setdefault(method, []).append(ms)
        if self.observer is not None:
            self.observer.observe(method, ms)


class SimEngine:
    """Single source of truth for the device-array realization of links."""

    def __init__(self, store: TopologyStore, capacity: int = 1024,
                 node_ip: str = "10.0.0.1", dialer=None) -> None:
        # One engine serves a 16-thread gRPC pool; all state mutation is
        # serialized here (the reference daemon locks per link uid —
        # common/utils.go:21-26 — but its state lives in the kernel; ours
        # is a single device-array pytree, so a coarse lock is the correct
        # unit).
        self._lock = threading.RLock()
        self.store = store
        self.node_ip = node_ip  # the daemon's HOST_IP equivalent
        self._state = es.init_state(capacity)
        # Pending device ops, coalesced per row with last-writer-wins
        # semantics; flushed as at most THREE batched device calls
        # (delete, apply, update) when device state is actually read.
        # This is the TPU answer to the reference's per-link netlink
        # round-trips (handler.go:316-459): a reconcile drain over
        # thousands of topologies becomes one scatter, not thousands.
        # Invariant: a row appears in at most ONE of the three structures.
        self._pending_apply: dict[int, tuple[int, int, int, np.ndarray]] = {}
        self._pending_update: dict[int, np.ndarray] = {}
        self._pending_delete: set[int] = set()
        # host mirror of "does this row shape traffic at all" — the data
        # plane's TCP/IP-bypass guard consults it per frame without a
        # device readback (the role of the redir_disable attach point on
        # each shaped veth, reference common/qdisc.go:285-287)
        self._shaped_rows: set[int] = set()
        # rows touched by control-plane ops since the data plane's last
        # snapshot — the tick's write-back keeps THEIR current dynamic
        # state instead of its pre-snapshot copy (see runtime.py).
        # `_touched_all` is the whole-capacity form compact() raises:
        # the dispatch path treats it as "every row touched" without
        # anyone materializing an O(capacity) Python set
        self._rows_touched: set[int] = set()
        self._touched_all: bool = False
        self.stats = EngineStats()
        # per-action structured logs, the role of the reference's
        # WithField("daemon"/"action") context loggers
        # (reference common/context.go:11-29)
        self.log = get_logger("engine")
        # host-side registries (the daemon's managers):
        self._pod_ids: dict[str, int] = {}   # endpoint name -> node index
        # persistent inverse of _pod_ids, maintained incrementally so
        # barrier bodies (migration fork) never rebuild an O(pods)
        # inverse map under the lock
        self._pod_names: dict[int, str] = {}
        self._rows: dict[tuple[str, int], int] = {}  # (pod_key, uid) -> row
        # persistent inverse of _rows, maintained incrementally so the
        # data-plane tick never rebuilds an O(rows) map under the lock
        self._row_owner: dict[int, tuple[str, int]] = {}
        self._peer: dict[tuple[str, int], tuple[str, int]] = {}
        # columnar free list: O(1) pop/push, vectorized growth/rebuild
        # (the historical Python list was rebuilt O(capacity) on every
        # grow/compact — the dtnscale layer budgets those walks out)
        self._free: FreeStack = FreeStack.from_range(0, capacity)
        # row -> stable 64-bit key id (link_key_id of the owning
        # (pod_key, uid)): the per-row fold_in constant the shaping
        # kernels key their uniforms by (multi-tenant byte-identity).
        # Columnar (capacity-sized uint64, 0 = unbound) so compact()'s
        # renumbering is one vectorized gather, not a per-row FNV
        # re-derive
        self._row_keyid: np.ndarray = np.zeros((capacity,), np.uint64)
        # optional tenancy.TenantRegistry (set by TenantRegistry.attach):
        # consulted at row allocation so tenant-reserved blocks steer
        # the free list, and at free so block rows return to their pool
        self.tenancy = None
        # >1 when a sharded data plane is attached (set by
        # WireDataPlane.enable_sharding): row allocation colocates link
        # pairs inside one shard block (parallel.partition)
        self.shard_count: int = 1
        self._topology_manager: set[str] = set()  # alive pods (metrics/TopologyManager)
        # placement answers cached per store placement generation
        self._placement_cache: dict[str, tuple[str, str]] = {}
        self._placement_gen: int = -1
        # observers of compact()'s row renumbering (the data plane keeps
        # cumulative per-row counters that must move with the rows)
        self._remap_callbacks: list = []
        # cross-node peer-daemon dialing (reference common/utils.go:53-62,
        # "passthrough:///<nodeIP>:51111"): src_ip -> client with .Update.
        # Injectable for tests / non-default ports; cached per address.
        self._dialer = dialer
        self._peer_clients: dict[str, object] = {}
        self._peer_clients_lock = threading.Lock()

    def _peer_daemon(self, src_ip: str):
        # Raced by the engine's Update path, the per-frame forward path,
        # and (round 5) every per-peer egress sender thread: without the
        # double-checked emplace two racers both dial and one channel
        # leaks open for the process lifetime. The dial itself happens
        # OUTSIDE the lock (it can block on a slow network); the loser's
        # channel is closed if it supports it.
        client = self._peer_clients.get(src_ip)
        if client is not None:
            return client
        if self._dialer is not None:
            client = self._dialer(src_ip)
        else:
            from kubedtn_tpu.wire.client import dial_daemon

            client = dial_daemon(src_ip)
        with self._peer_clients_lock:
            won = self._peer_clients.setdefault(src_ip, client)
        if won is not client:
            close = getattr(client, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
        return won

    # -- registries ----------------------------------------------------

    def _pod_id(self, endpoint: str) -> int:
        """pod_id for callers already holding the engine lock — the
        per-link hot path, where re-entering the RLock per endpoint
        measurably slows a 100k-link drain."""
        pid = self._pod_ids.get(endpoint)
        if pid is None:
            pid = self._pod_ids[endpoint] = len(self._pod_ids)
            self._pod_names[pid] = endpoint
        return pid

    @_locked
    def pod_id(self, endpoint: str) -> int:
        """Stable integer id for any endpoint name (pod key, "localhost",
        "physical/<ip>")."""
        return self._pod_id(endpoint)

    def row_of(self, pod_key: str, uid: int) -> int | None:
        return self._rows.get((pod_key, uid))

    def reverse_row(self, pod_key: str, uid: int) -> int | None:
        """Row of the opposite direction of this p2p link, if realized."""
        peer = self._peer.get((pod_key, uid))
        if peer is None:
            return None
        return self._rows.get(peer)

    @property
    def num_active(self) -> int:
        return len(self._rows)

    # -- capacity ------------------------------------------------------

    def _ensure_capacity(self, extra: int) -> None:
        need = self.num_active + extra
        if self.tenancy is not None:
            # rows reserved inside tenant blocks but not yet realized
            # are unavailable to the global pool: count them or an
            # all-reserved plane pops from an empty free list.
            # reserved_free() reads ONE incrementally-maintained
            # counter — this runs on barrier/drain paths, where a
            # per-call walk of every tenant's block pool was a
            # redundant accounting re-derive (dtnscale scost)
            need += self.tenancy.reserved_free()
        cap = self._state.capacity
        if need <= cap:
            return
        new_cap = _next_pow2(need, floor=cap * 2)
        old_cap = self._state.capacity
        # growth commutes with pending row ops (rows are preserved and all
        # pending targets are < old capacity), so no flush is needed here
        self._state = es.grow_state(self._state, new_cap)
        # vectorized: new rows slide UNDER the existing free entries
        # (same pop order as the historical list-concat rebuild)
        self._free.prepend_range(old_cap, new_cap)
        kid = np.zeros((new_cap,), np.uint64)
        kid[:old_cap] = self._row_keyid
        self._row_keyid = kid
        if self.tenancy is not None:
            self.tenancy.on_capacity(new_cap)

    # -- device op coalescing -----------------------------------------
    #
    # Mutators enqueue per-row ops; the device sees them as three batched
    # scatters at the next read of `engine.state` (the property flushes).
    # Host registries (_rows/_peer/_free) stay eagerly consistent — they
    # are the source of truth for control flow; the device arrays carry
    # the shaping data plane.

    def _enqueue_apply(self, entries) -> None:
        """entries: (row, uid, src, dst, props_row, shaped)."""
        pa = self._pending_apply
        pu_pop = self._pending_update.pop
        pd_discard = self._pending_delete.discard
        s_add, s_discard = self._shaped_rows.add, self._shaped_rows.discard
        touched = self._rows_touched.add
        for row, uid, src, dst, props, shaped in entries:
            pd_discard(row)
            pu_pop(row, None)
            pa[row] = (uid, src, dst, props)
            (s_add if shaped else s_discard)(row)
            touched(row)

    def _enqueue_delete(self, rows_list: list[int]) -> None:
        for row in rows_list:
            self._pending_apply.pop(row, None)
            self._pending_update.pop(row, None)
            self._pending_delete.add(row)
            self._shaped_rows.discard(row)
            self._rows_touched.add(row)

    def _enqueue_update(self, entries) -> None:
        """entries: (row, props_row, shaped). A row with a pending apply
        merges into it (apply fully overwrites the row anyway)."""
        pa, pa_get = self._pending_apply, self._pending_apply.get
        pu = self._pending_update
        s_add, s_discard = self._shaped_rows.add, self._shaped_rows.discard
        touched = self._rows_touched.add
        for row, props, shaped in entries:
            pending = pa_get(row)
            if pending is not None:
                pa[row] = (*pending[:3], props)
            else:
                pu[row] = props
            (s_add if shaped else s_discard)(row)
            touched(row)

    def is_shaped(self, row: int) -> bool:
        """True when the row's current properties shape traffic (any
        non-zero netem/TBF field)."""
        return row in self._shaped_rows

    def _pad_host(self, arrs: list[np.ndarray], n: int):
        """Pad host batches to a power-of-two lane count (host arrays —
        the single place the padding policy lives)."""
        b = _next_pow2(max(n, 1))
        out = [np.pad(a, [(0, b - n)] + [(0, 0)] * (a.ndim - 1))
               for a in arrs]
        valid = np.zeros((b,), dtype=bool)
        valid[:n] = True
        return out, valid

    def _pad(self, arrs: list[np.ndarray], n: int):
        """_pad_host, staged onto device."""
        out, valid = self._pad_host(arrs, n)
        return [jnp.asarray(a) for a in out], jnp.asarray(valid)

    def _flush_device_locked(self) -> None:
        """Apply all pending ops as at most three batched device calls.
        Order delete → apply → update is safe: coalescing keeps the three
        row sets disjoint."""
        if self._pending_delete:
            rows_list = sorted(self._pending_delete)
            self._pending_delete.clear()
            n = len(rows_list)
            (rows,), valid = self._pad([np.array(rows_list, np.int32)], n)
            self._state = _delete_links_nd(self._state, rows, valid)
            self.stats.device_calls += 1
        if self._pending_apply:
            items = sorted(self._pending_apply.items())
            self._pending_apply.clear()
            n = len(items)
            rows = np.fromiter((r for r, _ in items), np.int32, n)
            uids = np.fromiter((e[0] for _, e in items), np.int32, n)
            src = np.fromiter((e[1] for _, e in items), np.int32, n)
            dst = np.fromiter((e[2] for _, e in items), np.int32, n)
            props = np.stack([e[3] for _, e in items]).astype(np.float32)
            (rows, uids, src, dst, props), valid = self._pad(
                [rows, uids, src, dst, props], n)
            self._state = _apply_links_nd(self._state, rows, uids, src,
                                          dst, props, valid)
            self.stats.device_calls += 1
        if self._pending_update:
            items = sorted(self._pending_update.items())
            self._pending_update.clear()
            n = len(items)
            rows = np.fromiter((r for r, _ in items), np.int32, n)
            props = np.stack([p for _, p in items]).astype(np.float32)
            # consecutive-row batches (the allocator hands out consecutive
            # rows, so whole-topology updates usually qualify) take the
            # gather/scatter-free streaming path
            (rows_pad, props_pad), valid_np = self._pad_host(
                [rows, props], n)
            contig = es.contiguous_window(rows_pad, valid_np,
                                          self._state.capacity)
            self._state = _update_links_nd(
                self._state, jnp.asarray(rows_pad), jnp.asarray(props_pad),
                jnp.asarray(valid_np), contig)
            self.stats.device_calls += 1

    def flush(self) -> None:
        """Force pending device ops out (normally lazy via `state`)."""
        with self._lock:
            self._flush_device_locked()

    def warm_kernels(self, lanes: int | None = None) -> None:
        """Pre-compile the three batched link kernels at the given lane
        count (default: full capacity, the widest bucket a flush can pad
        to). All-invalid batches make each call a semantic no-op; a
        steady-state controller never pays XLA compile time on its first
        real reconcile. Scenarios/benches call this outside the timed
        region."""
        with self._lock:
            self._flush_device_locked()
            n = lanes or self._state.capacity
            rows = jnp.zeros((n,), jnp.int32)
            zeros = jnp.zeros((n,), jnp.int32)
            valid = jnp.zeros((n,), bool)
            props = jnp.zeros((n, es.NPROP), jnp.float32)
            self._state = _delete_links_nd(self._state, rows, valid)
            self._state = _apply_links_nd(self._state, rows, zeros, zeros,
                                          zeros, props, valid)
            self._state = _update_links_nd(self._state, rows, props, valid,
                                           False)
            self._state = _update_links_nd(self._state, rows, props, valid,
                                           True)
            jax.block_until_ready(self._state.props)

    @property
    def state(self):
        """Device edge state, with pending ops flushed — every external
        read observes the registries' current truth."""
        with self._lock:
            self._flush_device_locked()
            return self._state

    @state.setter
    def state(self, value) -> None:
        # assignment replaces the arrays but keeps pending ops queued:
        # they encode registry changes not yet realized on device and
        # apply row-wise to whatever arrays are current at the next flush
        with self._lock:
            self._state = value

    # -- pod / link lifecycle (the Local gRPC surface) ----------------

    def get_pod(self, name: str, ns: str = "default") -> Topology:
        """Local.Get equivalent (handler.go:50-60)."""
        return self.store.get(ns or "default", name)

    @_locked
    def set_alive(self, name: str, ns: str, src_ip: str, net_ns: str) -> bool:
        """Local.SetAlive equivalent (handler.go:90-147): write placement
        into status, manage the finalizer, register with the topology
        manager. Alive ⇔ both src_ip and net_ns set."""
        from kubedtn_tpu import GROUP_VERSION

        alive = bool(src_ip) and bool(net_ns)

        def txn_status():
            topo = self.store.get(ns, name)
            topo.status.src_ip = src_ip
            topo.status.net_ns = net_ns
            self.store.update_status(topo)

        retry_on_conflict(txn_status)

        def txn_meta():
            topo = self.store.get(ns, name)
            if alive:
                if GROUP_VERSION not in topo.finalizers:
                    topo.finalizers.append(GROUP_VERSION)
            else:
                # remove only our own finalizer — foreign holders keep the
                # object alive (the reference removes just its entry,
                # handler.go:125-140)
                topo.finalizers = [f for f in topo.finalizers
                                   if f != GROUP_VERSION]
            self.store.update(topo)

        retry_on_conflict(txn_meta)

        key = f"{ns or 'default'}/{name}"
        if alive:
            self._topology_manager.add(key)
        else:
            self._topology_manager.discard(key)
        return True

    def setup_pod(self, name: str, ns: str = "default",
                  net_ns: str = "") -> bool:
        """Local.SetupPod equivalent (handler.go:495-535).

        Deliberately NOT @_locked: every sub-operation takes the engine
        lock itself, and add_links must issue its cross-node completion
        RPCs with the lock released — holding it here would let two nodes'
        SetupPods deadlock dialing each other (the scenario behind the
        reference's unlock-early discipline, handler.go:442-446).

        Returns add_links' verdict: a failed cross-node completion RPC
        surfaces as False so the caller (gRPC SetupPod → CNI, or a
        reconcile pass) can retry instead of recording the link as
        realized (the reference propagates the same failure,
        handler.go:524-532)."""
        t0 = time.perf_counter()
        try:
            topo = self.get_pod(name, ns)
        except NotFoundError:
            # Not a topology pod: CNI delegates to the next plugin.
            return True
        self.set_alive(name, ns, self.node_ip, net_ns or f"/run/netns/{name}")
        topo = self.get_pod(name, ns)
        ok = self.add_links(topo, topo.spec.links)
        ms = (time.perf_counter() - t0) * 1e3
        self.stats.observe("setup", ms)
        self.log.info("setup_pod %s", _fields(
            pod=f"{ns or 'default'}/{name}", links=len(topo.spec.links),
            ok=ok, ms=round(ms, 2)))
        return ok

    def destroy_pod(self, name: str, ns: str = "default") -> bool:
        """Local.DestroyPod equivalent (handler.go:538-590). Not @_locked
        for the same reason as setup_pod — sub-operations self-lock."""
        key = f"{ns or 'default'}/{name}"
        self._topology_manager.discard(key)
        try:
            topo = self.get_pod(name, ns)
        except NotFoundError:
            return False
        # Fetch links BEFORE clearing alive status: dropping the finalizer
        # may complete a pending CR deletion, after which the object is gone
        # (the reference reads localPod first for the same reason —
        # handler.go:559-586).
        links = topo.spec.links
        self.set_alive(name, ns, "", "")
        self.del_links(topo, links)
        return True

    def _refresh_placement_cache(self) -> None:
        """Drop cached placements if any placement may have moved. Checked
        once per engine operation, not per link — the store lock behind
        placement_generation is itself measurable at 100k links."""
        gen = self.store.placement_generation
        if gen != self._placement_gen:
            self._placement_cache.clear()
            self._placement_gen = gen

    def _placement_cached(self, pod_key: str) -> tuple[str, str]:
        """(src_ip, net_ns) via the generation-validated cache; the caller
        must have called _refresh_placement_cache() this operation."""
        hit = self._placement_cache.get(pod_key)
        if hit is None:
            ns, _, name = pod_key.partition("/")
            try:
                hit = self.store.peek_placement(ns, name)
            except NotFoundError:
                hit = ("", "")
            self._placement_cache[pod_key] = hit
        return hit

    def _placement(self, pod_key: str) -> tuple[str, str]:
        """(src_ip, net_ns) for a pod key, cached against the store's
        placement generation — a 100k-link drain asks hundreds of times
        per topology and placement only moves on CNI events, so the cache
        typically survives the whole drain (status copy-backs don't bump
        the generation)."""
        self._refresh_placement_cache()
        return self._placement_cached(pod_key)

    @_locked
    def is_alive(self, pod_key: str) -> bool:
        # _locked: the placement cache is engine state — every mutator of
        # it must hold the engine lock like the other registries do.
        src_ip, net_ns = self._placement(pod_key)
        return bool(src_ip) and bool(net_ns)

    def add_links(self, topo: Topology, links: list[Link]) -> bool:
        """Local.AddLinks equivalent: the reference's per-link dispatch
        (handler.go:316-459) collapsed into one batched device op, plus
        peer-daemon completion RPCs for cross-node links issued AFTER the
        engine lock is released — the reference's explicit unlock-before-
        RPC deadlock avoidance (handler.go:442-446)."""
        remote_calls = self._add_links_locked(topo, links)
        ok = self.complete_remote(remote_calls, pod_key=topo.key)
        if links:
            self.log.debug("add_links %s", _fields(
                action="add", pod=topo.key, links=len(links),
                remote_calls=len(remote_calls), ok=ok))
        return ok

    def complete_remote(self, remote_calls, pod_key: str = "",
                        action: str = "add") -> bool:
        """Issue the cross-node completion RPCs `_add_links_locked`
        returned — ALWAYS with the engine lock released (the reference's
        unlock-before-RPC deadlock avoidance, handler.go:442-446). The
        one completion loop shared by `add_links` and the planned-update
        stager's round apply."""
        ok = True
        for src_ip, remote_pod in remote_calls:
            try:
                resp = self._peer_daemon(src_ip).Update(remote_pod)
                ok = ok and bool(resp.response)
            except Exception as e:
                self.stats.remote_errors += 1
                self.log.warning("remote completion failed %s", _fields(
                    action=action, pod=pod_key, peer_daemon=src_ip,
                    error=type(e).__name__))
                ok = False
        return ok

    @_locked
    def _add_links_locked(self, topo: Topology, links: list[Link]):
        t0 = time.perf_counter()
        local_key = topo.key
        self._ensure_capacity(2 * len(links))
        entries: list[tuple[int, int, int, int, np.ndarray, bool]] = []
        remote_calls: list[tuple[str, object]] = []
        # peer-pod name → "<ns>/<name>" key, built once per peer per call
        # (the f-string per link was itself visible at 100k-link scale)
        peer_keys: dict[str, str] = {}
        ns_prefix = topo.namespace + "/"
        local_pid = self._pod_id(local_key)
        self._refresh_placement_cache()
        # hot-loop locals: at 1M links every attribute/method lookup in
        # this loop is a measurable slice of realize time
        rows_map = self._rows
        alloc = self._alloc
        pod_id = self._pod_id
        peer_map = self._peer
        props_pack = es.props_row_and_shaped
        entries_append = entries.append
        node_ip = self.node_ip
        for link in links:
            uid_ = link.uid
            peer_pod = link.peer_pod
            if (peer_pod != LOCALHOST
                    and not peer_pod.startswith(PHYSICAL_PREFIX)):
                # common case first: a pod-to-pod link
                peer_key = peer_keys.get(peer_pod)
                if peer_key is None:
                    peer_key = peer_keys[peer_pod] = ns_prefix + peer_pod
                peer_src_ip, peer_net_ns = self._placement_cached(peer_key)
                if not (peer_src_ip and peer_net_ns):
                    # Peer not up: the peer plumbs both ends when it
                    # arrives (handler.go:389-395)
                    continue
                if peer_src_ip and node_ip and peer_src_ip != node_ip:
                    # Branch D, cross-node — same semantics as the slow
                    # path below (handler.go:419-453)
                    if (local_key, uid_) not in rows_map:
                        row = alloc(local_key, uid_)
                        props, shaped = props_pack(link.properties)
                        entries_append((row, uid_, local_pid,
                                        pod_id(f"vtep/{peer_src_ip}"),
                                        props, shaped))
                    from kubedtn_tpu.wire import proto as pb

                    remote_calls.append((peer_src_ip, pb.RemotePod(
                        net_ns="", intf_name=link.peer_intf,
                        intf_ip=link.peer_ip, peer_vtep=node_ip,
                        vni=vni_from_uid(uid_),
                        kube_ns=topo.namespace, name=peer_pod,
                        properties=pb.props_to_proto(link.properties),
                    )))
                    continue
                lk = (local_key, uid_)
                pk = (peer_key, uid_)
                if lk in rows_map and pk in rows_map:
                    # both ends realized (common/veth.go:73-76)
                    continue
                # both alive same-node: plumb BOTH directions
                # (common/veth.go:44-62, common/utils.go:39-68).
                # Sharded planes colocate the pair in one shard block
                # (_alloc_link_pair) so a link's two directed rows never
                # straddle the cross-shard mailbox boundary.
                props, shaped = props_pack(link.properties)
                peer_pid = pod_id(peer_key)
                row, prow = self._alloc_link_pair(local_key, peer_key,
                                                  uid_)
                entries_append((row, uid_, local_pid, peer_pid, props,
                                shaped))
                entries_append((prow, uid_, peer_pid, local_pid, props,
                                shaped))
                peer_map[lk] = pk
                peer_map[pk] = lk
                continue
            if link.is_macvlan():
                # macvlan uplink: realized immediately, NO shaping applied
                # (reference handler.go:335-345 never touches qdiscs here).
                row = self._alloc(local_key, link.uid)
                entries.append((
                    row, link.uid, local_pid, self._pod_id(LOCALHOST),
                    np.zeros((es.NPROP,), np.float32), False,
                ))
                continue
            if link.is_physical():
                # Physical-virtual link: daemon handles both perspectives
                # locally (handler.go:348-369); the physical host is always
                # "alive".
                row = self._alloc(local_key, link.uid)
                props, shaped = es.props_row_and_shaped(link.properties)
                entries.append((row, link.uid, local_pid,
                                self._pod_id(link.peer_pod), props, shaped))
                continue

        self._enqueue_apply(entries)
        self.stats.adds += len(entries)
        self.stats.observe("add", (time.perf_counter() - t0) * 1e3)
        return remote_calls

    @_locked
    def del_links(self, topo: Topology, links: list[Link]) -> bool:
        """Local.DelLinks equivalent (handler.go:461-492, 613-632).

        Removing a local veth end destroys the pair, so the peer-direction
        row of each link dies with it.
        """
        t0 = time.perf_counter()
        local_key = topo.key
        rows: list[int] = []
        for link in links:
            row = self._rows.pop((local_key, link.uid), None)
            self._peer.pop((local_key, link.uid), None)
            if row is not None:
                rows.append(row)
                self._free_row(row)
                self._row_owner.pop(row, None)
            if not (link.is_macvlan() or link.is_physical()):
                peer_key = f"{topo.namespace}/{link.peer_pod}"
                prow = self._rows.pop((peer_key, link.uid), None)
                self._peer.pop((peer_key, link.uid), None)
                if prow is not None:
                    rows.append(prow)
                    self._free_row(prow)
                    self._row_owner.pop(prow, None)
        self._enqueue_delete(rows)
        self.stats.dels += len(rows)
        self.stats.observe("del", (time.perf_counter() - t0) * 1e3)
        if rows:
            self.log.debug("del_links %s", _fields(
                action="delete", pod=local_key, rows=len(rows)))
        return True

    @_locked
    def update_links(self, topo: Topology, links: list[Link]) -> bool:
        """Local.UpdateLinks equivalent (handler.go:634-671): rebuild only
        the LOCAL end's shaping, leaving the peer direction untouched."""
        t0 = time.perf_counter()
        local_key = topo.key
        entries: list[tuple[int, np.ndarray, bool]] = []
        for link in links:
            row = self._rows.get((local_key, link.uid))
            if row is None:
                continue
            entries.append((row, *es.props_row_and_shaped(link.properties)))
        self._enqueue_update(entries)
        self.stats.updates += len(entries)
        self.stats.observe("update", (time.perf_counter() - t0) * 1e3)
        if entries:
            self.log.debug("update_links %s", _fields(
                action="update", pod=local_key, rows=len(entries)))
        return True

    @_locked
    def remote_update(self, name: str, ns: str, uid: int, intf_name: str,
                      intf_ip: str, peer_vtep: str, props) -> bool:
        """Remote.Update equivalent (reference handler.go:149-198): a peer
        daemon asks us to realize our end of a cross-node link, identified
        by VNI→uid. The far end is the peer's VTEP, not a local pod."""
        del intf_name, intf_ip  # interface identity lives in the CR spec
        t0 = time.perf_counter()
        pod_key = f"{ns or 'default'}/{name}"
        self._ensure_capacity(1)
        row = self._alloc(pod_key, uid)
        prow, shaped = es.props_row_and_shaped(props)
        entry = (row, uid, self._pod_id(pod_key),
                 self._pod_id(f"vtep/{peer_vtep}"), prow, shaped)
        self._enqueue_apply([entry])
        self.stats.observe("remoteUpdate", (time.perf_counter() - t0) * 1e3)
        return True

    def _bind_row(self, pod_key: str, uid: int, row: int) -> None:
        k = (pod_key, uid)
        self._rows[k] = row
        self._row_owner[row] = k
        self._row_keyid[row] = link_key_id(pod_key, uid)
        if self.tenancy is not None:
            # per-tenant accounting masks are maintained incrementally
            # at bind/unbind (columnar, O(1) per row) instead of being
            # re-derived from the registries per generation
            self.tenancy.note_bind(row, pod_key)

    def _alloc(self, pod_key: str, uid: int) -> int:
        k = (pod_key, uid)
        if k in self._rows:
            return self._rows[k]  # idempotent re-plumb (SetupVeth semantics)
        row = None
        if self.tenancy is not None:
            # tenant-reserved block first: the registry hands out rows
            # from the tenant's contiguous range, keeping its edges in
            # one block of the shared SoA (falls through to the global
            # free list when the tenant has no block / block is full)
            row = self.tenancy.alloc_row(pod_key)
        if row is None:
            row = self._free.pop()
        self._bind_row(pod_key, uid, row)
        return row

    def _free_row(self, row: int) -> None:
        """Return a freed row to its pool: the owning tenant's block
        free list when the row sits in a reserved block, the global
        free list otherwise."""
        self._row_keyid[row] = 0
        if self.tenancy is not None:
            self.tenancy.note_unbind(row)
            if self.tenancy.release_row(row):
                return
        self._free.push(row)

    def _alloc_link_pair(self, k1: str, k2: str, uid: int):
        """Allocate both directed rows of one link, colocated in one
        shard block when the data plane is sharded (shard_count > 1,
        set by WireDataPlane.enable_sharding): frames between colocated
        endpoints never ride the cross-shard mailbox. Idempotent like
        _alloc; unsharded behavior is byte-for-byte the historical
        two-pop path. Tenant-reserved blocks take precedence: both
        directions of an intra-tenant link land inside the tenant's
        contiguous block (which itself avoids straddling a shard
        boundary where it fits — parallel.partition.tenant_block)."""
        a = self._rows.get((k1, uid))
        b = self._rows.get((k2, uid))
        if a is not None and b is not None:
            return a, b
        if (a is None and b is None and self.tenancy is not None):
            pair = self.tenancy.alloc_pair(k1, k2)
            if pair is not None:
                self._bind_row(k1, uid, pair[0])
                self._bind_row(k2, uid, pair[1])
                return pair
        S = getattr(self, "shard_count", 1)
        if (a is None and b is None and S > 1 and len(self._free) >= 2
                and self._state.capacity % S == 0):
            from kubedtn_tpu.parallel.partition import pick_pair_rows

            r1, r2 = pick_pair_rows(self._free, self._state.capacity, S)
            self._bind_row(k1, uid, r1)
            self._bind_row(k2, uid, r2)
            return r1, r2
        return self._alloc(k1, uid), self._alloc(k2, uid)

    @_locked
    def adopt_rows(self, entries, peers=None) -> list[int]:
        """Bind + realize rows arriving from ANOTHER plane (live tenant
        migration, federation.migrate): `entries` are (pod_key, uid,
        src_name, dst_name, props_row, shaped) with node NAMES instead
        of ids — ids are a per-engine numbering, names are the portable
        identity. Idempotent per (pod_key, uid) like `_alloc` (a resumed
        RESTORE re-adopts only what is missing). `peers` lists
        ((pod_key, uid), (peer_key, peer_uid)) pairs to re-establish in
        the peer registry. Props land bit-exact (the captured f32 row,
        never re-parsed); the caller scatters the dynamic shaping
        columns separately. Returns the bound row per entry, in order.
        Allocation honors tenant blocks: with a tenancy registry
        attached, adopted rows carve into the owning tenant's
        contiguous reservation exactly like native allocations."""
        self._ensure_capacity(len(entries))
        rows: list[int] = []
        apply_entries = []
        for pod_key, uid, src_name, dst_name, props, shaped in entries:
            k = (pod_key, int(uid))
            row = self._rows.get(k)
            if row is None:
                row = self._alloc(pod_key, int(uid))
                apply_entries.append((
                    row, int(uid), self._pod_id(src_name),
                    self._pod_id(dst_name),
                    np.asarray(props, np.float32), bool(shaped)))
            rows.append(row)
        for k, pk in (peers or ()):
            k = (k[0], int(k[1]))
            pk = (pk[0], int(pk[1]))
            self._peer[k] = pk
            self._peer[pk] = k
        self._enqueue_apply(apply_entries)
        self.stats.adds += len(apply_entries)
        if apply_entries:
            self.log.info("adopt_rows %s", _fields(
                action="adopt", rows=len(apply_entries),
                total=len(entries)))
        return rows

    @_locked
    def abandon_rows(self, keys) -> int:
        """Release rows by (pod_key, uid) identity without a Topology
        object — the migration RELEASE/rollback path (the rows' links
        live on in another plane's SoA; this end just frees the
        realization). Freed block rows return to their tenant pool via
        `_free_row` as usual. Idempotent; returns rows freed."""
        rows: list[int] = []
        for k in keys:
            k = (k[0], int(k[1]))
            row = self._rows.pop(k, None)
            self._peer.pop(k, None)
            if row is not None:
                rows.append(row)
                self._free_row(row)
                self._row_owner.pop(row, None)
        self._enqueue_delete(rows)
        self.stats.dels += len(rows)
        if rows:
            self.log.info("abandon_rows %s", _fields(
                action="abandon", rows=len(rows)))
        return len(rows)

    def on_rows_remapped(self, cb) -> None:
        """Register cb(old_rows_np, n_active): called after compact()
        renumbers rows (new row i held old row old_rows_np[i]). Held by
        WEAK reference: a replaced data plane must not be kept alive by
        the engine, nor have its stale counters permuted forever."""
        import weakref

        ref = (weakref.WeakMethod(cb) if hasattr(cb, "__self__")
               else weakref.ref(cb))
        with self._lock:
            self._remap_callbacks.append(ref)

    def compact(self) -> dict:
        """Repack active rows to [0, n): defragmentation after churn.

        The allocator recycles freed rows LIFO, so heavy delete/add churn
        scatters a topology's rows across capacity and whole-drain update
        batches fall off the contiguous streaming fast path (they remain
        correct via the scatter path, just slower). compact() restores
        the dense layout with ONE device gather (SURVEY §7 hard part (a):
        capacity padding + free-list compaction). Registered observers
        (the data plane's per-row counters) are remapped OUTSIDE the
        engine lock — a tick racing the callback may smear at most one
        tick of counter increments across the renumbering.

        The whole pass (device gather + registry rebuild + observer
        remap) reports into the owning plane's pause ledger (cause
        "compact" — the engine carries `pauses` as a back-reference the
        plane sets, None for engine-only embedders).
        """
        t_pause0 = time.perf_counter()
        with self._lock:
            self._flush_device_locked()
            items = sorted(self._rows.items())
            n = len(items)
            cap = self._state.capacity
            old_rows = np.fromiter((r for _, r in items), np.int64, n)
            perm = np.zeros((cap,), np.int32)
            perm[:n] = old_rows
            self._state = es.compact_state(
                self._state, jnp.asarray(perm), jnp.int32(n))
            # ONE pass over the sorted registry rebuilds both row maps
            # (new row i == position i in sorted-key order); every
            # other row-keyed column remaps as a vectorized gather
            # through `new_of_old` — the historical per-row dict
            # rebuilds and FNV re-derives were each their own
            # O(active-rows) Python walk under the engine lock
            rows_new: dict[tuple[str, int], int] = {}
            owner_new: dict[int, tuple[str, int]] = {}
            for i, (k, _r) in enumerate(items):
                rows_new[k] = i
                owner_new[i] = k
            self._rows = rows_new
            self._row_owner = owner_new
            new_of_old = np.full((cap,), -1, np.int64)
            new_of_old[old_rows] = np.arange(n)
            if self._shaped_rows:
                shaped_old = np.fromiter(self._shaped_rows, np.int64,
                                         len(self._shaped_rows))
                self._shaped_rows = set(
                    new_of_old[shaped_old].tolist())
                self._shaped_rows.discard(-1)
            # key ids are identity-derived and identities are
            # unchanged: the remap is one gather of the column
            kid = np.zeros((cap,), np.uint64)
            kid[:n] = self._row_keyid[old_rows]
            self._row_keyid = kid
            self._free = FreeStack.from_range(n, cap)
            if self.tenancy is not None:
                # contiguous tenant blocks do not survive a global
                # repack: the registry re-carves each tenant's
                # reservation at its full requested size from the
                # rebuilt free list (healing on the next compact or
                # create when it doesn't fit); per-tenant ACCOUNTING
                # masks permute with the same old_rows gather the SoA
                # columns used, staying exact through the renumbering
                self.tenancy.on_compact(old_rows, n, cap)
            # the data plane's next write-back must not resurrect
            # pre-compact dynamic state for any row — raised as a flag,
            # never materialized as an O(capacity) Python set
            self._rows_touched.clear()
            self._touched_all = True
            moved = int((old_rows != np.arange(n)).sum())
            live = []
            for ref in self._remap_callbacks:
                cb = ref()
                if cb is not None:
                    live.append(cb)
            self._remap_callbacks = [r for r in self._remap_callbacks
                                     if r() is not None]
        for cb in live:
            cb(old_rows, n)
        pauses = getattr(self, "pauses", None)
        if pauses is not None:
            pauses.record("compact", time.perf_counter() - t_pause0,
                          rows=n, moved=moved)
        self.log.info("compact %s", _fields(action="compact", active=n,
                                            moved=moved))
        return {"active": n, "moved": moved}

    # -- queries -------------------------------------------------------

    @_locked
    def metrics_snapshot(self, limit: int | None = None):
        """(realized_snapshot(limit), total_active, active_rows_np) in ONE
        locked read — the scrape's truncation count and node totals must
        be consistent with the snapshot they accompany."""
        snap = self.realized_snapshot(limit)
        rows = np.fromiter(self._rows.values(), np.int64, len(self._rows))
        return snap, len(self._rows), rows

    @_locked
    def realized_snapshot(self, limit: int | None = None
                          ) -> list[tuple[str, int, int, int | None]]:
        """(pod_key, uid, row, reverse_row) for realized link ends in
        sorted-key order, taken under the engine lock — the safe read for
        concurrent metrics scrapes (a gRPC worker may be mutating the
        registries). With `limit`, only the first `limit` ends are built
        via a heap (O(n log limit)) so a capped 100k-row scrape doesn't
        hold the lock for a full sort."""
        if limit is None or limit >= len(self._rows):
            items = sorted(self._rows.items())
        else:
            import heapq

            items = heapq.nsmallest(limit, self._rows.items())
        out = []
        for (pod_key, uid), row in items:
            peer = self._peer.get((pod_key, uid))
            rev = self._rows.get(peer) if peer is not None else None
            out.append((pod_key, uid, row, rev))
        return out

    def link_row(self, pod_key: str, uid: int) -> dict | None:
        """Host-side readout of one directed link's realized state."""
        row = self._rows.get((pod_key, uid))
        if row is None:
            return None
        state = self.state  # one flush+snapshot
        props = np.asarray(state.props[row])
        return {
            "row": row,
            "uid": int(state.uid[row]),
            "active": bool(state.active[row]),
            **{name: float(props[i]) for i, name in enumerate(es.PROP_NAMES)},
        }

    @_locked
    def ping(self, a: str, b: str, uid: int, size_bytes: float = 84.0,
             ns: str = "default", seed: int = 0) -> dict:
        """Ping-equivalent probe: push one ICMP-sized packet each way
        through the shaping kernels and report the RTT — the analogue of
        the reference's e2e smoke test (reference hack/test-3node.sh:1-10).
        """
        from kubedtn_tpu.ops import netem

        akey, bkey = f"{ns}/{a}", f"{ns}/{b}"
        ra = self._rows.get((akey, uid))
        rb = self._rows.get((bkey, uid))
        if ra is None or rb is None:
            return {"reachable": False, "rtt_us": float("inf")}
        E = self.state.capacity
        sizes = jnp.full((E,), size_bytes, jnp.float32)
        have = jnp.zeros((E,), bool).at[jnp.array([ra, rb])].set(True)
        t0 = jnp.zeros((E,), jnp.float32)
        # non-donating: a concurrent data-plane tick may hold these
        # buffers in its lock-free snapshot
        # fold the link uid into the probe key: two pings with the same
        # seed on different links must not draw identical loss/jitter
        # bits (dtnlint key-discipline)
        self.state, res = netem.shape_step_nodonate(
            self.state, sizes, have, t0,
            jax.random.fold_in(jax.random.key(seed), uid))
        d_ab = float(res.depart_us[ra])
        d_ba = float(res.depart_us[rb])
        delivered = bool(res.delivered[ra]) and bool(res.delivered[rb])
        return {
            "reachable": delivered,
            "rtt_us": d_ab + d_ba if delivered else float("inf"),
            "fwd_us": d_ab,
            "rev_us": d_ba,
        }

    def trace(self, a: str, b: str, ns: str = "default",
              max_hops: int = 16) -> dict:
        """Traceroute-equivalent: walk the device-computed shortest path
        from pod a to pod b hop by hop, reporting each traversed link's
        uid and configured latency plus the path total. Multi-hop — where
        ping probes ONE direct link, trace routes across the whole fabric
        (the role `traceroute` plays next to `ping` in the reference's
        manual test workflow)."""
        from kubedtn_tpu.ops import routing as R

        akey, bkey = f"{ns}/{a}", f"{ns}/{b}"
        with self._lock:
            # ids and state under ONE lock hold: a pod registered between
            # the two reads would put node ids >= n_nodes into the edge
            # arrays, which the routing gathers silently clamp
            ids = dict(self._pod_ids)
            state = self.state  # flushes pending control-plane ops
        if akey not in ids or bkey not in ids:
            return {"reachable": False, "hops": [],
                    "error": "unknown pod(s)"}
        n_nodes = max(ids.values()) + 1
        dist, nh = R.recompute_routes(state, n_nodes, max_hops=max_hops)
        nh_np = np.asarray(nh)
        dstv = np.asarray(state.dst)
        uid_np = np.asarray(state.uid)
        lat = np.asarray(state.props[:, es.P_LATENCY_US])
        names = {v: k for k, v in ids.items()}
        cur, goal = ids[akey], ids[bkey]
        reachable = bool(np.isfinite(np.asarray(dist[cur, goal])))
        hops = []
        total = 0.0
        if reachable:
            # a reachable shortest path has < n_nodes edges; the bound
            # guards the walk against float-tie pathologies in nh
            for _ in range(n_nodes):
                if cur == goal:
                    break
                edge = int(nh_np[cur, goal])
                if edge < 0:
                    return {"reachable": False, "hops": hops,
                            "error": "next-hop walk diverged "
                                     "(finite dist but no next hop)"}
                nxt = int(dstv[edge])
                total += float(lat[edge])
                hops.append({
                    "from": names.get(cur, str(cur)),
                    "to": names.get(nxt, str(nxt)),
                    "uid": int(uid_np[edge]),
                    "latency_us": float(lat[edge]),
                })
                cur = nxt
            if cur != goal:
                # float-tie pathologies in nh (e.g. zero-latency
                # equal-cost cycles under the tie epsilon) can make the
                # walk loop without reaching goal; report, don't crash
                return {"reachable": False, "hops": hops,
                        "error": "next-hop walk diverged from dist"}
        return {"reachable": reachable, "hops": hops,
                "total_latency_us": total}
