"""SimEngine — the node-daemon equivalent, in front of device arrays.

The reference's per-node daemon (reference daemon/kubedtn/handler.go) turns
pod lifecycle and link batches into kernel plumbing: veth pairs, VXLAN
tunnels, qdisc chains. This engine turns the same calls into row operations
on the batched EdgeState device arrays (kubedtn_tpu.ops.edge_state) — one
row per directed link endpoint.

Reference behaviors reproduced exactly:
- SetupPod (handler.go:495-535): unknown pod → "not in topology" and
  delegate; otherwise mark alive (status.src_ip/net_ns + finalizer) and add
  every spec link.
- addLink dispatch (handler.go:316-459): macvlan for peer "localhost" (the
  reference applies NO qdiscs on macvlan links — handler.go:335-345);
  "physical/<ip>" links realized immediately on behalf of the physical
  host; pod-to-pod links gated on peer aliveness — "whoever comes up last
  does the plumbing" (handler.go:386-395), and the plumbing pod's declared
  properties are applied to BOTH ends (common/veth.go:44-62 applies
  link.Properties to self and peer; common/utils.go:39-68 ships the same
  properties to the remote end).
- UpdateLinks (handler.go:634-671): rebuilds only the LOCAL end's qdiscs.
- DestroyPod (handler.go:538-590): clear alive status + finalizers, then
  delete the pod's link rows; deleting a local veth end kills the pair, so
  both directions of its links are deactivated.

Batched device ops are padded to power-of-two bucket sizes so the jitted
scatters compile O(log n) distinct shapes, never per batch.
"""

from __future__ import annotations

import functools
import threading
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from kubedtn_tpu.api.types import LOCALHOST, Link, Topology
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.topology.store import (
    NotFoundError,
    TopologyStore,
    retry_on_conflict,
)

# VXLAN VNI base kept for wire-level parity (reference common/constants.go:8,
# common/utils.go:29-36: vni = 5000 + uid).
VXLAN_BASE = 5000


def vni_from_uid(uid: int) -> int:
    return VXLAN_BASE + uid


def uid_from_vni(vni: int) -> int:
    return vni - VXLAN_BASE


def _locked(fn):
    """Serialize a public engine method on the engine lock."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    return wrapper


def _next_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


@dataclass
class EngineStats:
    """Per-op latency records — feeds the parity histogram
    kubedtnd_request_duration_milliseconds (reference
    daemon/metrics/latency_histograms.go:5-30)."""

    adds: int = 0
    dels: int = 0
    updates: int = 0
    device_calls: int = 0
    remote_errors: int = 0  # failed peer-daemon completion RPCs
    op_ms: dict[str, list[float]] = field(default_factory=dict)
    observer: object = None  # optional LatencyHistograms

    def observe(self, method: str, ms: float) -> None:
        self.op_ms.setdefault(method, []).append(ms)
        if self.observer is not None:
            self.observer.observe(method, ms)


class SimEngine:
    """Single source of truth for the device-array realization of links."""

    def __init__(self, store: TopologyStore, capacity: int = 1024,
                 node_ip: str = "10.0.0.1", dialer=None) -> None:
        # One engine serves a 16-thread gRPC pool; all state mutation is
        # serialized here (the reference daemon locks per link uid —
        # common/utils.go:21-26 — but its state lives in the kernel; ours
        # is a single device-array pytree, so a coarse lock is the correct
        # unit).
        self._lock = threading.RLock()
        self.store = store
        self.node_ip = node_ip  # the daemon's HOST_IP equivalent
        self.state = es.init_state(capacity)
        self.stats = EngineStats()
        # host-side registries (the daemon's managers):
        self._pod_ids: dict[str, int] = {}   # endpoint name -> node index
        self._rows: dict[tuple[str, int], int] = {}  # (pod_key, uid) -> row
        self._peer: dict[tuple[str, int], tuple[str, int]] = {}
        self._free: list[int] = list(range(capacity - 1, -1, -1))
        self._topology_manager: set[str] = set()  # alive pods (metrics/TopologyManager)
        # cross-node peer-daemon dialing (reference common/utils.go:53-62,
        # "passthrough:///<nodeIP>:51111"): src_ip -> client with .Update.
        # Injectable for tests / non-default ports; cached per address.
        self._dialer = dialer
        self._peer_clients: dict[str, object] = {}

    def _peer_daemon(self, src_ip: str):
        client = self._peer_clients.get(src_ip)
        if client is None:
            if self._dialer is not None:
                client = self._dialer(src_ip)
            else:
                from kubedtn_tpu.wire.client import dial_daemon

                client = dial_daemon(src_ip)
            self._peer_clients[src_ip] = client
        return client

    # -- registries ----------------------------------------------------

    @_locked
    def pod_id(self, endpoint: str) -> int:
        """Stable integer id for any endpoint name (pod key, "localhost",
        "physical/<ip>")."""
        if endpoint not in self._pod_ids:
            self._pod_ids[endpoint] = len(self._pod_ids)
        return self._pod_ids[endpoint]

    def row_of(self, pod_key: str, uid: int) -> int | None:
        return self._rows.get((pod_key, uid))

    def reverse_row(self, pod_key: str, uid: int) -> int | None:
        """Row of the opposite direction of this p2p link, if realized."""
        peer = self._peer.get((pod_key, uid))
        if peer is None:
            return None
        return self._rows.get(peer)

    @property
    def num_active(self) -> int:
        return len(self._rows)

    # -- capacity ------------------------------------------------------

    def _ensure_capacity(self, extra: int) -> None:
        need = self.num_active + extra
        cap = self.state.capacity
        if need <= cap:
            return
        new_cap = _next_pow2(need, floor=cap * 2)
        old_cap = self.state.capacity
        self.state = es.grow_state(self.state, new_cap)
        self._free = list(range(new_cap - 1, old_cap - 1, -1)) + self._free

    # -- device op helpers --------------------------------------------

    def _pad(self, arrs: list[np.ndarray], n: int):
        """Pad host batches to a power-of-two lane count."""
        b = _next_pow2(max(n, 1))
        out = []
        for a in arrs:
            pad_width = [(0, b - n)] + [(0, 0)] * (a.ndim - 1)
            out.append(jnp.asarray(np.pad(a, pad_width)))
        valid = np.zeros((b,), dtype=bool)
        valid[:n] = True
        return out, jnp.asarray(valid)

    def _apply_rows(self, entries: list[tuple[int, int, int, int, np.ndarray]]):
        """entries: (row, uid, src, dst, props_row)."""
        n = len(entries)
        if n == 0:
            return
        rows = np.array([e[0] for e in entries], np.int32)
        uids = np.array([e[1] for e in entries], np.int32)
        src = np.array([e[2] for e in entries], np.int32)
        dst = np.array([e[3] for e in entries], np.int32)
        props = np.stack([e[4] for e in entries]).astype(np.float32)
        (rows, uids, src, dst, props), valid = self._pad(
            [rows, uids, src, dst, props], n)
        self.state = es.apply_links(self.state, rows, uids, src, dst, props,
                                    valid)
        self.stats.device_calls += 1

    def _delete_rows(self, rows_list: list[int]) -> None:
        n = len(rows_list)
        if n == 0:
            return
        rows = np.array(rows_list, np.int32)
        (rows,), valid = self._pad([rows], n)
        self.state = es.delete_links(self.state, rows, valid)
        self.stats.device_calls += 1

    def _update_rows(self, entries: list[tuple[int, np.ndarray]]) -> None:
        n = len(entries)
        if n == 0:
            return
        rows = np.array([e[0] for e in entries], np.int32)
        props = np.stack([e[1] for e in entries]).astype(np.float32)
        (rows, props), valid = self._pad([rows, props], n)
        self.state = es.update_links(self.state, rows, props, valid)
        self.stats.device_calls += 1

    # -- pod / link lifecycle (the Local gRPC surface) ----------------

    def get_pod(self, name: str, ns: str = "default") -> Topology:
        """Local.Get equivalent (handler.go:50-60)."""
        return self.store.get(ns or "default", name)

    @_locked
    def set_alive(self, name: str, ns: str, src_ip: str, net_ns: str) -> bool:
        """Local.SetAlive equivalent (handler.go:90-147): write placement
        into status, manage the finalizer, register with the topology
        manager. Alive ⇔ both src_ip and net_ns set."""
        from kubedtn_tpu import GROUP_VERSION

        alive = bool(src_ip) and bool(net_ns)

        def txn_status():
            topo = self.store.get(ns, name)
            topo.status.src_ip = src_ip
            topo.status.net_ns = net_ns
            self.store.update_status(topo)

        retry_on_conflict(txn_status)

        def txn_meta():
            topo = self.store.get(ns, name)
            if alive:
                if GROUP_VERSION not in topo.finalizers:
                    topo.finalizers.append(GROUP_VERSION)
            else:
                # remove only our own finalizer — foreign holders keep the
                # object alive (the reference removes just its entry,
                # handler.go:125-140)
                topo.finalizers = [f for f in topo.finalizers
                                   if f != GROUP_VERSION]
            self.store.update(topo)

        retry_on_conflict(txn_meta)

        key = f"{ns or 'default'}/{name}"
        if alive:
            self._topology_manager.add(key)
        else:
            self._topology_manager.discard(key)
        return True

    def setup_pod(self, name: str, ns: str = "default",
                  net_ns: str = "") -> bool:
        """Local.SetupPod equivalent (handler.go:495-535).

        Deliberately NOT @_locked: every sub-operation takes the engine
        lock itself, and add_links must issue its cross-node completion
        RPCs with the lock released — holding it here would let two nodes'
        SetupPods deadlock dialing each other (the scenario behind the
        reference's unlock-early discipline, handler.go:442-446).

        Returns add_links' verdict: a failed cross-node completion RPC
        surfaces as False so the caller (gRPC SetupPod → CNI, or a
        reconcile pass) can retry instead of recording the link as
        realized (the reference propagates the same failure,
        handler.go:524-532)."""
        t0 = time.perf_counter()
        try:
            topo = self.get_pod(name, ns)
        except NotFoundError:
            # Not a topology pod: CNI delegates to the next plugin.
            return True
        self.set_alive(name, ns, self.node_ip, net_ns or f"/run/netns/{name}")
        topo = self.get_pod(name, ns)
        ok = self.add_links(topo, topo.spec.links)
        self.stats.observe("setup", (time.perf_counter() - t0) * 1e3)
        return ok

    def destroy_pod(self, name: str, ns: str = "default") -> bool:
        """Local.DestroyPod equivalent (handler.go:538-590). Not @_locked
        for the same reason as setup_pod — sub-operations self-lock."""
        key = f"{ns or 'default'}/{name}"
        self._topology_manager.discard(key)
        try:
            topo = self.get_pod(name, ns)
        except NotFoundError:
            return False
        # Fetch links BEFORE clearing alive status: dropping the finalizer
        # may complete a pending CR deletion, after which the object is gone
        # (the reference reads localPod first for the same reason —
        # handler.go:559-586).
        links = topo.spec.links
        self.set_alive(name, ns, "", "")
        self.del_links(topo, links)
        return True

    def is_alive(self, pod_key: str) -> bool:
        ns, _, name = pod_key.partition("/")
        try:
            topo = self.store.get(ns, name)
        except NotFoundError:
            return False
        return topo.is_alive()

    def add_links(self, topo: Topology, links: list[Link]) -> bool:
        """Local.AddLinks equivalent: the reference's per-link dispatch
        (handler.go:316-459) collapsed into one batched device op, plus
        peer-daemon completion RPCs for cross-node links issued AFTER the
        engine lock is released — the reference's explicit unlock-before-
        RPC deadlock avoidance (handler.go:442-446)."""
        remote_calls = self._add_links_locked(topo, links)
        ok = True
        for src_ip, remote_pod in remote_calls:
            try:
                resp = self._peer_daemon(src_ip).Update(remote_pod)
                ok = ok and bool(resp.response)
            except Exception:
                self.stats.remote_errors += 1
                ok = False
        return ok

    @_locked
    def _add_links_locked(self, topo: Topology, links: list[Link]):
        t0 = time.perf_counter()
        local_key = topo.key
        self._ensure_capacity(2 * len(links))
        entries: list[tuple[int, int, int, int, np.ndarray]] = []
        remote_calls: list[tuple[str, object]] = []
        alive_cache: dict[str, bool] = {}
        for link in links:
            if link.is_macvlan():
                # macvlan uplink: realized immediately, NO shaping applied
                # (reference handler.go:335-345 never touches qdiscs here).
                row = self._alloc(local_key, link.uid)
                entries.append((
                    row, link.uid, self.pod_id(local_key),
                    self.pod_id(LOCALHOST),
                    np.zeros((es.NPROP,), np.float32),
                ))
                continue
            if link.is_physical():
                # Physical-virtual link: daemon handles both perspectives
                # locally (handler.go:348-369); the physical host is always
                # "alive".
                row = self._alloc(local_key, link.uid)
                props = es.props_row(link.properties.to_numeric())
                entries.append((row, link.uid, self.pod_id(local_key),
                                self.pod_id(link.peer_pod), np.asarray(props)))
                continue

            peer_key = f"{topo.namespace}/{link.peer_pod}"
            if peer_key not in alive_cache:
                alive_cache[peer_key] = self.is_alive(peer_key)
            if not alive_cache[peer_key]:
                # Peer not up: do nothing — the peer will plumb both ends
                # when it arrives (handler.go:389-395).
                continue

            peer_src_ip = self._pod_src_ip(peer_key)
            if peer_src_ip and self.node_ip and peer_src_ip != self.node_ip:
                # Branch D, cross-node (handler.go:419-453): realize only
                # the LOCAL egress end (far end = the peer node's VTEP,
                # VNI = 5000+uid), and queue a Remote.Update so the peer
                # daemon realizes ITS end — issued after unlock. The RPC is
                # queued even when the local row already exists: the peer
                # side is idempotent (CreateOrUpdate, vxlan.go:54-151), and
                # re-sending is what heals a link left half-realized by an
                # earlier failed completion RPC on retry.
                if (local_key, link.uid) not in self._rows:
                    row = self._alloc(local_key, link.uid)
                    props = np.asarray(
                        es.props_row(link.properties.to_numeric()))
                    entries.append((row, link.uid, self.pod_id(local_key),
                                    self.pod_id(f"vtep/{peer_src_ip}"),
                                    props))
                from kubedtn_tpu.wire import proto as pb

                remote_calls.append((peer_src_ip, pb.RemotePod(
                    net_ns="", intf_name=link.peer_intf,
                    intf_ip=link.peer_ip, peer_vtep=self.node_ip,
                    vni=vni_from_uid(link.uid),
                    kube_ns=topo.namespace, name=link.peer_pod,
                    properties=pb.props_to_proto(link.properties),
                )))
                continue

            if ((local_key, link.uid) in self._rows
                    and (peer_key, link.uid) in self._rows):
                # Both ends already realized: do nothing, like SetupVeth's
                # "both interfaces already exist" path (common/veth.go:73-76).
                continue

            # Both alive same-node: this pod plumbs BOTH directions with ITS
            # declared properties (common/veth.go:44-62, common/utils.go:39-68).
            props = np.asarray(es.props_row(link.properties.to_numeric()))
            row = self._alloc(local_key, link.uid)
            entries.append((row, link.uid, self.pod_id(local_key),
                            self.pod_id(peer_key), props))
            prow = self._alloc(peer_key, link.uid)
            entries.append((prow, link.uid, self.pod_id(peer_key),
                            self.pod_id(local_key), props))
            self._peer[(local_key, link.uid)] = (peer_key, link.uid)
            self._peer[(peer_key, link.uid)] = (local_key, link.uid)
        self._apply_rows(entries)
        self.stats.adds += len(entries)
        self.stats.observe("add", (time.perf_counter() - t0) * 1e3)
        return remote_calls

    def _pod_src_ip(self, pod_key: str) -> str:
        ns, _, name = pod_key.partition("/")
        try:
            return self.store.get(ns, name).status.src_ip
        except NotFoundError:
            return ""

    @_locked
    def del_links(self, topo: Topology, links: list[Link]) -> bool:
        """Local.DelLinks equivalent (handler.go:461-492, 613-632).

        Removing a local veth end destroys the pair, so the peer-direction
        row of each link dies with it.
        """
        t0 = time.perf_counter()
        local_key = topo.key
        rows: list[int] = []
        for link in links:
            row = self._rows.pop((local_key, link.uid), None)
            self._peer.pop((local_key, link.uid), None)
            if row is not None:
                rows.append(row)
                self._free.append(row)
            if not (link.is_macvlan() or link.is_physical()):
                peer_key = f"{topo.namespace}/{link.peer_pod}"
                prow = self._rows.pop((peer_key, link.uid), None)
                self._peer.pop((peer_key, link.uid), None)
                if prow is not None:
                    rows.append(prow)
                    self._free.append(prow)
        self._delete_rows(rows)
        self.stats.dels += len(rows)
        self.stats.observe("del", (time.perf_counter() - t0) * 1e3)
        return True

    @_locked
    def update_links(self, topo: Topology, links: list[Link]) -> bool:
        """Local.UpdateLinks equivalent (handler.go:634-671): rebuild only
        the LOCAL end's shaping, leaving the peer direction untouched."""
        t0 = time.perf_counter()
        local_key = topo.key
        entries: list[tuple[int, np.ndarray]] = []
        for link in links:
            row = self._rows.get((local_key, link.uid))
            if row is None:
                continue
            entries.append(
                (row, np.asarray(es.props_row(link.properties.to_numeric()))))
        self._update_rows(entries)
        self.stats.updates += len(entries)
        self.stats.observe("update", (time.perf_counter() - t0) * 1e3)
        return True

    @_locked
    def remote_update(self, name: str, ns: str, uid: int, intf_name: str,
                      intf_ip: str, peer_vtep: str, props) -> bool:
        """Remote.Update equivalent (reference handler.go:149-198): a peer
        daemon asks us to realize our end of a cross-node link, identified
        by VNI→uid. The far end is the peer's VTEP, not a local pod."""
        del intf_name, intf_ip  # interface identity lives in the CR spec
        t0 = time.perf_counter()
        pod_key = f"{ns or 'default'}/{name}"
        self._ensure_capacity(1)
        row = self._alloc(pod_key, uid)
        entry = (row, uid, self.pod_id(pod_key),
                 self.pod_id(f"vtep/{peer_vtep}"),
                 np.asarray(es.props_row(props.to_numeric())))
        self._apply_rows([entry])
        self.stats.observe("remoteUpdate", (time.perf_counter() - t0) * 1e3)
        return True

    def _alloc(self, pod_key: str, uid: int) -> int:
        k = (pod_key, uid)
        if k in self._rows:
            return self._rows[k]  # idempotent re-plumb (SetupVeth semantics)
        row = self._free.pop()
        self._rows[k] = row
        return row

    # -- queries -------------------------------------------------------

    @_locked
    def realized_snapshot(self) -> list[tuple[str, int, int, int | None]]:
        """(pod_key, uid, row, reverse_row) for every realized link end,
        taken under the engine lock — the safe read for concurrent metrics
        scrapes (a gRPC worker may be mutating the registries)."""
        out = []
        for (pod_key, uid), row in sorted(self._rows.items()):
            peer = self._peer.get((pod_key, uid))
            rev = self._rows.get(peer) if peer is not None else None
            out.append((pod_key, uid, row, rev))
        return out

    def link_row(self, pod_key: str, uid: int) -> dict | None:
        """Host-side readout of one directed link's realized state."""
        row = self._rows.get((pod_key, uid))
        if row is None:
            return None
        props = np.asarray(self.state.props[row])
        return {
            "row": row,
            "uid": int(self.state.uid[row]),
            "active": bool(self.state.active[row]),
            **{name: float(props[i]) for i, name in enumerate(es.PROP_NAMES)},
        }

    @_locked
    def ping(self, a: str, b: str, uid: int, size_bytes: float = 84.0,
             ns: str = "default", seed: int = 0) -> dict:
        """Ping-equivalent probe: push one ICMP-sized packet each way
        through the shaping kernels and report the RTT — the analogue of
        the reference's e2e smoke test (reference hack/test-3node.sh:1-10).
        """
        from kubedtn_tpu.ops import netem

        akey, bkey = f"{ns}/{a}", f"{ns}/{b}"
        ra = self._rows.get((akey, uid))
        rb = self._rows.get((bkey, uid))
        if ra is None or rb is None:
            return {"reachable": False, "rtt_us": float("inf")}
        E = self.state.capacity
        sizes = jnp.full((E,), size_bytes, jnp.float32)
        have = jnp.zeros((E,), bool).at[jnp.array([ra, rb])].set(True)
        t0 = jnp.zeros((E,), jnp.float32)
        self.state, res = netem.shape_step_auto(
            self.state, sizes, have, t0, jax.random.key(seed))
        d_ab = float(res.depart_us[ra])
        d_ba = float(res.depart_us[rb])
        delivered = bool(res.delivered[ra]) and bool(res.delivered[rb])
        return {
            "reachable": delivered,
            "rtt_us": d_ab + d_ba if delivered else float("inf"),
            "fwd_us": d_ab,
            "rev_us": d_ba,
        }
