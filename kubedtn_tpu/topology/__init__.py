from kubedtn_tpu.topology.engine import SimEngine, uid_from_vni, vni_from_uid
from kubedtn_tpu.topology.reconciler import Reconciler, ReconcileResult, calc_diff
from kubedtn_tpu.topology.store import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    TopologyStore,
    WatchEvent,
    retry_on_conflict,
)

__all__ = [
    "SimEngine",
    "Reconciler",
    "ReconcileResult",
    "calc_diff",
    "TopologyStore",
    "WatchEvent",
    "ConflictError",
    "NotFoundError",
    "AlreadyExistsError",
    "retry_on_conflict",
    "vni_from_uid",
    "uid_from_vni",
]
