"""FreeStack — the engine's columnar row free list.

The historical allocator was a Python ``list[int]`` stack: initialized
``list(range(capacity-1, -1, -1))`` so consecutive pops hand out
consecutive rows, appended on free, REBUILT with ``list(range(...))``
on every ``_ensure_capacity`` growth and ``compact()``, and filtered
element-by-element (``[r for r in free if r not in taken]``) by the
tenant-block carve and the rollback reclaim. Every one of those
rebuilds/filters is an O(capacity) *Python-level* walk under the
engine lock — invisible at 1k rows, a multi-hundred-millisecond
runner pause at the roadmap's million-edge scale, and exactly the
class of host cost the dtnscale layer (`analysis/scale`) budgets.

This class keeps the SAME stack semantics — byte-identical pop order,
pinned against the historical list model by
``tests/test_columnar_allocator.py`` — on one int32 numpy buffer:

- ``pop``/``push`` are O(1) scalar ops on the top pointer;
- growth (``prepend_range``) and compact's rebuild (``from_range``)
  are single vectorized ``np.arange`` writes;
- the tenant-block carve and the rollback reclaim use ``remove_rows``
  — ONE vectorized ``np.isin`` mask, order-preserving like the
  historical comprehension;
- ``pick_pair_rows``' colocation scan reads a bounded ``top_view``
  window and pops by index with a ≤ ``scan_limit`` memmove.

Stack layout: ``_buf[:_n]`` holds live entries bottom→top; pops come
off ``_buf[_n-1]``. The descending initialization puts row 0 on top.
"""

from __future__ import annotations

from typing import Iterable, Iterator

import numpy as np

__all__ = ["FreeStack"]

_DTYPE = np.int32


class FreeStack:
    """Columnar LIFO free list (see module docstring)."""

    __slots__ = ("_buf", "_n")

    def __init__(self, rows: Iterable[int] = ()) -> None:
        arr = np.asarray(list(rows) if not isinstance(rows, np.ndarray)
                         else rows, _DTYPE)
        self._buf = np.array(arr, _DTYPE)  # owned copy
        self._n = int(self._buf.shape[0])

    # -- constructors --------------------------------------------------

    @classmethod
    def from_range(cls, lo: int, hi: int) -> "FreeStack":
        """Rows [lo, hi) as a descending stack — pops yield lo first
        (the historical ``list(range(hi-1, lo-1, -1))``), built as one
        vectorized ``np.arange``."""
        s = cls.__new__(cls)
        s._buf = np.arange(hi - 1, lo - 1, -1, dtype=_DTYPE)
        s._n = int(s._buf.shape[0])
        return s

    # -- core stack ops ------------------------------------------------

    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return self._n > 0

    def __contains__(self, row: int) -> bool:
        # test/diagnostic surface only — the allocator never membership-
        # scans its own free list (that is the `scost` linear-scan
        # class this structure exists to kill)
        return bool(np.any(self._buf[:self._n] == row))

    def __iter__(self) -> Iterator[int]:
        return iter(self._buf[:self._n].tolist())

    def view(self) -> np.ndarray:
        """Read-only view of the live entries, bottom→top."""
        v = self._buf[:self._n]
        v.flags.writeable = False
        return v

    def top_view(self, k: int) -> np.ndarray:
        """Read-only view of (at most) the top `k` entries, in stack
        order bottom→top — the colocation scan window."""
        v = self._buf[max(0, self._n - k):self._n]
        v.flags.writeable = False
        return v

    def peek(self) -> int:
        if not self._n:
            raise IndexError("peek from empty FreeStack")
        return int(self._buf[self._n - 1])

    def pop(self) -> int:
        if not self._n:
            raise IndexError("pop from empty FreeStack")
        self._n -= 1
        return int(self._buf[self._n])

    def pop_at(self, i: int) -> int:
        """Remove and return the entry at absolute index `i` (bottom-
        based, like ``list.pop(i)``). The callers (the colocation
        scan) only reach into the top ``scan_limit`` entries, so the
        shift is a bounded memmove."""
        if not 0 <= i < self._n:
            raise IndexError(i)
        row = int(self._buf[i])
        self._buf[i:self._n - 1] = self._buf[i + 1:self._n]
        self._n -= 1
        return row

    def _reserve(self, extra: int) -> None:
        need = self._n + extra
        cap = self._buf.shape[0]
        if need <= cap:
            return
        new = np.empty((max(need, cap * 2, 8),), _DTYPE)
        new[:self._n] = self._buf[:self._n]
        self._buf = new

    def push(self, row: int) -> None:
        self._reserve(1)
        self._buf[self._n] = row
        self._n += 1

    append = push  # list-compatible spelling

    def extend(self, rows) -> None:
        """Vectorized bulk push (stack order = iteration order, so the
        LAST element lands on top, like ``list.extend``)."""
        arr = np.asarray(rows if isinstance(rows, np.ndarray)
                         else list(rows), _DTYPE)
        self._reserve(arr.shape[0])
        self._buf[self._n:self._n + arr.shape[0]] = arr
        self._n += int(arr.shape[0])

    def prepend_range(self, lo: int, hi: int) -> None:
        """Capacity growth: rows [lo, hi) slide UNDER the existing
        entries (descending, so within the new block lo pops first) —
        the historical ``list(range(hi-1, lo-1, -1)) + free``, as one
        arange + one copy instead of an O(capacity) Python rebuild."""
        n_new = hi - lo
        if n_new <= 0:
            return
        new = np.empty((max(self._n + n_new, 8),), _DTYPE)
        new[:n_new] = np.arange(hi - 1, lo - 1, -1, dtype=_DTYPE)
        new[n_new:n_new + self._n] = self._buf[:self._n]
        self._buf = new
        self._n += n_new

    def remove_rows(self, rows) -> int:
        """Drop every entry present in `rows`, preserving the order of
        the remainder — ONE vectorized ``np.isin`` pass (the historical
        ``[r for r in free if r not in taken]``). Returns the number
        of entries removed."""
        arr = np.asarray(rows if isinstance(rows, np.ndarray)
                         else list(rows), np.int64)
        if not arr.size or not self._n:
            return 0
        live = self._buf[:self._n]
        keep = ~np.isin(live, arr)
        kept = live[keep]
        removed = self._n - int(kept.shape[0])
        if removed:
            self._buf[:kept.shape[0]] = kept
            self._n = int(kept.shape[0])
        return removed

    def drop_top_while_in(self, members) -> None:
        """Pop entries off the top while they appear in `members`
        (a set/dict keyed by row) — the rollback path's bounded
        'owned leftovers on top' sweep."""
        while self._n and int(self._buf[self._n - 1]) in members:
            self._n -= 1

    # -- serialization -------------------------------------------------

    def tolist(self) -> list[int]:
        """Bottom→top Python list — the checkpoint-manifest encoding
        (identical to the historical list's JSON form)."""
        return self._buf[:self._n].tolist()

    def __repr__(self) -> str:  # diagnostics only
        return f"FreeStack(n={self._n})"
