"""``--diff OLD.json`` — reviewer-facing delta between two ANALYSIS
artifacts.

Findings are keyed by ``(layer, rule, path, message)`` — line numbers
shift with unrelated edits, so they are display detail, not identity.
The report buckets: **new** (in the current run only), **fixed** (in
the old artifact only), and **waiver changes** (same finding, waived
flag flipped). Works across schema versions: a v1 artifact (no
``schema_version``, no ``jaxpr`` section) is an AST-only doc.
"""

from __future__ import annotations

import json
from pathlib import Path


def _load(path: Path) -> dict:
    doc = json.loads(Path(path).read_text())
    if "findings" not in doc:
        raise ValueError(f"{path}: not an ANALYSIS artifact "
                         f"(no `findings` key)")
    return doc


def _index(doc: dict) -> dict[tuple, dict]:
    out: dict[tuple, dict] = {}
    for layer, findings in (("ast", doc.get("findings", [])),
                            ("jaxpr", (doc.get("jaxpr") or {})
                             .get("findings", [])),
                            ("scale", (doc.get("scale") or {})
                             .get("findings", []))):
        for f in findings:
            out[(layer, f["rule"], f["path"], f["message"])] = f
    return out


def diff_docs(old: dict, new: dict) -> dict:
    oi, ni = _index(old), _index(new)
    added = sorted(k for k in ni if k not in oi)
    fixed = sorted(k for k in oi if k not in ni)
    waiver_changes = sorted(
        k for k in ni
        if k in oi and bool(oi[k].get("waived")) != bool(
            ni[k].get("waived")))
    return {
        "old_schema": old.get("schema_version", 1),
        "new_schema": new.get("schema_version", 1),
        "new": [ni[k] for k in added],
        "fixed": [oi[k] for k in fixed],
        "waiver_changes": [
            {"finding": ni[k],
             "was_waived": bool(oi[k].get("waived")),
             "now_waived": bool(ni[k].get("waived"))}
            for k in waiver_changes],
    }


def _fmt(f: dict) -> str:
    tag = " [waived]" if f.get("waived") else ""
    return (f"  {f['path']}:{f.get('line', '?')}: [{f['rule']}] "
            f"{f['message']}{tag}")


def print_diff(d: dict) -> None:
    print(f"schema {d['old_schema']} → {d['new_schema']}")
    for title, key in (("new findings", "new"),
                       ("fixed findings", "fixed")):
        rows = d[key]
        print(f"{title}: {len(rows)}")
        for f in rows:
            print(_fmt(f))
    rows = d["waiver_changes"]
    print(f"waiver changes: {len(rows)}")
    for ch in rows:
        arrow = ("active → waived" if ch["now_waived"]
                 else "waived → ACTIVE")
        print(_fmt(ch["finding"]) + f"  ({arrow})")


def run_diff(old_path: Path, new_path: Path) -> int:
    """CLI driver: prints the delta; exit 1 iff new ACTIVE findings
    appeared (a reviewer gate, not a style opinion)."""
    d = diff_docs(_load(old_path), _load(new_path))
    print_diff(d)
    new_active = [f for f in d["new"] if not f.get("waived")]
    reactivated = [c for c in d["waiver_changes"]
                   if not c["now_waived"]]
    return 1 if new_active or reactivated else 0
