"""Best-effort package-local call graph + traced-context discovery.

The purity and host-sync passes need to know which functions execute
*inside* a jax trace (jit / vmap / scan / shard_map bodies) or inside a
configured hot path. Resolution is intentionally conservative and
package-local:

- bare names resolve to functions of the same module or explicit
  ``from kubedtn_tpu.x import f`` imports;
- dotted names resolve through ``import kubedtn_tpu.x as alias``
  module aliases (one attribute hop);
- ``self.method`` resolves to methods of the lexically enclosing class;
- a trailing ``.__wrapped__`` (the repo's jit-unwrap idiom) is
  stripped before resolution.

Unresolvable calls are simply not followed — a static pass that guesses
would drown the tree in false positives. Waivers cover the residue.
"""

from __future__ import annotations

import ast
import dataclasses
from collections import deque
from typing import Iterable

from kubedtn_tpu.analysis.core import (
    Project,
    SourceFile,
    call_name,
    dotted,
    iter_functions,
)

_FIRST_PARTY = "kubedtn_tpu"

# callables whose function-valued arguments run under trace
_TRACING_CALLS = {
    "jax.jit", "jit", "jax.vmap", "vmap", "jax.pmap", "pmap",
    "jax.lax.scan", "lax.scan", "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop", "jax.lax.cond", "lax.cond",
    "jax.lax.switch", "lax.switch", "shard_map", "jax.checkpoint",
    "jax.remat",
}
_TRACING_DECORATORS = {"jax.jit", "jit", "jax.vmap", "vmap", "jax.pmap"}


@dataclasses.dataclass(frozen=True)
class FuncRef:
    """A function occurrence: (file rel path, qualname)."""
    path: str
    qual: str


class CallGraph:
    def __init__(self, project: Project) -> None:
        self.project = project
        # (path, qualname) -> FunctionDef
        self.functions: dict[FuncRef, ast.FunctionDef] = {}
        # per file: alias -> module ("np" -> "numpy",
        # "netem" -> "kubedtn_tpu.ops.netem") and
        # name -> imported qualname ("shape_packets" ->
        # "kubedtn_tpu.ops.queues.shape_packets")
        self.module_aliases: dict[str, dict[str, str]] = {}
        self.from_imports: dict[str, dict[str, str]] = {}
        # qualname prefix of the class each method belongs to
        for src in project:
            self.module_aliases[src.rel] = {}
            self.from_imports[src.rel] = {}
            self._index_imports(src)
            for qual, fn in iter_functions(src.tree):
                self.functions[FuncRef(src.rel, qual)] = fn

    # -- imports -------------------------------------------------------

    def _index_imports(self, src: SourceFile) -> None:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    alias = al.asname or al.name.split(".")[0]
                    self.module_aliases[src.rel][alias] = (
                        al.name if al.asname else al.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                mod = node.module
                if node.level:  # relative import: anchor at the package
                    base = src.module.rsplit(".", node.level)[0]
                    mod = f"{base}.{mod}" if mod else base
                for al in node.names:
                    if al.name == "*":
                        continue
                    local = al.asname or al.name
                    self.from_imports[src.rel][local] = f"{mod}.{al.name}"

    def _module_file(self, module: str) -> SourceFile | None:
        if not module.startswith(_FIRST_PARTY):
            return None
        return self.project.by_module(module)

    # -- resolution ----------------------------------------------------

    def resolve(self, src: SourceFile, scope_qual: str,
                name: str) -> FuncRef | None:
        """Resolve a (possibly dotted) callee name seen inside
        ``scope_qual`` of ``src`` to a package function."""
        if name.endswith(".__wrapped__"):
            name = name[: -len(".__wrapped__")]
        parts = name.split(".")
        # self.method -> method of the enclosing class
        if parts[0] == "self" and len(parts) == 2:
            cls = scope_qual.split(".")[0]
            ref = FuncRef(src.rel, f"{cls}.{parts[1]}")
            return ref if ref in self.functions else None
        if len(parts) == 1:
            # the current scope's own nested defs first, then sibling
            # nested functions, then module-level, then a from-import
            ref = FuncRef(src.rel, f"{scope_qual}.<locals>.{parts[0]}")
            if ref in self.functions:
                return ref
            if "." in scope_qual:
                parent = scope_qual.rsplit(".", 1)[0]
                ref = FuncRef(src.rel, f"{parent}.{parts[0]}")
                if ref in self.functions:
                    return ref
            ref = FuncRef(src.rel, parts[0])
            if ref in self.functions:
                return ref
            target = self.from_imports[src.rel].get(parts[0])
            if target:
                mod, _, fn = target.rpartition(".")
                f = self._module_file(mod)
                if f is not None:
                    ref = FuncRef(f.rel, fn)
                    return ref if ref in self.functions else None
            return None
        # module_alias.func  (one attribute hop)
        mod = self.module_aliases[src.rel].get(parts[0])
        if mod is None:
            target = self.from_imports[src.rel].get(parts[0])
            if target:  # `from kubedtn_tpu.ops import netem` style
                mod = target
        if mod is not None and len(parts) == 2:
            f = self._module_file(mod)
            if f is not None:
                ref = FuncRef(f.rel, parts[1])
                return ref if ref in self.functions else None
        return None

    # -- traced roots --------------------------------------------------

    def traced_roots(self) -> set[FuncRef]:
        """Every function that runs under a jax trace: jit-decorated
        functions and functions passed (by name) to jit/vmap/scan/
        shard_map call sites anywhere in the package."""
        roots: set[FuncRef] = set()
        for src in self.project:
            for qual, fn in iter_functions(src.tree):
                for dec in fn.decorator_list:
                    if self._is_tracing_decorator(dec):
                        roots.add(FuncRef(src.rel, qual))
            for qual, fn in iter_functions(src.tree):
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    cn = call_name(node)
                    if cn is None:
                        continue
                    is_tracer = (cn in _TRACING_CALLS
                                 or cn.split(".")[-1] == "shard_map")
                    if cn in ("functools.partial", "partial"):
                        # partial(jax.jit, ...)(f) — treat the partial's
                        # first arg being a tracer as a tracing call
                        if node.args and isinstance(
                                node.args[0], (ast.Name, ast.Attribute)):
                            first = dotted(node.args[0])
                            is_tracer = first in _TRACING_CALLS
                    if not is_tracer:
                        continue
                    for arg in [*node.args,
                                *(kw.value for kw in node.keywords)]:
                        tgt = dotted(arg)
                        if tgt is None:
                            continue
                        ref = self.resolve(src, qual, tgt)
                        if ref is not None:
                            roots.add(ref)
        return roots

    def _is_tracing_decorator(self, dec: ast.AST) -> bool:
        name = dotted(dec)
        if name in _TRACING_DECORATORS:
            return True
        if isinstance(dec, ast.Call):
            cn = call_name(dec)
            if cn in _TRACING_DECORATORS:
                return True
            if cn in ("functools.partial", "partial") and dec.args:
                return dotted(dec.args[0]) in _TRACING_DECORATORS
        return False

    # -- closure -------------------------------------------------------

    def closure(self, roots: Iterable[FuncRef],
                max_depth: int = 6) -> set[FuncRef]:
        """Roots plus everything reachable through resolvable calls and
        lexically nested defs (nested functions execute at trace time)."""
        seen: set[FuncRef] = set()
        work: deque[tuple[FuncRef, int]] = deque(
            (r, 0) for r in roots if r in self.functions)
        while work:
            ref, depth = work.popleft()
            if ref in seen:
                continue
            seen.add(ref)
            # nested defs belong to the traced scope
            prefix = f"{ref.qual}.<locals>."
            for other in self.functions:
                if other.path == ref.path and \
                        other.qual.startswith(prefix) and \
                        other not in seen:
                    work.append((other, depth))
            if depth >= max_depth:
                continue
            src = self.project.files[ref.path]
            fn = self.functions[ref]
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    cn = call_name(node)
                    if cn is None:
                        continue
                    tgt = self.resolve(src, ref.qual, cn)
                    if tgt is not None and tgt not in seen:
                        work.append((tgt, depth + 1))
        return seen
