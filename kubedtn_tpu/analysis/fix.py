"""dtnlint ``--fix`` — mechanical repair of the hygiene findings.

Two fixers, both conservative enough to run unattended on the tree:

- **unused imports**: every `unused import `name`` finding's alias is
  removed from its import statement; a statement left with no aliases
  is deleted outright. Multi-alias (`import a, b`) and parenthesized
  from-imports are handled by rebuilding the statement from its
  surviving aliases. Lines carrying a dtnlint waiver are left alone —
  a waived finding is a decision, not a chore.
- **import-group order**: the LEADING import block of a module (after
  the docstring, up to the first non-import statement) is stably
  re-sorted into future < stdlib < third-party < first-party groups,
  one blank line between groups. Comment lines directly above an
  import travel with it (the isort convention). Imports below the
  first non-import statement are deliberate (lazy jax) and untouched.

Every rewritten file is re-parsed before it is written back; a fixer
that would produce a syntax error or change the imported-name set
leaves the file untouched and reports failure instead. The fixed tree
is re-linted by the caller — hygiene findings go to zero without
waivers, which is the point.
"""

from __future__ import annotations

import ast
from pathlib import Path

from kubedtn_tpu.analysis.core import RULE_HYGIENE, Finding
from kubedtn_tpu.analysis.passes.hygiene import _group


def _import_names(tree: ast.AST) -> set[tuple]:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for al in node.names:
                out.add(("import", al.name, al.asname))
        elif isinstance(node, ast.ImportFrom):
            for al in node.names:
                out.add(("from", node.level, node.module, al.name,
                         al.asname))
    return out


def _rebuild_import(node, keep: list) -> str:
    """The statement's source with only the `keep` aliases (ast
    round-trip — comment-free, which is acceptable for a line being
    shrunk; full-line deletes preserve neighbors untouched)."""
    clone = ast.Import(names=keep) if isinstance(node, ast.Import) \
        else ast.ImportFrom(module=node.module, names=keep,
                            level=node.level)
    return ast.unparse(ast.fix_missing_locations(ast.Module(
        body=[clone], type_ignores=[])))


def fix_unused_imports(path: Path, findings: list[Finding]) -> bool:
    """Drop the aliases named by this file's `unused import` findings.
    Returns True when the file changed."""
    names = set()
    for f in findings:
        if f.rule == RULE_HYGIENE and not f.waived \
                and f.message.startswith("unused import `"):
            names.add(f.message.split("`")[1])
    if not names:
        return False
    text = path.read_text()
    lines = text.splitlines(keepends=True)
    tree = ast.parse(text)
    edits: list[tuple[int, int, str | None]] = []  # (start, end, repl)
    for node in tree.body:
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if isinstance(node, ast.ImportFrom) and (
                node.module == "__future__"
                or any(al.name == "*" for al in node.names)):
            continue
        is_from = isinstance(node, ast.ImportFrom)
        bound = (lambda al: (al.asname or al.name) if is_from
                 else (al.asname or al.name).split(".")[0])
        keep = [al for al in node.names if bound(al) not in names]
        if len(keep) == len(node.names):
            continue
        start, end = node.lineno - 1, node.end_lineno
        if keep:
            edits.append((start, end, _rebuild_import(node, keep) + "\n"))
        else:
            edits.append((start, end, None))
    if not edits:
        return False
    for start, end, repl in reversed(edits):
        lines[start:end] = [repl] if repl is not None else []
    new_text = "".join(lines)
    try:
        new_tree = ast.parse(new_text)
    except SyntaxError:
        return False
    # safety: exactly the targeted aliases vanished, nothing else moved
    removed = _import_names(tree) - _import_names(new_tree)
    removed_names = {(t[4] or t[3]) if t[0] == "from"
                     else (t[2] or t[1]).split(".")[0] for t in removed}
    if not removed_names <= names:
        return False
    path.write_text(new_text)
    return True


def fix_import_order(path: Path) -> bool:
    """Stably regroup the leading import block. Returns True when the
    file changed."""
    text = path.read_text()
    tree = ast.parse(text)
    lines = text.splitlines(keepends=True)

    # the leading block: import statements (with any directly-attached
    # comment lines above) from after the docstring to the first
    # non-import statement
    body = list(tree.body)
    i = 0
    if body and isinstance(body[0], ast.Expr) and isinstance(
            body[0].value, ast.Constant) and isinstance(
            body[0].value.value, str):
        i = 1
    imports = []
    while i < len(body) and isinstance(body[i],
                                       (ast.Import, ast.ImportFrom)):
        imports.append(body[i])
        i += 1
    if len(imports) < 2:
        return False

    units = []  # (group, original_idx, [lines])
    block_start = None
    prev_end = None
    captured: set[int] = set()
    for idx, node in enumerate(imports):
        start = node.lineno - 1
        # attach contiguous comment lines directly above
        while start > 0 and lines[start - 1].lstrip().startswith("#") \
                and (prev_end is None or start - 1 >= prev_end):
            start -= 1
        if block_start is None:
            block_start = start
        mod = (node.names[0].name if isinstance(node, ast.Import)
               else "." * node.level + (node.module or ""))
        units.append((_group(mod), idx, lines[start:node.end_lineno]))
        captured.update(range(start, node.end_lineno))
        prev_end = node.end_lineno
    block_end = prev_end
    # a line in the block belonging to NO unit (a free-standing comment
    # separated from the next import by a blank line) would be silently
    # dropped by the rebuild — refuse to reorder rather than eat it
    for i in range(block_start, block_end):
        if i not in captured and lines[i].strip():
            return False

    ordered = sorted(units, key=lambda u: (u[0], u[1]))
    if [u[1] for u in ordered] == list(range(len(units))):
        return False
    out: list[str] = []
    last_group = None
    for g, _idx, chunk in ordered:
        if last_group is not None and g != last_group:
            out.append("\n")
        out.extend(chunk)
        last_group = g
    new_lines = lines[:block_start] + out + lines[block_end:]
    new_text = "".join(new_lines)
    try:
        new_tree = ast.parse(new_text)
    except SyntaxError:
        return False
    if _import_names(tree) != _import_names(new_tree):
        return False
    path.write_text(new_text)
    return True


def fix_tree(root: Path, project, findings: list[Finding]) -> list[str]:
    """Apply both fixers across the project; returns the repo-relative
    paths that changed."""
    by_file: dict[str, list[Finding]] = {}
    for f in findings:
        if f.rule == RULE_HYGIENE:
            by_file.setdefault(f.path, []).append(f)
    changed: list[str] = []
    for rel, fs in sorted(by_file.items()):
        p = root / rel
        did = fix_unused_imports(p, fs)
        if any("out of group order" in f.message for f in fs
               if not f.waived):
            did = fix_import_order(p) or did
        if did:
            changed.append(rel)
    return changed
