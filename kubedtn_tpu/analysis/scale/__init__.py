"""dtnscale — host-asymptotics analysis of the scale-critical paths.

The third analysis layer. dtnlint (AST) checks the determinism
contracts where they are written and dtnverify (jaxpr) where they are
staked in the compiled programs; neither sees the HOST side — the
Python bookkeeping that runs under the engine/tick locks on every
tick, drain, barrier, compact, checkpoint, and migration step. At the
roadmap's million-edge scale that bookkeeping is the ceiling: a free
list rebuilt ``list(range(capacity...))`` per grow/compact, a
per-dispatch ``set(engine._shaped_rows)`` copy, per-generation
O(all-rows) tenant row-set re-derives — all invisible to the first
two layers, all measured in hundreds of milliseconds of runner pause
at 1M rows. Beehive's thesis (PAPERS.md, arxiv 2403.14770) is that
the host must stay OFF the data path for accelerator-attached
networking to scale; dtnscale enforces that as a machine-checked
budget, the way COST_BUDGET.json pins device flops and dispatches.

Two halves, one ``scale`` section in ANALYSIS.json (schema v3):

- **static** (`bounds.py` + `entrypoints.py`): reuse the PR 6
  call-graph machinery to close over each scale-critical entry point
  (tick/dispatch/complete, drain, barrier bodies, compact,
  checkpoint save/load, migration fork/restore/cutover), infer the
  bound class of every *Python-level* loop/comprehension/
  materialization in the closure (rows-touched / tenants / capacity —
  vectorized numpy passes are free), and flag ``scost`` findings
  where an entry exceeds its ``SCALE_BUDGET.json`` class: the steady
  tick and drain must be capacity-independent, barrier bodies at most
  O(rows_touched), compact/save linear.
- **empirical** (`probe.py`): run the REAL engine at increasing row
  counts, fit log-log wall-time slopes for alloc-churn / drain-policy
  / stage-barrier / compact / checkpoint-save, and fail on
  superlinear drift past the budget file's slope ceilings — the same
  pattern as the dtnverify dispatch probe. ``bench.py``'s
  ``host_scale`` phase runs the same probe at 10k/100k/1M rows.

Waiver tag: ``# dtnlint: scost-ok(reason)`` — reason mandatory,
audited in the artifact, stale-detected like every other rule. The
tree policy is fix-not-waive: PR 12 made the columnar-bookkeeping
refactor (FreeStack, vectorized compact, incremental tenant masks)
instead of waivering the findings that forced it.
"""

from __future__ import annotations

from kubedtn_tpu.analysis.scale.bounds import run_scale_pass
from kubedtn_tpu.analysis.scale.entrypoints import SCALE_ENTRIES
from kubedtn_tpu.analysis.scale.runner import run_scale

__all__ = ["run_scale", "run_scale_pass", "SCALE_ENTRIES"]
