"""dtnscale static half: per-function loop-bound inference.

For every function in an entry's call-graph closure, find the
*Python-level* iteration constructs — ``for`` loops, comprehensions
and generator expressions, linear builtins (``sorted``/``list``/
``set``/``tuple``/``sum``/``max``/``min``) over classified
containers, ``list(range(...))`` materializations, and per-element
free-list scans — and classify each one's bound against the
vocabulary in `entrypoints.py`:

- a ``range()`` whose argument mentions a capacity bound name, or an
  iteration over a capacity-classified container → ``O(capacity)``;
- iteration over the tenant registry → ``O(tenants)``;
- iteration over the autopilot's candidate lattice → ``O(grid)``;
- everything else (batch parameters, local collections, unresolvable
  names) → ``O(rows_touched)`` — the conservative default that keeps
  the pass quiet on the batch-shaped hot loops;
- a classified loop nested inside another classified loop →
  superlinear (``nested`` kind), never budgetable.

Vectorized numpy calls are exempt by construction: their arguments
are not visited as iteration (``np.fromiter(owned.keys(), ...)`` is a
C-speed pass), which is exactly the columnar-bookkeeping contract the
budgets enforce.

Findings carry the entry name, the construct's inferred class, and
the entry's budget, and are waivable with ``scost-ok(reason)``.
"""

from __future__ import annotations

import ast
import dataclasses

from kubedtn_tpu.analysis.callgraph import CallGraph, FuncRef
from kubedtn_tpu.analysis.core import (
    RULE_SCOST,
    Finding,
    Project,
    call_name,
)
from kubedtn_tpu.analysis.scale.entrypoints import (
    CAPACITY_BOUNDS,
    CAPACITY_CONTAINERS,
    CAPACITY_LISTS,
    CLASS_CAPACITY,
    CLASS_GRID,
    CLASS_O1,
    CLASS_ORDER,
    CLASS_RANK,
    CLASS_ROWS,
    CLASS_SUPER,
    CLASS_TENANTS,
    GRID_CONTAINERS,
    SCALE_ENTRIES,
    TENANT_CONTAINERS,
)

# builtins that walk their (first) argument linearly at Python speed
_LINEAR_BUILTINS = {"sorted", "list", "set", "tuple", "sum", "max",
                    "min", "frozenset"}
# call prefixes whose arguments are C-speed array passes — NOT
# Python-level iteration (the contract the budgets enforce)
_VECTORIZED_PREFIXES = ("np.", "numpy.", "jnp.", "jax.")


@dataclasses.dataclass
class Contribution:
    """One classified construct inside an entry closure."""

    line: int
    kind: str        # loop | linear-call | range-materialize | scan
    klass: str       # inferred bound class
    detail: str      # what was iterated/scanned
    always_flag: bool = False


def _name_class(name: str) -> str | None:
    """Class of a bare/attribute NAME, or None when unclassified."""
    if name in CAPACITY_BOUNDS or name in CAPACITY_CONTAINERS:
        return CLASS_CAPACITY
    if name in TENANT_CONTAINERS:
        return CLASS_TENANTS
    if name in GRID_CONTAINERS:
        return CLASS_GRID
    return None


def _leaf_name(node: ast.AST) -> str | None:
    """The classification-relevant final name of an expression:
    ``self._rows`` → ``_rows``, ``engine._free`` → ``_free``,
    ``cap`` → ``cap``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def classify_expr(node: ast.AST) -> tuple[str, str]:
    """(class, detail) for an iterable/bound expression. Constants →
    O(1); classified names dominate; anything else defaults to
    O(rows_touched)."""
    if isinstance(node, ast.Constant):
        return CLASS_O1, repr(node.value)
    if isinstance(node, ast.Call):
        cn = call_name(node)
        # range(X) / reversed(X) / enumerate(X) / zip(...) / X.items()
        if cn == "range":
            best, det = CLASS_O1, "range(<const>)"
            for a in node.args:
                k, d = classify_expr(a)
                if CLASS_RANK[k] > CLASS_RANK[best]:
                    best, det = k, f"range({d})"
            return best, det
        if cn in ("reversed", "enumerate", "iter"):
            if node.args:
                return classify_expr(node.args[0])
            return CLASS_ROWS, cn
        if cn == "zip":
            best, det = CLASS_O1, "zip()"
            for a in node.args:
                k, d = classify_expr(a)
                if CLASS_RANK[k] > CLASS_RANK[best]:
                    best, det = k, d
            return (best if best != CLASS_O1 else CLASS_ROWS), det
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("items", "values", "keys"):
            return classify_expr(node.func.value)
        # unknown call → bounded by its own result: batch default
        return CLASS_ROWS, cn or "<call>"
    if isinstance(node, (ast.GeneratorExp, ast.ListComp,
                         ast.SetComp, ast.DictComp)):
        # a comprehension used as an iterable is bounded by its own
        # sources
        best, det = CLASS_O1, "<genexp>"
        for gen in node.generators:
            k, d = classify_expr(gen.iter)
            if CLASS_RANK[k] > CLASS_RANK[best]:
                best, det = k, d
        return (best if best != CLASS_O1 else CLASS_ROWS), det
    leaf = _leaf_name(node)
    if leaf is not None:
        k = _name_class(leaf)
        if k is not None:
            return k, leaf
        return CLASS_ROWS, leaf
    # composite expressions (``cap - 1``, conditionals, subscripts):
    # classified by the names they mention — the strongest wins.
    # Names inside a nested call's FUNC position are skipped: a
    # method call ON a container (`_by_key.get(k)`) is not an
    # iteration OVER it.
    best: str | None = None
    best_name = "<expr>"
    saw_name = [False]

    def scan(n: ast.AST) -> None:
        nonlocal best, best_name
        for child in ast.iter_child_nodes(n):
            if isinstance(child, ast.Call):
                for a in (*child.args,
                          *(kw.value for kw in child.keywords)):
                    scan_node(a)
                continue
            scan_node(child)

    def scan_node(n: ast.AST) -> None:
        nonlocal best, best_name
        nm = _leaf_name(n) if isinstance(
            n, (ast.Name, ast.Attribute)) else None
        if nm is not None:
            saw_name[0] = True
            k = _name_class(nm)
            if k is not None and (
                    best is None or CLASS_RANK[k] > CLASS_RANK[best]):
                best, best_name = k, nm
        scan(n)

    scan_node(node)
    if best is not None:
        return best, best_name
    return (CLASS_ROWS if saw_name[0] else CLASS_O1), "<expr>"


def _combine_nested(outer: str, inner: str) -> str:
    """Effective class of an `inner`-classified construct under an
    `outer` enclosing loop. O(1) never multiplies; rows×rows stays
    rows_touched (a batch of batches is still the batch) and a rows
    walk under a tenant loop is the per-tenant slice of one batch —
    but capacity×anything (and tenants×tenants) is superlinear."""
    ro, ri = CLASS_RANK[outer], CLASS_RANK[inner]
    if ro == 0 or ri == 0:
        return inner
    if CLASS_CAPACITY in (outer, inner):
        return CLASS_SUPER
    if outer == CLASS_TENANTS and inner == CLASS_TENANTS:
        return CLASS_SUPER
    return CLASS_ORDER[max(ro, ri)]


def analyze_function(fn: ast.FunctionDef) -> list[Contribution]:
    """Classified constructs of `fn`'s own body (nested defs are their
    own closure members)."""
    out: list[Contribution] = []

    def visit(node: ast.AST, loop_stack: tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack = loop_stack
            if isinstance(child, (ast.For, ast.AsyncFor)):
                k, det = classify_expr(child.iter)
                eff = k
                for outer in loop_stack:
                    eff = _combine_nested(outer, eff)
                if CLASS_RANK[eff] > 0:
                    out.append(Contribution(
                        child.lineno,
                        "nested" if eff == CLASS_SUPER else "loop",
                        eff, det))
                stack = loop_stack + (k,)
            elif isinstance(child, (ast.ListComp, ast.SetComp,
                                    ast.DictComp, ast.GeneratorExp)):
                for gen in child.generators:
                    k, det = classify_expr(gen.iter)
                    eff = k
                    for outer in loop_stack:
                        eff = _combine_nested(outer, eff)
                    if CLASS_RANK[eff] > 0:
                        out.append(Contribution(
                            child.lineno,
                            "nested" if eff == CLASS_SUPER
                            else "comprehension", eff, det))
            elif isinstance(child, ast.Call):
                _classify_call(child, loop_stack, out)
            elif isinstance(child, ast.Compare):
                _classify_membership(child, out)
            visit(child, stack)

    visit(fn, ())
    return out


def _classify_call(node: ast.Call, loop_stack: tuple[str, ...],
                   out: list[Contribution]) -> None:
    cn = call_name(node)
    if cn is None:
        return
    if cn.startswith(_VECTORIZED_PREFIXES):
        return  # C-speed array pass — the budgeted alternative
    # list(range(CAP)) / set(range(CAP)): materializing an O(capacity)
    # Python collection — flagged regardless of the entry budget (the
    # columnar structures exist so this never happens)
    if cn in ("list", "set", "tuple") and node.args and \
            isinstance(node.args[0], ast.Call) and \
            call_name(node.args[0]) == "range":
        k, det = classify_expr(node.args[0])
        if k == CLASS_CAPACITY:
            out.append(Contribution(
                node.lineno, "range-materialize", k,
                f"{cn}({det})", always_flag=True))
            return
    if cn in _LINEAR_BUILTINS and node.args:
        if isinstance(node.args[0], (ast.GeneratorExp, ast.ListComp,
                                     ast.SetComp, ast.DictComp)):
            return  # the comprehension visitor owns that construct
        k, det = classify_expr(node.args[0])
        eff = k
        for outer in loop_stack:
            eff = _combine_nested(outer, eff)
        if CLASS_RANK[eff] >= CLASS_RANK[CLASS_TENANTS]:
            out.append(Contribution(
                node.lineno,
                "nested" if eff == CLASS_SUPER else "linear-call",
                eff, f"{cn}({det})"))
        return
    # free-list element scans: c.remove(x) / c.pop(i)
    if isinstance(node.func, ast.Attribute) and \
            node.func.attr in ("remove", "pop"):
        leaf = _leaf_name(node.func.value)
        if leaf in CAPACITY_LISTS and node.args:
            out.append(Contribution(
                node.lineno, "scan", CLASS_CAPACITY,
                f"{leaf}.{node.func.attr}(...)", always_flag=True))


def _classify_membership(node: ast.Compare,
                         out: list[Contribution]) -> None:
    """``x in _free`` — a linear scan of the columnar free list per
    call (set/dict membership is O(1) and exempt by vocabulary)."""
    for op, comp in zip(node.ops, node.comparators):
        if not isinstance(op, (ast.In, ast.NotIn)):
            continue
        leaf = _leaf_name(comp)
        if leaf in CAPACITY_LISTS:
            out.append(Contribution(
                node.lineno, "scan", CLASS_CAPACITY,
                f"<x> in {leaf}", always_flag=True))


def run_scale_pass(project: Project, graph: CallGraph,
                   entries: dict | None = None,
                   budgets: dict[str, str] | None = None,
                   ) -> tuple[list[Finding], dict]:
    """Run the static half over `entries` (default: the configured
    SCALE_ENTRIES). `budgets` overrides each entry's budget class
    (the SCALE_BUDGET.json values; defaults come from the entry
    config). Returns (findings, per-entry report)."""
    entries = entries if entries is not None else SCALE_ENTRIES
    findings: list[Finding] = []
    report: dict[str, dict] = {}
    for name, spec in entries.items():
        budget = (budgets or {}).get(name, spec["budget"])
        budget_rank = CLASS_RANK[budget]
        roots = [FuncRef(p, q) for p, q in spec["roots"]
                 if FuncRef(p, q) in graph.functions]
        closure = graph.closure(roots)
        worst = CLASS_O1
        n_constructs = 0
        for ref in sorted(closure, key=lambda r: (r.path, r.qual)):
            fn = graph.functions[ref]
            for c in analyze_function(fn):
                n_constructs += 1
                if CLASS_RANK[c.klass] > CLASS_RANK[worst]:
                    worst = c.klass
                over = CLASS_RANK[c.klass] > budget_rank
                if not (over or c.always_flag):
                    continue
                if c.kind == "range-materialize":
                    why = ("materializes an O(capacity) Python "
                           "collection — keep it columnar "
                           "(np.arange / FreeStack)")
                elif c.kind == "scan":
                    why = ("per-element scan of the free list — "
                           "O(capacity) per call, superlinear in any "
                           "loop (use FreeStack.remove_rows / "
                           "drop_top_while_in)")
                elif c.kind == "nested":
                    why = "nested data-dependent loops — superlinear"
                else:
                    why = (f"exceeds the entry budget {budget} "
                           f"(one {c.klass} Python walk per "
                           f"invocation)")
                findings.append(Finding(
                    RULE_SCOST, ref.path, c.line,
                    f"[{name}] {c.kind} over `{c.detail}` in "
                    f"`{ref.qual}` is {c.klass}: {why}"))
        report[name] = {
            "budget": budget,
            "inferred": worst,
            "functions": len(closure),
            "constructs": n_constructs,
            "roots_resolved": len(roots),
            "roots_configured": len(spec["roots"]),
        }
    return findings, report
