"""dtnscale runner: the empirical probe gate + its result cache.

The static half runs inside `analysis.run_suite` (so scost findings
share the waiver/stale machinery with every other rule); this module
owns the part that costs real time — building engines and timing the
host paths — and caches it exactly like the dtnverify trace cache:
keyed on a content hash of the package tree plus SCALE_BUDGET.json,
replayed only under ``--cached`` (`make verify-fast`), refreshed by
every full run.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

from kubedtn_tpu.analysis.core import RULE_SAVAIL, RULE_SCOST, Finding
from kubedtn_tpu.analysis.scale import budget as budget_mod

CACHE_FILE = ".dtnscale-cache.json"
_CACHE_SCHEMA = 2
PAUSE_BENCH_FILE = "BENCH_pauses.json"


def _tree_hash(root: Path) -> str:
    import numpy as np

    h = hashlib.sha256()
    # numpy drives every columnar path the probe times; a version
    # change must miss the cache like a jax change misses dtnverify's
    h.update(f"numpy={np.__version__};".encode())
    for p in sorted((root / "kubedtn_tpu").rglob("*.py")):
        h.update(p.relative_to(root).as_posix().encode())
        h.update(p.read_bytes())
    budget = root / budget_mod.BUDGET_FILE
    if budget.exists():
        h.update(budget.read_bytes())
    # the savail gate judges the banked pause record: re-banking it
    # must miss the cache even when no source changed
    pauses = root / PAUSE_BENCH_FILE
    if pauses.exists():
        h.update(pauses.read_bytes())
    return h.hexdigest()


def _load_cache(root: Path, key: str):
    p = root / CACHE_FILE
    if not p.exists():
        return None
    try:
        doc = json.loads(p.read_text())
    except (OSError, ValueError):
        return None
    if doc.get("tree_hash") != key or doc.get("schema") != _CACHE_SCHEMA:
        return None
    findings = [Finding(**f) for f in doc.get("findings", [])]
    return findings, dict(doc.get("probe", {}))


def _save_cache(root: Path, key: str, findings, probe: dict) -> None:
    doc = {"schema": _CACHE_SCHEMA, "tree_hash": key,
           "findings": [f.to_json() for f in findings],
           "probe": dict(probe)}
    try:
        (root / CACHE_FILE).write_text(json.dumps(doc) + "\n")
    except OSError:
        pass  # the cache is an optimization, never a failure


def _check_availability(root: Path, doc, findings: list) -> dict:
    """savail: gate the banked BENCH_pauses.json barrier-pause record
    against the budget's `availability` ceilings. A missing record is
    informational (the bench has simply not been banked on this tree),
    but a record with an unbudgeted cause, a cause over its wall-clock
    share, a single pause over its ceiling, or ledger hook overhead
    past the bar is a finding — availability regressions gate exactly
    like host-complexity regressions."""
    avail = budget_mod.availability(doc)
    p = root / PAUSE_BENCH_FILE
    report: dict = {"file": PAUSE_BENCH_FILE, "present": p.exists(),
                    "ceilings": avail}
    if not p.exists():
        report["note"] = (
            "no banked pause record — `python bench.py` "
            "(pause_observability phase) banks one; informational")
        return report
    try:
        rec = json.loads(p.read_text())
    except (OSError, ValueError):
        findings.append(Finding(
            RULE_SAVAIL, PAUSE_BENCH_FILE, 1,
            "banked pause record unreadable — re-bank with "
            "`python bench.py`"))
        return report
    wall = float(rec.get("wall_s") or 0.0)
    shares: dict[str, float] = {}
    for cause, st in sorted((rec.get("causes") or {}).items()):
        try:
            secs = float(st.get("seconds", 0.0))
            max_s = float(st.get("max_s", 0.0))
        except (AttributeError, TypeError, ValueError):
            continue
        if secs <= 0.0:
            continue
        share = secs / wall if wall > 0 else 0.0
        shares[cause] = round(share, 4)
        limit = avail["max_share"].get(cause)
        if limit is None:
            findings.append(Finding(
                RULE_SAVAIL, budget_mod.BUDGET_FILE, 1,
                f"pause cause `{cause}` appears in the banked record "
                f"({secs:.3f}s) but has no `availability.max_share` "
                f"budget — new barrier causes must be budgeted "
                f"deliberately"))
        elif share > limit:
            findings.append(Finding(
                RULE_SAVAIL, PAUSE_BENCH_FILE, 1,
                f"`{cause}` pauses ate {share:.1%} of the bench wall "
                f"clock ({secs:.3f}s / {wall:.3f}s) > budget "
                f"{limit:.1%} — the plane's availability under this "
                f"barrier regressed"))
        single = avail["max_single_pause_s"].get(cause)
        if single is not None and max_s > single:
            findings.append(Finding(
                RULE_SAVAIL, PAUSE_BENCH_FILE, 1,
                f"worst single `{cause}` pause {max_s:.3f}s > ceiling "
                f"{single:.3f}s — one barrier hold-down this long "
                f"stalls every tick behind it"))
    hook = rec.get("hook_overhead_pct")
    if hook is not None:
        try:
            hookf = float(hook)
        except (TypeError, ValueError):
            hookf = None
        if hookf is not None and hookf > avail["hook_overhead_pct"]:
            findings.append(Finding(
                RULE_SAVAIL, PAUSE_BENCH_FILE, 1,
                f"pause-ledger hook overhead {hookf:.2f}% > "
                f"{avail['hook_overhead_pct']:.2f}% budget — the "
                f"observability plane itself is taxing the tick path"))
    report.update(wall_s=wall, shares=shares,
                  hook_overhead_pct=hook)
    return report


def run_scale(root: Path, use_cache: bool = False,
              update_budgets: bool = False,
              sizes: list[int] | None = None,
              ) -> tuple[list[Finding], dict]:
    """Run (or replay) the empirical probe and gate its fitted slopes
    against SCALE_BUDGET.json. With `update_budgets`, re-baseline the
    budget file from the measured slopes instead of checking.
    Returns (findings, probe report)."""
    from kubedtn_tpu.analysis.scale.probe import run_probe

    doc = budget_mod.load_budget(root)
    cache_key = (_tree_hash(root)
                 if sizes is None and not update_budgets else None)
    if use_cache and cache_key is not None:
        hit = _load_cache(root, cache_key)
        if hit is not None:
            findings, probe = hit
            probe["cache"] = "hit"
            return findings, probe

    probe = run_probe(sizes if sizes is not None
                      else budget_mod.probe_sizes(doc))
    measured = {name: ph["slope"]
                for name, ph in probe["phases"].items()}

    findings: list[Finding] = []
    if update_budgets:
        newdoc = budget_mod.write_budget(root, measured)
        probe["budget_updated"] = True
        probe["ceilings"] = newdoc["probe"]["max_slope"]
        return findings, probe

    ceilings = budget_mod.probe_slopes(doc)
    probe["ceilings"] = ceilings
    for name, slope in sorted(measured.items()):
        limit = ceilings.get(name)
        if limit is None or slope <= limit:
            continue
        secs = probe["phases"][name]["seconds"]
        if max(secs) < 0.005:
            # sub-5ms at the largest size: pure timer noise, and
            # trivially within any host budget — a path that later
            # grows with plane size will cross the floor and get
            # judged (the bench-scale 1M run makes real growth
            # unmissable)
            continue
        findings.append(Finding(
            RULE_SCOST, budget_mod.BUDGET_FILE, 1,
            f"[probe] `{name}` wall time scales superlinearly past "
            f"its budget: fitted slope {slope:.2f} > {limit:.2f} "
            f"over rows {probe['sizes']} — host work on this path "
            f"grew with plane size"))
    probe["availability"] = _check_availability(root, doc, findings)
    if cache_key is not None:
        _save_cache(root, cache_key, findings, probe)
    return findings, probe
