"""dtnscale empirical half: the host-scaling probe.

Builds the REAL engine (and a real data plane on top of it) at a
ladder of row counts, times the scale-critical host operations at
each size, and fits log-log wall-time slopes — the empirical check
that the static budgets describe the code that actually runs, in the
same pattern as the dtnverify dispatch probe. Phases:

=================  ====================================================
phase              measures (expected)
=================  ====================================================
``alloc_churn``    row alloc/free through the engine allocator +
                   columnar free list (capacity-independent)
``drain_policy``   the tenancy admission snapshot per tick
                   (O(tenants), capacity-independent)
``stage_barrier``  an empty `stage_update_round` — the tick-lock
                   flush barrier every staged change pays
                   (capacity-independent)
``compact``        full engine.compact() — repack + registry rebuild
                   + tenant re-carve (one linear pass)
``checkpoint_save``  checkpoint.save of store+engine+arrays
                   (one linear pass)
=================  ====================================================

A fitted slope above the ``SCALE_BUDGET.json`` ``probe.max_slope``
ceiling for its phase is a ``scost`` finding — superlinear drift on a
path the static pass believes is budgeted. ``bench.py``'s
``host_scale`` phase runs this probe process-isolated at
10k/100k/1M rows and banks the slopes in the bench record; the CLI
(``--scale``) runs the small default ladder so tier-1 stays fast.
"""

from __future__ import annotations

import math
import os
import tempfile
import time

import numpy as np

# number of timed repetitions per (phase, size); min is kept (load
# spikes on shared hosts only ever inflate)
_REPS = 3
_ALLOC_OPS = 256
_POLICY_CALLS = 64
_BARRIER_CALLS = 8
_PROBE_TENANTS = 8


def _next_pow2(n: int) -> int:
    p = 8
    while p < n:
        p *= 2
    return p


def _build(n_rows: int):
    """A real engine + registry + plane realized at `n_rows` directed
    rows (pair-allocated like add_links, flushed to device)."""
    from kubedtn_tpu.ops import edge_state as es
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.tenancy import TenantRegistry
    from kubedtn_tpu.topology.engine import SimEngine
    from kubedtn_tpu.topology.store import TopologyStore
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    # capacity = 2× the realized rows at every size (the churn phase
    # needs free headroom, and a proportional cap keeps the fitted
    # slopes honest — cap-linear passes scale with rows exactly);
    # the alloc-churn floor keeps tiny probe sizes from exhausting
    # the pool mid-phase
    engine = SimEngine(store, capacity=_next_pow2(
        max(n_rows * 2, n_rows + 2 * _ALLOC_OPS)))
    registry = TenantRegistry(engine)
    for t in range(_PROBE_TENANTS):
        registry.create(f"probe-t{t}", namespaces=[f"ns{t}"])
    props = np.zeros((es.NPROP,), np.float32)
    with engine._lock:
        entries = []
        for i in range(n_rows // 2):
            ns = f"ns{i % _PROBE_TENANTS}"
            k1, k2 = f"{ns}/p{i}a", f"{ns}/p{i}b"
            r1, r2 = engine._alloc_link_pair(k1, k2, 1)
            a, b = engine._pod_id(k1), engine._pod_id(k2)
            entries.append((r1, 1, a, b, props, False))
            entries.append((r2, 1, b, a, props, False))
        engine._enqueue_apply(entries)
        engine._flush_device_locked()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=10_000.0)
    return store, engine, registry, daemon, plane


def _timed(fn) -> float:
    best = math.inf
    for _ in range(_REPS):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _probe_size(n_rows: int) -> dict[str, float]:
    import jax

    from kubedtn_tpu import checkpoint

    store, engine, registry, _daemon, plane = _build(n_rows)

    def alloc_churn():
        with engine._lock:
            rows = []
            for j in range(_ALLOC_OPS):
                rows.append(engine._alloc("probe/churn", 900_000 + j))
            for j, r in enumerate(rows):
                engine._rows.pop(("probe/churn", 900_000 + j), None)
                engine._row_owner.pop(r, None)
                engine._free_row(r)

    def drain_policy():
        now = 1.0
        for _ in range(_POLICY_CALLS):
            registry.drain_policy(64, now)
            now += 0.01

    def stage_barrier():
        for _ in range(_BARRIER_CALLS):
            plane.stage_update_round(lambda: None)

    def compact():
        out = engine.compact()
        jax.block_until_ready(engine.state.props)
        return out

    def save():
        with tempfile.TemporaryDirectory() as td:
            checkpoint.save(os.path.join(td, "ckpt"), store, engine)

    times = {}
    # warm each phase once (jit compiles, allocator high-water) before
    # the timed reps
    for name, fn in (("alloc_churn", alloc_churn),
                     ("drain_policy", drain_policy),
                     ("stage_barrier", stage_barrier),
                     ("compact", compact),
                     ("checkpoint_save", save)):
        fn()
        times[name] = _timed(fn)
    # explicit teardown: 1M-row planes hold ~100MB of device arrays
    del plane, engine, store, registry
    return times


def fit_slope(sizes, seconds) -> float:
    """Least-squares slope of log(seconds) vs log(rows). Times are
    floored at 20µs first: below that the measurement is timer noise
    and a 2µs→8µs wobble must not read as 'superlinear'."""
    xs = np.log(np.asarray(sizes, np.float64))
    ys = np.log(np.maximum(np.asarray(seconds, np.float64), 2e-5))
    if xs.size < 2:
        return 0.0
    return float(np.polyfit(xs, ys, 1)[0])


def run_probe(sizes: list[int]) -> dict:
    """The probe report: per-phase wall times at each size + fitted
    slope. Sizes are directed-row counts (engine capacity pads to the
    next power of two)."""
    per_phase: dict[str, list[float]] = {}
    for n in sizes:
        times = _probe_size(int(n))
        for name, s in times.items():
            per_phase.setdefault(name, []).append(s)
    return {
        "sizes": [int(s) for s in sizes],
        "phases": {
            name: {
                "seconds": [round(s, 6) for s in secs],
                "slope": round(fit_slope(sizes, secs), 3),
            }
            for name, secs in per_phase.items()
        },
    }
