"""dtnscale entry-point configuration: roots, vocabulary, budgets.

An *entry* is one scale-critical host path: a set of call-graph roots
(the same (path, qualname) addressing the PR 6 machinery uses) whose
closure the bounds pass analyzes as one unit, plus a complexity
budget class. The class ladder, coarsest host-cost vocabulary that
still separates the real offenders:

======================  ================================================
class                   meaning (Python-level work per invocation)
======================  ================================================
``O(1)``                no data-dependent Python iteration at all
``O(rows_touched)``     bounded by the operation's own batch — frames
                        drained this tick, links in this call, rows in
                        this journal — never by how big the plane is
``O(grid)``             bounded by a fixed search lattice — the
                        autopilot's candidate grid (fixed rungs + a
                        seeded exploration block of configured width)
                        times the tenant's own edges, never plane size
``O(tenants)``          one pass over the tenant registry (drain-policy
                        snapshot) is allowed on top of rows_touched
``O(capacity)``         linear in the SoA — legal only for the paths
                        DOCUMENTED as one linear pass (compact,
                        checkpoint save/load, accounting rebuild)
======================  ================================================

Vectorized numpy work (``np.*`` calls, FreeStack ops) is NOT counted:
the columnar-bookkeeping contract is precisely that linear row work
happens as C-speed array passes, and what the budget polices is
Python-level iteration under the engine/tick locks. Two shapes are
flagged regardless of budget: materializing an O(capacity) Python
collection (``list(range(cap))`` — the columnar structures exist so
this never happens), and per-element scans of the free list
(``row in _free`` / ``_free.remove(row)``), which turn any enclosing
loop quadratic.
"""

from __future__ import annotations

# ---- complexity classes (ordered) -------------------------------------

CLASS_O1 = "O(1)"
CLASS_ROWS = "O(rows_touched)"
CLASS_GRID = "O(grid)"
CLASS_TENANTS = "O(tenants)"
CLASS_CAPACITY = "O(capacity)"
CLASS_SUPER = "O(capacity x N)"   # nested/superlinear — never budgeted

CLASS_ORDER = (CLASS_O1, CLASS_ROWS, CLASS_GRID, CLASS_TENANTS,
               CLASS_CAPACITY, CLASS_SUPER)
CLASS_RANK = {c: i for i, c in enumerate(CLASS_ORDER)}

# ---- bound-classification vocabulary ----------------------------------
# Names (bare or as the final attribute of a dotted chain) whose size
# scales with the SoA / total realized rows. Iterating one of these —
# or a range() over one of the bound names — is an O(capacity) walk.
CAPACITY_BOUNDS = {"capacity", "cap", "new_cap", "old_cap"}
CAPACITY_CONTAINERS = {
    "_free",          # engine free list (FreeStack)
    "_rows",          # (pod_key, uid) -> row registry
    "_row_owner",     # row -> (pod_key, uid)
    "_peer",          # directed-link peer map
    "_row_keyid",     # per-row identity key-id column
    "_shaped_rows",   # shaped-row mirror
    "_pod_ids",       # endpoint name -> node id
    "_pod_names",     # node id -> endpoint name
    "_by_id",         # wire registries
    "_by_key",
    "_objects",       # topology store records
}
# capacity containers with LIST semantics: `x in c` / `c.remove(x)` /
# `c.pop(i)` is a linear scan per call (set/dict membership is O(1)
# and exempt)
CAPACITY_LISTS = {"_free"}
# registry-sized containers: one pass = O(tenants). The fleet layer's
# plane registries (_watch / _handles) and the placement ledger's
# tenant map classify here too — a fleet sweep is one pass over the
# registered planes, a ledger commit one pass over the placements.
TENANT_CONTAINERS = {"_tenants", "_ns_map", "ns_map", "_holds",
                     "_masks", "tenants", "_watch", "_handles",
                     "_placements", "placements", "_cordoned"}
# search-lattice containers: the autopilot's candidate grid and its
# exploration lattice — sized by (fixed rungs + configured width),
# never by the plane. One pass = O(grid).
GRID_CONTAINERS = {"grid", "lattice", "candidates", "ranked"}

# ---- entries ----------------------------------------------------------
# name -> (budget class, ((path, qualname), ...) call-graph roots).
# Unresolvable calls (attr chains through self.daemon / self.tenancy /
# handle.engine ...) are not followed by the closure — the cross-object
# hops each path takes are therefore listed as EXPLICIT roots of the
# entry that reaches them, same discipline as dtnlint's hot-path list.
_RT = "kubedtn_tpu/runtime.py"
_SRV = "kubedtn_tpu/wire/server.py"
_ENG = "kubedtn_tpu/topology/engine.py"
_REG = "kubedtn_tpu/tenancy/registry.py"
_PAR = "kubedtn_tpu/parallel/partition.py"
_STG = "kubedtn_tpu/updates/stager.py"
_CKP = "kubedtn_tpu/checkpoint.py"
_MIG = "kubedtn_tpu/federation/migrate.py"
_SUP = "kubedtn_tpu/federation/supervisor.py"
_PLC = "kubedtn_tpu/federation/placement.py"
_TEL = "kubedtn_tpu/telemetry.py"
_SLO = "kubedtn_tpu/slo/evaluator.py"
_SLF = "kubedtn_tpu/slo/fleet.py"
_APC = "kubedtn_tpu/autopilot/candidates.py"
_APS = "kubedtn_tpu/autopilot/search.py"
_APA = "kubedtn_tpu/autopilot/actuator.py"
_APK = "kubedtn_tpu/autopilot/controller.py"

SCALE_ENTRIES: dict[str, dict] = {
    # the steady data path: host work per tick must scale with the
    # frames drained THIS tick, never with plane size
    "tick": {
        "budget": CLASS_ROWS,
        "roots": (
            (_RT, "WireDataPlane.tick"),
            (_RT, "WireDataPlane._tick_inner"),
            (_RT, "WireDataPlane._dispatch"),
            (_RT, "WireDataPlane._dispatch_inner"),
            (_RT, "WireDataPlane._complete"),
            (_RT, "WireDataPlane._complete_or_requeue"),
            (_RT, "WireDataPlane._release"),
            (_RT, "WireDataPlane._adapt_budget"),
        ),
    },
    "drain_ingress": {
        "budget": CLASS_ROWS,
        "roots": ((_SRV, "Daemon.drain_ingress"),),
    },
    # shm ring drain: one native batch-dequeue + one columnar regroup
    # per attached ring — host work scales with the frames dequeued
    # THIS drain (and the per-drain wire set), never with ring
    # capacity or plane size; the admission check at the ring head is
    # O(1) per ring against the tick's policy snapshot
    "shm_drain": {
        "budget": CLASS_ROWS,
        "roots": (
            ("kubedtn_tpu/shm/ingest.py", "ShmIngest.drain_into"),
            ("kubedtn_tpu/shm/ingest.py", "ShmIngest._emit"),
        ),
    },
    # admission: one registry snapshot per tick, O(1) per wire
    "drain_policy": {
        "budget": CLASS_TENANTS,
        "roots": (
            (_REG, "TenantRegistry.drain_policy"),
            (_REG, "TenantRegistry.charge_drained"),
        ),
    },
    # row allocation/free — the per-link hot path of every realize,
    # delete, adopt and rollback
    "alloc": {
        "budget": CLASS_ROWS,
        "roots": (
            (_ENG, "SimEngine._alloc"),
            (_ENG, "SimEngine._alloc_link_pair"),
            (_ENG, "SimEngine._bind_row"),
            (_ENG, "SimEngine._free_row"),
            (_ENG, "SimEngine._ensure_capacity"),
            (_REG, "TenantRegistry.alloc_row"),
            (_REG, "TenantRegistry.alloc_pair"),
            (_REG, "TenantRegistry.release_row"),
            (_REG, "TenantRegistry.reserved_free"),
            (_REG, "TenantRegistry.note_bind"),
            (_REG, "TenantRegistry.note_unbind"),
            (_PAR, "pick_pair_rows"),
        ),
    },
    "add_links": {
        "budget": CLASS_ROWS,
        "roots": (
            (_ENG, "SimEngine._add_links_locked"),
            (_ENG, "SimEngine.del_links"),
            (_ENG, "SimEngine.update_links"),
            (_ENG, "SimEngine.adopt_rows"),
            (_ENG, "SimEngine.abandon_rows"),
        ),
    },
    # every tick-lock staging barrier body: planned-update rounds,
    # journal capture, rollback replay
    "stage_barrier": {
        "budget": CLASS_ROWS,
        "roots": (
            (_RT, "WireDataPlane.stage_update_round"),
            (_STG, "UpdateStager._apply_round"),
            (_STG, "UpdateStager._capture_images"),
            (_STG, "UpdateStager._endpoints"),
            (_STG, "UpdateStager._rollback"),
            (_STG, "UpdateStager._restore_image_locked"),
        ),
    },
    # the documented linear passes
    "compact": {
        "budget": CLASS_CAPACITY,
        "roots": (
            (_ENG, "SimEngine.compact"),
            (_REG, "TenantRegistry.on_compact"),
            (_PAR, "tenant_blocks"),
            (_RT, "WireDataPlane._on_rows_remapped"),
            (_TEL, "LinkTelemetry.remap_rows"),
        ),
    },
    "checkpoint_save": {
        "budget": CLASS_CAPACITY,
        "roots": (
            (_CKP, "_capture"),
            (_CKP, "_write_captured"),
            (_CKP, "store_records"),
            (_CKP, "save_pending"),
            (_CKP, "save_live"),
        ),
    },
    "checkpoint_load": {
        "budget": CLASS_CAPACITY,
        "roots": (
            (_CKP, "_load_traced"),
            (_CKP, "restore_store"),
            (_CKP, "load_pending"),
            (_CKP, "load_tenancy"),
            (_CKP, "rebuild_engine"),
            (_CKP, "read_pending_entries"),
            (_CKP, "read_ingress_entries"),
            (_CKP, "load_ingress"),
            (_CKP, "load_wires"),
            (_CKP, "restore_plane_counters"),
        ),
    },
    # per-tenant slicing: one vectorized mask read per query, with the
    # namespace-binding rebuild as the documented linear slow path
    "tenant_accounting": {
        "budget": CLASS_CAPACITY,
        "roots": (
            (_REG, "TenantRegistry.rows_of"),
            (_REG, "TenantRegistry._rebuild_masks_locked"),
            (_REG, "TenantRegistry.tenant_counters"),
            (_REG, "TenantRegistry.tenant_window"),
        ),
    },
    # live-migration steps: tenant-scoped, so rows_touched = the
    # migrating tenant's rows/wires — never the whole plane's
    "migration_fork": {
        "budget": CLASS_ROWS,
        "roots": ((_MIG, "MigrationCoordinator._step_fork"),),
    },
    "migration_restore": {
        "budget": CLASS_ROWS,
        "roots": ((_MIG, "MigrationCoordinator._step_restore"),),
    },
    "migration_cutover": {
        "budget": CLASS_ROWS,
        "roots": (
            (_MIG, "MigrationCoordinator._step_cutover"),
            (_MIG, "MigrationCoordinator._wire_pairs"),
            (_MIG, "MigrationCoordinator._transfer"),
            (_SRV, "WireManager.in_namespaces"),
        ),
    },
    # SLO evaluation: one pass per telemetry window rollover — one
    # vectorized ring reduction per burn-window span plus O(tenants)
    # Python arithmetic (mask gather + scalar comparisons per tenant);
    # the censored-tail fit is bounded by the constant bucket ladder
    "slo_evaluate": {
        "budget": CLASS_TENANTS,
        "roots": (
            (_SLO, "SloEvaluator.evaluate"),
            (_SLO, "SloEvaluator.maybe_evaluate"),
            (_SLO, "SloEvaluator._throttle_pressure"),
            (_SLO, "SloEvaluator.verdicts"),
            (_SLO, "SloEvaluator.verdict_payloads"),
            (_SLO, "evaluate_tenant"),
            (_SLO, "_burns"),
        ),
    },
    # fleet SLO merge: one pass over the registered planes' verdict
    # payloads + the journal's frozen slices, one exact histogram sum
    # per tenant — O(planes·tenants), both registry-sized
    "fleet_slo_merge": {
        "budget": CLASS_TENANTS,
        "roots": (
            (_SUP, "FleetSupervisor.fleet_slo"),
            (_SUP, "FleetSupervisor.last_fleet_slo"),
            (_SLF, "fleet_slo"),
            (_SLF, "merge_tenant"),
            (_SLF, "merge_hists"),
            (_SLF, "from_verdict"),
            (_SLF, "from_frozen_window"),
            (_SLF, "contribution"),
            (_SLF, "_row_of"),
            (_MIG, "FederationController.frozen_windows"),
        ),
    },
    # fleet supervision: one probe + state-machine step per registered
    # plane per sweep — a registry-sized pass, never capacity work
    "fleet_sweep": {
        "budget": CLASS_TENANTS,
        "roots": (
            (_SUP, "FleetSupervisor.sweep"),
            (_SUP, "FleetSupervisor.probe"),
            (_SUP, "FleetSupervisor._observe"),
            (_SUP, "FleetSupervisor.status"),
            (_SUP, "FleetSupervisor._live_candidates"),
            (_SRV, "Daemon.health_snapshot"),
        ),
    },
    # placement ledger: O(1) in-memory ops plus ONE registry-sized
    # record serialization per committed mutation
    "placement_ledger": {
        "budget": CLASS_TENANTS,
        "roots": (
            (_PLC, "PlacementLedger.assign"),
            (_PLC, "PlacementLedger.remove"),
            (_PLC, "PlacementLedger.cordon"),
            (_PLC, "PlacementLedger.uncordon"),
            (_PLC, "PlacementLedger._commit_locked"),
            (_PLC, "plane_score"),
            (_PLC, "pressure_of"),
            (_PLC, "choose_plane"),
        ),
    },
    # autopilot search: grid generation and scoring walk the candidate
    # lattice (fixed rungs + seeded width) times the tenant's OWN
    # edges — O(grid), never O(capacity); the heavy per-replica work
    # is the one batched twin sweep, which is device-side
    "autopilot_candidates": {
        "budget": CLASS_GRID,
        "roots": (
            (_APC, "candidate_grid"),
            (_APC, "_shape"),
            (_APC, "_scaled_props"),
            (_APC, "_loss_of"),
            (_APS, "score_candidates"),
            (_APS, "_telemetry_row"),
            (_APS, "_projected"),
        ),
    },
    # autopilot control loop: one verdict read per poll (O(tenants),
    # the SloEvaluator surface) plus per-tenant state-machine steps;
    # actuation is per-plan work over the tenant's own topologies
    "autopilot_poll": {
        "budget": CLASS_TENANTS,
        "roots": (
            (_APK, "Autopilot.poll"),
            (_APK, "Autopilot._verify_step"),
            (_APK, "Autopilot._maybe_escalate"),
            (_APK, "Autopilot._remediate"),
            (_APK, "Autopilot._edge_props"),
            (_APK, "Autopilot.status"),
            (_APA, "actuate"),
            (_APA, "_actuate_admission"),
            (_APA, "_shape_plans"),
            (_APA, "_tenant_topologies"),
            (_APA, "_copy_back_status"),
        ),
    },
    # the restore half of an evacuation is tenant-scoped: rows_touched
    # = the evacuated tenant's rows/wires, like the migration steps
    "evacuation_restore": {
        "budget": CLASS_ROWS,
        "roots": (
            (_MIG, "restore_tenant_slice"),
            (_MIG, "_restore_slice_locked"),
            (_MIG, "discard_partial_restore"),
        ),
    },
    # the slicing half reads a dead plane's checkpoint — a documented
    # cold linear pass, budgeted like checkpoint_load
    "evacuation": {
        "budget": CLASS_CAPACITY,
        "roots": (
            (_SUP, "FleetSupervisor.evacuate"),
            (_SUP, "FleetSupervisor._resolve_migrations"),
            (_SUP, "FleetSupervisor.resume_orphans"),
            (_SUP, "FleetSupervisor.check_failover_accounting"),
            (_SUP, "fork_from_checkpoint"),
            (_SUP, "_counters_summary"),
        ),
    },
}

# empirical probe phases -> default max fitted log-log slope. The
# capacity-independent phases get a near-flat ceiling (constant
# overhead dominates at probe sizes, so honest slopes sit near 0);
# the documented linear passes get a generous ≤ ~1.35 (compression,
# allocator noise). Re-baselined by --update-budgets (measured+margin,
# never below the default).
PROBE_DEFAULT_SLOPES: dict[str, float] = {
    "alloc_churn": 0.35,
    "drain_policy": 0.35,
    "stage_barrier": 0.35,
    "compact": 1.35,
    "checkpoint_save": 1.35,
}
