"""SCALE_BUDGET.json — the checked-in host-complexity budgets.

Layout::

    {
      "classes": [...],                  # the ordered class ladder
      "entries": {"tick": "O(rows_touched)", ...},
      "probe": {
        "sizes": [2048, 8192, 32768],    # default CLI probe sizes
        "max_slope": {"compact": 1.35, ...}
      }
    }

`check_budget` compares the static pass's configuration against the
file (a configured entry with no budget record is itself a finding —
new scale-critical paths must be budgeted deliberately, same rule as
COST_BUDGET.json) and hands the per-entry budget classes to the
bounds pass. `write_budget` (--update-budgets) re-baselines: entries
get their configured defaults where missing (an EXISTING budget is
kept — tightening or loosening a class is a reviewed hand edit, not
a mechanical refresh), and probe slope ceilings become
measured + margin, never below the configured defaults.
"""

from __future__ import annotations

import json
from pathlib import Path

from kubedtn_tpu.analysis.core import RULE_SCOST, Finding
from kubedtn_tpu.analysis.scale.entrypoints import (
    CLASS_ORDER,
    PROBE_DEFAULT_SLOPES,
    SCALE_ENTRIES,
)

BUDGET_FILE = "SCALE_BUDGET.json"
_SLOPE_MARGIN = 0.25


def load_budget(root: Path) -> dict | None:
    p = root / BUDGET_FILE
    if not p.exists():
        return None
    try:
        return json.loads(p.read_text())
    except (OSError, ValueError):
        return None


def budget_classes(doc: dict | None) -> dict[str, str]:
    """entry -> budget class, file values over configured defaults."""
    out = {name: spec["budget"] for name, spec in SCALE_ENTRIES.items()}
    for name, cls in ((doc or {}).get("entries") or {}).items():
        if cls in CLASS_ORDER:
            out[name] = cls
    return out


def probe_slopes(doc: dict | None) -> dict[str, float]:
    out = dict(PROBE_DEFAULT_SLOPES)
    for phase, v in (((doc or {}).get("probe") or {})
                     .get("max_slope") or {}).items():
        try:
            out[phase] = float(v)
        except (TypeError, ValueError):
            pass
    return out


def probe_sizes(doc: dict | None) -> list[int]:
    sizes = ((doc or {}).get("probe") or {}).get("sizes")
    if isinstance(sizes, list) and sizes:
        return [int(s) for s in sizes]
    return [2048, 8192, 32768]


def check_budget(root: Path, findings: list[Finding]) -> dict:
    """Gate the budget file itself: missing file / unbudgeted entries
    are findings (a scale-critical path nobody budgeted is exactly
    the drift this layer exists to catch)."""
    doc = load_budget(root)
    if doc is None:
        findings.append(Finding(
            RULE_SCOST, BUDGET_FILE, 1,
            "SCALE_BUDGET.json missing or unreadable — run "
            "`python -m kubedtn_tpu.analysis --scale "
            "--update-budgets` to baseline it"))
        return {"file": BUDGET_FILE, "present": False}
    recorded = set((doc.get("entries") or {}))
    missing = sorted(set(SCALE_ENTRIES) - recorded)
    for name in missing:
        findings.append(Finding(
            RULE_SCOST, BUDGET_FILE, 1,
            f"entry `{name}` has no budget record — new "
            f"scale-critical paths must be budgeted deliberately "
            f"(--update-budgets adds the configured default)"))
    stale = sorted(recorded - set(SCALE_ENTRIES))
    return {"file": BUDGET_FILE, "present": True,
            "missing_entries": missing, "stale_entries": stale}


def write_budget(root: Path, measured_slopes: dict[str, float] | None
                 ) -> dict:
    """--update-budgets: rewrite SCALE_BUDGET.json. Existing entry
    classes are KEPT; missing entries get their configured defaults;
    probe ceilings become max(default, measured + margin)."""
    old = load_budget(root) or {}
    entries = {name: spec["budget"]
               for name, spec in SCALE_ENTRIES.items()}
    for name, cls in (old.get("entries") or {}).items():
        if name in entries and cls in CLASS_ORDER:
            entries[name] = cls
    slopes = dict(PROBE_DEFAULT_SLOPES)
    for phase, v in (measured_slopes or {}).items():
        if phase in slopes:
            slopes[phase] = round(
                max(slopes[phase], float(v) + _SLOPE_MARGIN), 2)
    doc = {
        "comment": (
            "dtnscale host-complexity budgets (see "
            "ARCHITECTURE.md 'Host scalability contract'). "
            "`entries` pins each scale-critical entry point's "
            "allowed Python-level bound class; `probe.max_slope` "
            "ceilings the empirical log-log wall-time slopes the "
            "scaling probe fits. Checked by `python -m "
            "kubedtn_tpu.analysis --scale` (tier-1) and re-baselined "
            "by --update-budgets."),
        "classes": list(CLASS_ORDER),
        "entries": dict(sorted(entries.items())),
        "probe": {
            "sizes": probe_sizes(old),
            "max_slope": dict(sorted(slopes.items())),
        },
    }
    (root / BUDGET_FILE).write_text(json.dumps(doc, indent=2) + "\n")
    return doc
