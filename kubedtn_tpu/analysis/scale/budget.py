"""SCALE_BUDGET.json — the checked-in host-complexity budgets.

Layout::

    {
      "classes": [...],                  # the ordered class ladder
      "entries": {"tick": "O(rows_touched)", ...},
      "probe": {
        "sizes": [2048, 8192, 32768],    # default CLI probe sizes
        "max_slope": {"compact": 1.35, ...}
      },
      "availability": {                  # savail barrier-pause budgets
        "max_share": {"checkpoint_save": 0.15, ...},
        "max_single_pause_s": {"compact": 1.0, ...},
        "hook_overhead_pct": 2.0
      }
    }

`check_budget` compares the static pass's configuration against the
file (a configured entry with no budget record is itself a finding —
new scale-critical paths must be budgeted deliberately, same rule as
COST_BUDGET.json) and hands the per-entry budget classes to the
bounds pass. `write_budget` (--update-budgets) re-baselines: entries
get their configured defaults where missing (an EXISTING budget is
kept — tightening or loosening a class is a reviewed hand edit, not
a mechanical refresh), and probe slope ceilings become
measured + margin, never below the configured defaults.
"""

from __future__ import annotations

import json
from pathlib import Path

from kubedtn_tpu.analysis.core import RULE_SCOST, Finding
from kubedtn_tpu.analysis.scale.entrypoints import (
    CLASS_ORDER,
    PROBE_DEFAULT_SLOPES,
    SCALE_ENTRIES,
)

BUDGET_FILE = "SCALE_BUDGET.json"
_SLOPE_MARGIN = 0.25

# availability (savail) configured defaults — ceilings on each pause
# cause's share of bench wall clock and on any single pause, plus the
# ledger's own instrumentation overhead. Generous on purpose: the
# budget exists to catch a cause REGRESSING (a checkpoint that starts
# eating half the window), not to flag the forced-barrier bench shape
# itself. jit_compile is the outlier — a cold XLA compile is seconds
# by design and only its recurrence (retrace churn) is pathological.
AVAIL_DEFAULT_MAX_SHARE = {
    "checkpoint_save": 0.15, "checkpoint_load": 0.15,
    "compact": 0.10, "staged_update": 0.15,
    "migration_fork": 0.10, "migration_restore": 0.10,
    "migration_cutover": 0.05, "pipeline_flush": 0.10,
    "shm_stall": 0.05, "jit_compile": 0.50, "gc": 0.05,
}
AVAIL_DEFAULT_MAX_SINGLE_S = {
    "checkpoint_save": 2.0, "checkpoint_load": 2.0,
    "compact": 1.0, "staged_update": 2.0,
    "migration_fork": 2.0, "migration_restore": 2.0,
    "migration_cutover": 1.0, "pipeline_flush": 1.0,
    "shm_stall": 0.5, "jit_compile": 30.0, "gc": 0.5,
}
AVAIL_DEFAULT_HOOK_PCT = 2.0


def load_budget(root: Path) -> dict | None:
    p = root / BUDGET_FILE
    if not p.exists():
        return None
    try:
        return json.loads(p.read_text())
    except (OSError, ValueError):
        return None


def budget_classes(doc: dict | None) -> dict[str, str]:
    """entry -> budget class, file values over configured defaults."""
    out = {name: spec["budget"] for name, spec in SCALE_ENTRIES.items()}
    for name, cls in ((doc or {}).get("entries") or {}).items():
        if cls in CLASS_ORDER:
            out[name] = cls
    return out


def probe_slopes(doc: dict | None) -> dict[str, float]:
    out = dict(PROBE_DEFAULT_SLOPES)
    for phase, v in (((doc or {}).get("probe") or {})
                     .get("max_slope") or {}).items():
        try:
            out[phase] = float(v)
        except (TypeError, ValueError):
            pass
    return out


def availability(doc: dict | None) -> dict:
    """The `availability` section — barrier-pause budgets checked by
    the savail rule against the banked BENCH_pauses.json record.
    Missing/garbled sections degrade to the configured defaults so a
    pre-PR-20 budget file still gates the headline ceilings."""
    out = {
        "max_share": dict(AVAIL_DEFAULT_MAX_SHARE),
        "max_single_pause_s": dict(AVAIL_DEFAULT_MAX_SINGLE_S),
        "hook_overhead_pct": AVAIL_DEFAULT_HOOK_PCT,
    }
    sec = (doc or {}).get("availability") or {}
    for key in ("max_share", "max_single_pause_s"):
        for cause, v in (sec.get(key) or {}).items():
            try:
                out[key][cause] = float(v)
            except (TypeError, ValueError):
                pass
    try:
        out["hook_overhead_pct"] = float(
            sec.get("hook_overhead_pct", out["hook_overhead_pct"]))
    except (TypeError, ValueError):
        pass
    return out


def probe_sizes(doc: dict | None) -> list[int]:
    sizes = ((doc or {}).get("probe") or {}).get("sizes")
    if isinstance(sizes, list) and sizes:
        return [int(s) for s in sizes]
    return [2048, 8192, 32768]


def check_budget(root: Path, findings: list[Finding]) -> dict:
    """Gate the budget file itself: missing file / unbudgeted entries
    are findings (a scale-critical path nobody budgeted is exactly
    the drift this layer exists to catch)."""
    doc = load_budget(root)
    if doc is None:
        findings.append(Finding(
            RULE_SCOST, BUDGET_FILE, 1,
            "SCALE_BUDGET.json missing or unreadable — run "
            "`python -m kubedtn_tpu.analysis --scale "
            "--update-budgets` to baseline it"))
        return {"file": BUDGET_FILE, "present": False}
    recorded = set((doc.get("entries") or {}))
    missing = sorted(set(SCALE_ENTRIES) - recorded)
    for name in missing:
        findings.append(Finding(
            RULE_SCOST, BUDGET_FILE, 1,
            f"entry `{name}` has no budget record — new "
            f"scale-critical paths must be budgeted deliberately "
            f"(--update-budgets adds the configured default)"))
    stale = sorted(recorded - set(SCALE_ENTRIES))
    return {"file": BUDGET_FILE, "present": True,
            "missing_entries": missing, "stale_entries": stale}


def write_budget(root: Path, measured_slopes: dict[str, float] | None
                 ) -> dict:
    """--update-budgets: rewrite SCALE_BUDGET.json. Existing entry
    classes are KEPT; missing entries get their configured defaults;
    probe ceilings become max(default, measured + margin)."""
    old = load_budget(root) or {}
    entries = {name: spec["budget"]
               for name, spec in SCALE_ENTRIES.items()}
    for name, cls in (old.get("entries") or {}).items():
        if name in entries and cls in CLASS_ORDER:
            entries[name] = cls
    slopes = dict(PROBE_DEFAULT_SLOPES)
    for phase, v in (measured_slopes or {}).items():
        if phase in slopes:
            slopes[phase] = round(
                max(slopes[phase], float(v) + _SLOPE_MARGIN), 2)
    # availability ceilings are reviewed hand edits like entry classes:
    # keep whatever the old file pinned, fill configured defaults in
    avail = availability(old)
    doc = {
        "comment": (
            "dtnscale host-complexity budgets (see "
            "ARCHITECTURE.md 'Host scalability contract'). "
            "`entries` pins each scale-critical entry point's "
            "allowed Python-level bound class; `probe.max_slope` "
            "ceilings the empirical log-log wall-time slopes the "
            "scaling probe fits; `availability` ceilings each "
            "barrier-pause cause's share of bench wall clock and "
            "worst single pause against the banked "
            "BENCH_pauses.json (savail rule). Checked by `python -m "
            "kubedtn_tpu.analysis --scale` (tier-1) and re-baselined "
            "by --update-budgets."),
        "classes": list(CLASS_ORDER),
        "entries": dict(sorted(entries.items())),
        "probe": {
            "sizes": probe_sizes(old),
            "max_slope": dict(sorted(slopes.items())),
        },
        "availability": {
            "max_share": dict(sorted(avail["max_share"].items())),
            "max_single_pause_s": dict(
                sorted(avail["max_single_pause_s"].items())),
            "hook_overhead_pct": avail["hook_overhead_pct"],
        },
    }
    (root / BUDGET_FILE).write_text(json.dumps(doc, indent=2) + "\n")
    return doc
