"""dtnlint core: source model, waiver parsing, findings, project scan.

Every pass consumes a ``Project`` (the parsed package tree) and emits
``Finding``s. A finding is *waived* when the offending line — or the
``def``/``class`` header line of any enclosing scope — carries a
``# dtnlint: <rule>-ok(<reason>)`` comment for the finding's rule. The
reason is mandatory: a waiver without one does not parse, and the JSON
artifact carries every reason so reviewers can audit waiver honesty.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Iterable, Iterator

# rule tags (the `<tag>-ok(...)` waiver vocabulary)
RULE_PURITY = "purity"
RULE_KEY = "key"
RULE_SYNC = "sync"
RULE_LOCK = "lock"
RULE_DTYPE = "dtype"
RULE_HYGIENE = "hygiene"
# meta-rule: a waiver comment whose line no longer triggers its rule
# (dead waivers rot the audit trail — the reason reads as if it
# justifies something, but nothing is being justified)
RULE_WAIVER = "waiver"
ALL_RULES = (RULE_PURITY, RULE_KEY, RULE_SYNC, RULE_LOCK, RULE_DTYPE,
             RULE_HYGIENE, RULE_WAIVER)
# the dtnverify (jaxpr-layer) rule tags. These are deliberately NOT
# waivable: a jaxpr finding means a compiled program breaks a
# byte-identity/fusion contract, and the sanctioned overrides are the
# vetted allowlist or --update-budgets. A `<tag>-ok(...)` comment for
# one of these is dead by construction — stale_waivers names it as
# such instead of pretending the rule merely stopped firing.
JAXPR_RULES = ("jops", "jkey", "jdtype", "jshard", "jtenant", "jcost")
# the dtnscale (host-asymptotics layer) rule tag: Python-level host
# complexity on the scale-critical entry points against
# SCALE_BUDGET.json. Waivable like the AST rules (`scost-ok(reason)`)
# — the designated slow paths are part of the contract and the reason
# lands in the artifact for audit — but the tree policy is FIX, not
# waive (PR 12 fixed every active finding instead of waivering it).
RULE_SCOST = "scost"
# the dtnscale availability rule: barrier-pause budgets (pause-seconds
# share of wall clock, single-pause ceilings, ledger hook overhead)
# checked against the banked BENCH_pauses.json record. Artifact-level
# like the probe slope gate — there is no source line to waive, the
# sanctioned overrides are the SCALE_BUDGET.json `availability`
# section's hand-edited ceilings.
RULE_SAVAIL = "savail"
SCALE_RULES = (RULE_SCOST, RULE_SAVAIL)

# the ANALYSIS.json artifact schema. v1: flat dtnlint findings doc
# (PRs 6-7). v2: adds `schema_version` and the dtnverify `jaxpr`
# section. v3: adds the dtnscale `scale` section (scost findings +
# budgets + empirical probe); the AST layer keeps its v1 top-level
# keys so v1 consumers (and `--diff` against old artifacts) keep
# working, and a writer that ran only some layers preserves the
# others' sections.
SCHEMA_VERSION = 3

# the reason may itself contain parens (`tick() re-reads...`): match
# lazily but only stop at a ')' followed by end-of-line, another
# comment, or another waiver — not at the first ')' inside the reason
_WAIVER_RE = re.compile(
    r"#\s*dtnlint:\s*([a-z]+)-ok\((.+?)\)(?=\s*(?:#|dtnlint:|$))")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str            # repo-relative, forward slashes
    line: int
    message: str
    waived: bool = False
    waiver_reason: str | None = None

    def format(self) -> str:
        tail = (f"  [waived: {self.waiver_reason}]"
                if self.waived else "")
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tail}"

    def to_json(self) -> dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "waived": self.waived,
            "waiver_reason": self.waiver_reason,
        }


class SourceFile:
    """One parsed module: source text, AST, waiver map, scope spans."""

    def __init__(self, root: Path, path: Path) -> None:
        self.abspath = path
        self.rel = path.relative_to(root).as_posix()
        self.module = self.rel[:-3].replace("/", ".")
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(path))
        # line -> {rule tag: reason}
        self.waivers: dict[int, dict[str, str]] = {}
        for i, ln in enumerate(self.lines, 1):
            if "dtnlint" not in ln:
                continue
            for m in _WAIVER_RE.finditer(ln):
                self.waivers.setdefault(i, {})[m.group(1)] = \
                    m.group(2).strip()
        # enclosing-scope spans for def-level waivers: (start, end, header)
        self._scopes: list[tuple[int, int, int]] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                end = node.end_lineno or node.lineno
                self._scopes.append((node.lineno, end, node.lineno))

    def waiver_for(self, rule: str, line: int) -> str | None:
        """The waiver reason covering (rule, line), if any: the line
        itself, the line above it (comment-on-its-own-line style), or
        any enclosing def/class header line."""
        m = self.waiver_match(rule, line)
        return m[1] if m is not None else None

    def waiver_match(self, rule: str, line: int
                     ) -> tuple[int, str] | None:
        """Like `waiver_for`, but returns (waiver_line, reason) so
        callers can track WHICH waiver comment fired — the stale-waiver
        meta-rule reports the ones that never do."""
        for cand in (line, line - 1):
            reason = self.waivers.get(cand, {}).get(rule)
            if reason is not None and (cand == line
                                       or self._is_comment_line(cand)):
                return cand, reason
        for start, end, header in self._scopes:
            if start <= line <= end:
                for cand in (header, header - 1):
                    reason = self.waivers.get(cand, {}).get(rule)
                    if reason is not None and (
                            cand == header
                            or self._is_comment_line(cand)):
                        return cand, reason
        return None

    def _is_comment_line(self, line: int) -> bool:
        if not 1 <= line <= len(self.lines):
            return False
        return self.lines[line - 1].lstrip().startswith("#")


class Project:
    """The analyzed tree: every ``*.py`` under the package roots."""

    def __init__(self, root: Path, packages: Iterable[str] = ("kubedtn_tpu",),
                 exclude: Iterable[str] = ()) -> None:
        self.root = root
        self.files: dict[str, SourceFile] = {}
        excl = tuple(exclude)
        for pkg in packages:
            base = root / pkg
            paths = (sorted(base.rglob("*.py")) if base.is_dir()
                     else [base] if base.is_file() else [])
            for p in paths:
                rel = p.relative_to(root).as_posix()
                if any(rel.startswith(e) for e in excl):
                    continue
                self.files[rel] = SourceFile(root, p)

    def __iter__(self) -> Iterator[SourceFile]:
        return iter(self.files.values())

    def by_module(self, module: str) -> SourceFile | None:
        for f in self.files.values():
            if f.module == module or f.module == module + ".__init__":
                return f
        return None


def apply_waivers(project: Project, findings: list[Finding],
                  used: set | None = None) -> list[Finding]:
    """Mark each finding waived when its file carries a matching
    ``<rule>-ok(reason)`` waiver in scope. `used` (when given)
    collects the ``(path, waiver_line, rule)`` triples that actually
    fired, for stale-waiver detection."""
    for f in findings:
        src = project.files.get(f.path)
        if src is None:
            continue
        m = src.waiver_match(f.rule, f.line)
        if m is not None:
            f.waived = True
            f.waiver_reason = m[1]
            if used is not None:
                used.add((f.path, m[0], f.rule))
    return findings


def stale_waivers(project: Project, used: set,
                  skip_rules: Iterable[str] = ()) -> list[Finding]:
    """The waiver meta-rule: every ``<rule>-ok(reason)`` comment that
    matched NO finding is itself a finding — the rule stopped
    triggering (code moved, bug fixed, rule refined) and the dead
    waiver now documents a justification for nothing. Only meaningful
    after a FULL pass run: a subset run would see every other rule's
    waivers as stale. `skip_rules` names rules that did NOT run this
    invocation (e.g. ``scost`` when the dtnscale layer was off) —
    their waivers cannot be judged and are left alone."""
    skip = set(skip_rules)
    out: list[Finding] = []
    for src in project:
        for line, rules in sorted(src.waivers.items()):
            for rule, reason in sorted(rules.items()):
                if rule == RULE_WAIVER:
                    continue  # waiving stale-waiver reports is circular
                if rule in skip:
                    continue  # layer not run: staleness unjudgeable
                if (src.rel, line, rule) in used:
                    continue
                if rule in JAXPR_RULES:
                    out.append(Finding(
                        RULE_WAIVER, src.rel, line,
                        f"waiver `{rule}-ok({reason})` targets a "
                        f"jaxpr-layer rule — dtnverify findings are "
                        f"not waivable; fix the program, extend the "
                        f"vetted allowlist, or re-baseline with "
                        f"--update-budgets"))
                else:
                    out.append(Finding(
                        RULE_WAIVER, src.rel, line,
                        f"stale waiver `{rule}-ok({reason})` — no "
                        f"`{rule}` finding triggers here anymore; "
                        f"drop the comment (dead waivers rot the "
                        f"audit trail)"))
    return out


def summarize(findings: list[Finding]) -> dict[str, object]:
    counts: dict[str, int] = {}
    waived = 0
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
        waived += int(f.waived)
    return {
        "total": len(findings),
        "waived": waived,
        "unwaivered": len(findings) - waived,
        "by_rule": dict(sorted(counts.items())),
    }


def write_json(path: Path, findings: list[Finding], root: Path,
               jaxpr: dict | None = None,
               scale: dict | None = None) -> None:
    """The machine-readable artifact (ANALYSIS.json, schema v3):
    stable ordering, no timestamps — diffs track the findings-count
    trajectory. The AST layer keeps the v1 top-level keys; the
    dtnverify layer lands in the `jaxpr` section and the dtnscale
    layer in the `scale` section. A writer that ran only some layers
    PRESERVES the other layers' existing sections, so the artifact
    stays complete whichever gate wrote last."""
    findings = sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    if (jaxpr is None or scale is None) and path.exists():
        try:
            old = json.loads(path.read_text())
        except (OSError, ValueError):
            old = {}
        if jaxpr is None:
            jaxpr = old.get("jaxpr")
        if scale is None:
            scale = old.get("scale")
    doc = {
        "tool": "dtnlint",
        "schema_version": SCHEMA_VERSION,
        "root": root.name,
        "summary": summarize(findings),
        "findings": [f.to_json() for f in findings],
    }
    if jaxpr is not None:
        doc["jaxpr"] = dict(jaxpr)
    if scale is not None:
        doc["scale"] = dict(scale)
    path.write_text(json.dumps(doc, indent=2) + "\n")


# ---- shared AST helpers ------------------------------------------------

def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> str | None:
    return dotted(node.func)


def iter_functions(
        tree: ast.AST) -> Iterator[tuple[str, ast.FunctionDef]]:
    """(qualname, node) for every function/method, including nested
    ones (qualified parent.<locals>.child, matching CPython)."""

    def walk(node: ast.AST, prefix: str) -> Iterator[
            tuple[str, ast.FunctionDef]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child
                yield from walk(child, f"{q}.<locals>.")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")


def local_bindings(fn: ast.FunctionDef) -> set[str]:
    """Names bound inside `fn` proper (params, assignments, loop/with
    targets, comprehension targets, nested defs) — NOT those of nested
    functions, whose bodies have their own scope."""
    bound: set[str] = set()
    args = fn.args
    for a in (*args.posonlyargs, *args.args, *args.kwonlyargs):
        bound.add(a.arg)
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)

    def collect_target(t: ast.AST) -> None:
        for n in ast.walk(t):
            if isinstance(n, ast.Name) and isinstance(
                    n.ctx, (ast.Store, ast.Del)):
                bound.add(n.id)

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(child.name)
                continue  # separate scope
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                  ast.For, ast.AsyncFor)):
                tgt = (child.targets if isinstance(child, ast.Assign)
                       else [child.target])
                for t in tgt:
                    collect_target(t)
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    if item.optional_vars is not None:
                        collect_target(item.optional_vars)
            if isinstance(child, (ast.ListComp, ast.SetComp, ast.DictComp,
                                  ast.GeneratorExp)):
                # comprehension targets live in their own scope in py3,
                # but treating them as local is the safe direction here
                for gen in child.generators:
                    collect_target(gen.target)
            if isinstance(child, ast.ExceptHandler) and child.name:
                bound.add(child.name)
            if isinstance(child, (ast.Import, ast.ImportFrom)):
                for al in child.names:
                    bound.add((al.asname or al.name).split(".")[0])
            walk(child)

    walk(fn)
    return bound
