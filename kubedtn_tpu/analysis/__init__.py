"""dtnlint — contract-checking static analysis for the kubedtn-tpu
invariants.

Five review rounds per PR kept re-finding violations of the same four
contracts by hand; this package encodes them as machine-checkable
passes (plus a hygiene floor), run as ``python -m kubedtn_tpu.analysis``
and in tier-1 via ``tests/test_static_analysis.py``:

========  ==============================================================
rule      contract (waiver tag is ``<rule>-ok(reason)``)
========  ==============================================================
purity    no host effects (time/random/print/closure mutation) inside
          jit/vmap/scan/shard_map-traced code
key       every PRNG sample consumes a fresh split/fold_in product; no
          key feeds two samplers (the PR 3 vmap-drift class)
sync      no implicit device→host syncs (np.asarray/.item()/float()/
          bool coercion) on the fused-tick/dispatch/complete hot paths
lock      ``@guarded_by`` attributes only under ``with self.<lock>``
          (static) + InstrumentedLock order-cycle detection (runtime)
dtype     f32 casts on f64 clock anchors, f64 leaks into the f32 SoA
          (the PR 3 ``clock_us`` freeze class)
hygiene   unused imports, bare excepts, import-group order (the ruff
          subset enforced even without ruff)
========  ==============================================================
"""

from __future__ import annotations

from pathlib import Path

from kubedtn_tpu.analysis.callgraph import CallGraph
from kubedtn_tpu.analysis.core import (
    ALL_RULES,
    Finding,
    Project,
    apply_waivers,
    stale_waivers,
    summarize,
    write_json,
)
from kubedtn_tpu.analysis.passes import PASSES

__all__ = ["ALL_RULES", "Finding", "Project", "CallGraph", "PASSES",
           "run_suite", "summarize", "write_json", "default_root"]


def default_root() -> Path:
    """The repo root (parent of the ``kubedtn_tpu`` package)."""
    return Path(__file__).resolve().parent.parent.parent


def run_suite(root: Path | None = None,
              rules: tuple[str, ...] | None = None,
              packages: tuple[str, ...] = ("kubedtn_tpu",),
              scale: dict | None = None,
              ) -> tuple[Project, list[Finding]]:
    """Parse the tree, run the selected passes, apply waivers. A full
    run (rules=None) additionally reports STALE waivers — `<rule>-ok`
    comments no finding matches anymore; a subset run cannot judge
    staleness (the un-run rules' waivers would all look dead).

    `scale`: pass a dict to ALSO run the dtnscale static half (the
    host-asymptotics bounds pass over the scale-critical entry
    closures, budgets from SCALE_BUDGET.json) — scost findings join
    the result (sharing the waiver and stale-waiver machinery) and
    the dict is filled with the per-entry report + budget status.
    When the scale layer is off, `scost-ok` waivers are exempt from
    staleness (the rule didn't run, so it cannot be judged dead)."""
    root = root if root is not None else default_root()
    project = Project(root, packages=packages)
    graph = CallGraph(project)
    findings: list[Finding] = []
    for rule in (rules if rules is not None else tuple(PASSES)):
        findings.extend(PASSES[rule](project, graph))
    if scale is not None:
        from kubedtn_tpu.analysis.scale import budget as _sbudget
        from kubedtn_tpu.analysis.scale.bounds import run_scale_pass

        bdoc = _sbudget.load_budget(root)
        scost, entry_report = run_scale_pass(
            project, graph, budgets=_sbudget.budget_classes(bdoc))
        scale["entries"] = entry_report
        scale["budget"] = _sbudget.check_budget(root, scost)
        findings.extend(scost)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    used: set = set()
    findings = apply_waivers(project, findings, used=used)
    if rules is None:
        skip = () if scale is not None else ("scost",)
        findings.extend(stale_waivers(project, used, skip_rules=skip))
        findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return project, findings
