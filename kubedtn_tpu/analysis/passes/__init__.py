"""dtnlint pass registry: rule tag → pass module (each exposes
``run(project, graph) -> list[Finding]``)."""

from __future__ import annotations

from kubedtn_tpu.analysis.passes import (
    dtype_drift,
    host_sync,
    hygiene,
    key_discipline,
    lock_discipline,
    traced_purity,
)

PASSES = {
    "purity": traced_purity.run,
    "key": key_discipline.run,
    "sync": host_sync.run,
    "lock": lock_discipline.run,
    "dtype": dtype_drift.run,
    "hygiene": hygiene.run,
}
