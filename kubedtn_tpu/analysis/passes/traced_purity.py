"""Pass 1 — traced-purity: no host-side effects inside traced code.

A function that runs under ``jax.jit`` / ``vmap`` / ``lax.scan`` /
``shard_map`` executes its Python body once, at trace time; host
effects inside it (wall-clock reads, host RNG draws, prints, mutation
of closed-over containers) either bake a trace-time value into the
compiled program or fire on a schedule unrelated to execution — both
silently break the byte-identical-delivery contract. This pass walks
the call-graph closure of every traced entry point and flags the
banned effects. Waiver: ``# dtnlint: purity-ok(reason)``.
"""

from __future__ import annotations

import ast

from kubedtn_tpu.analysis.callgraph import CallGraph
from kubedtn_tpu.analysis.core import (
    RULE_PURITY,
    Finding,
    Project,
    call_name,
    local_bindings,
)

# dotted-prefix -> human reason
_BANNED_PREFIXES = {
    "time.": "wall-clock read bakes a trace-time constant",
    "random.": "host RNG draws once at trace time",
    "np.random.": "host RNG draws once at trace time",
    "numpy.random.": "host RNG draws once at trace time",
    "os.urandom": "host RNG draws once at trace time",
}
_BANNED_CALLS = {
    "print": "host I/O inside a traced function",
    "open": "host I/O inside a traced function",
    "input": "host I/O inside a traced function",
}
_MUTATORS = {"append", "extend", "insert", "update", "setdefault",
             "pop", "popleft", "appendleft", "clear", "remove",
             "add", "discard"}


def run(project: Project, graph: CallGraph) -> list[Finding]:
    findings: list[Finding] = []
    traced = graph.closure(graph.traced_roots())
    for ref in sorted(traced, key=lambda r: (r.path, r.qual)):
        src = project.files[ref.path]
        fn = graph.functions[ref]
        local = local_bindings(fn)
        for node in _own_nodes(fn):
            if isinstance(node, ast.Call):
                cn = call_name(node)
                if cn is None:
                    continue
                reason = _BANNED_CALLS.get(cn)
                if reason is None:
                    for pref, why in _BANNED_PREFIXES.items():
                        if cn == pref.rstrip(".") or cn.startswith(pref):
                            reason = why
                            break
                if reason is not None:
                    findings.append(Finding(
                        RULE_PURITY, ref.path, node.lineno,
                        f"`{cn}` in traced `{ref.qual}`: {reason}"))
                    continue
                # mutation of a closed-over / global container
                f = node.func
                if isinstance(f, ast.Attribute) and \
                        f.attr in _MUTATORS and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id not in local:
                    findings.append(Finding(
                        RULE_PURITY, ref.path, node.lineno,
                        f"`{f.value.id}.{f.attr}(...)` mutates a "
                        f"closed-over container inside traced "
                        f"`{ref.qual}` — effects fire at trace time, "
                        f"not per step"))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id not in local:
                        findings.append(Finding(
                            RULE_PURITY, ref.path, node.lineno,
                            f"subscript store into closed-over "
                            f"`{t.value.id}` inside traced "
                            f"`{ref.qual}` — mutation happens at "
                            f"trace time"))
    return findings


def _own_nodes(fn: ast.FunctionDef):
    """Walk `fn` without descending into nested defs (those are traced
    scopes of their own and get their own findings)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
