"""Pass 4 — lock-discipline: guarded attributes stay under their lock.

Classes declare ownership with ``@guarded_by("_lock", "attr", ...)``
(kubedtn_tpu.contracts). This pass re-reads the same declaration from
the AST and flags every ``self.attr`` load/store in a method body that
is not lexically inside ``with self.<lock>`` — unless the method is
``__init__`` (construction precedes publication) or is decorated
``@requires_lock("<lock>")`` (the caller holds it). Nested functions
defined inside a method are checked against the with-blocks visible at
their definition site only if they are *immediately* called; otherwise
(thread bodies, callbacks) accesses inside them are flagged for an
explicit ``requires_lock``/waiver decision.

The runtime half (lock-ordering, cycle detection) lives in
``kubedtn_tpu.contracts.InstrumentedLock``; tests wire both together.
Waiver: ``# dtnlint: lock-ok(reason)``.
"""

from __future__ import annotations

import ast

from kubedtn_tpu.analysis.core import (
    RULE_LOCK,
    Finding,
    Project,
    call_name,
    dotted,
)


def run(project: Project, graph: object = None) -> list[Finding]:
    findings: list[Finding] = []
    for src in project:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                guarded = _guarded_map(node)
                if guarded:
                    findings.extend(_check_class(src.rel, node, guarded))
    return findings


def _guarded_map(cls: ast.ClassDef) -> dict[str, str]:
    """attr -> lock from @guarded_by("lock", "attr", ...) decorators."""
    out: dict[str, str] = {}
    for dec in cls.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        cn = call_name(dec)
        if cn is None or cn.split(".")[-1] != "guarded_by":
            continue
        args = [a.value for a in dec.args
                if isinstance(a, ast.Constant) and isinstance(a.value, str)]
        if len(args) >= 2:
            lock, attrs = args[0], args[1:]
            for a in attrs:
                out[a] = lock
    return out


def _requires(fn: ast.FunctionDef) -> set[str]:
    held: set[str] = set()
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            cn = call_name(dec)
            if cn and cn.split(".")[-1] == "requires_lock":
                for a in dec.args:
                    if isinstance(a, ast.Constant) and \
                            isinstance(a.value, str):
                        held.add(a.value)
    return held


def _check_class(path: str, cls: ast.ClassDef,
                 guarded: dict[str, str]) -> list[Finding]:
    out: list[Finding] = []
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if item.name == "__init__":
            continue
        held0 = _requires(item)
        out.extend(_walk_body(path, cls.name, item, item.body,
                              guarded, held0))
    return out


def _with_locks(node: ast.With) -> set[str]:
    """Lock names this `with self.<name>` statement acquires."""
    locks: set[str] = set()
    for it in node.items:
        d = dotted(it.context_expr)
        if d and d.startswith("self."):
            locks.add(d.split(".", 1)[1])
    return locks


def _walk_body(path: str, clsname: str, method: ast.FunctionDef,
               body: list[ast.stmt], guarded: dict[str, str],
               held: set[str]) -> list[Finding]:
    out: list[Finding] = []
    for stmt in body:
        out.extend(_walk_stmt(path, clsname, method, stmt, guarded, held))
    return out


def _walk_stmt(path: str, clsname: str, method: ast.FunctionDef,
               stmt: ast.stmt, guarded: dict[str, str],
               held: set[str]) -> list[Finding]:
    out: list[Finding] = []
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        newly = _with_locks(stmt) if isinstance(stmt, ast.With) else set()
        inner = held | newly
        out.extend(_walk_body(path, clsname, method, stmt.body,
                              guarded, inner))
        return out
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        # nested def (thread body / callback): lock state at call time
        # is unknown — require an explicit requires_lock or waiver for
        # guarded accesses inside
        nested_held = _requires(stmt)
        out.extend(_walk_body(path, clsname, method, stmt.body, guarded,
                              nested_held))
        return out
    # expressions & simple statements: scan for self.<guarded attr>
    for node in _shallow_walk(stmt):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and node.attr in guarded:
            lock = guarded[node.attr]
            if lock not in held:
                kind = ("write" if isinstance(node.ctx,
                                              (ast.Store, ast.Del))
                        else "read")
            else:
                continue
            out.append(Finding(
                RULE_LOCK, path, node.lineno,
                f"{kind} of `{clsname}.{node.attr}` (guarded by "
                f"`self.{lock}`) outside the lock in "
                f"`{method.name}`"))
    # recurse into nested statement bodies (if/for/try/...)
    for field in ("body", "orelse", "finalbody"):
        sub = getattr(stmt, field, None)
        if isinstance(sub, list):
            for s in sub:
                if isinstance(s, ast.stmt):
                    out.extend(_walk_stmt(path, clsname, method, s,
                                          guarded, held))
    for h in getattr(stmt, "handlers", []) or []:
        for s in h.body:
            out.extend(_walk_stmt(path, clsname, method, s, guarded,
                                  held))
    return out


def _shallow_walk(stmt: ast.stmt):
    """Expressions belonging to this statement only — child statements
    (and nested defs/withs) are handled by the recursive walk."""
    skip_fields = {"body", "orelse", "finalbody", "handlers"}
    stack: list[ast.AST] = []
    for field, value in ast.iter_fields(stmt):
        if field in skip_fields:
            continue
        if isinstance(value, ast.AST):
            stack.append(value)
        elif isinstance(value, list):
            stack.extend(v for v in value if isinstance(v, ast.AST))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.stmt)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
    return
