"""Pass 5 — dtype-drift: f32 casts on f64 anchors, f64 leaks into f32
columns.

The PR 3 ``clock_us`` freeze: a wall-clock anchor kept as float32
stops advancing once ``dt * ulp`` rounds to zero (~2.4 h of uptime at
µs resolution) — the fix pinned the host-side anchor to float64. The
inverse leak also bites: a Python float / ``np.float64`` intermediate
scattered into an f32 SoA column silently downcasts (fine) *per
element* but drifts when it is an accumulator. Rules:

- **anchor-f32**: a configured f64 anchor name (``clock_us`` et al.)
  cast or constructed as float32 — the freeze bug class verbatim;
- **column-f64**: a ``.at[...].set/add`` (or keyword construction) of
  a known f32 SoA column fed by ``np.float64(...)`` / ``time.*()``
  without an explicit f32 cast;
- **f64-dtype-in-kernel**: a ``float64`` dtype request inside the
  device-kernel modules (the SoA is f32 by contract; x64 is disabled
  and the request silently yields f32 — stating an intent the runtime
  ignores).

Waiver: ``# dtnlint: dtype-ok(reason)``.
"""

from __future__ import annotations

import ast

from kubedtn_tpu.analysis.core import (
    RULE_DTYPE,
    Finding,
    Project,
    call_name,
    dotted,
)

# host-side wall-clock anchors that must stay float64
ANCHOR_NAMES = {"clock_us", "clock0_us", "origin_us", "anchor_us"}

# modules whose arrays are the f32 device SoA: float64 dtype requests
# there are either silently ignored (x64 off) or a host leak
KERNEL_MODULES = (
    "kubedtn_tpu/ops/edge_state.py",
    "kubedtn_tpu/ops/netem.py",
    "kubedtn_tpu/ops/queues.py",
    "kubedtn_tpu/ops/routing.py",
    "kubedtn_tpu/ops/pallas/shaping.py",
)

_F32_CASTS = {"np.float32", "numpy.float32", "jnp.float32"}
_F64_MAKERS = {"np.float64", "numpy.float64"}
_TIME_CALLS = {"time.time", "time.monotonic", "time.perf_counter"}


def run(project: Project, graph: object = None) -> list[Finding]:
    findings: list[Finding] = []
    for src in project:
        findings.extend(_check_file(src))
    return findings


def _mentions_anchor(node: ast.AST) -> str | None:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in ANCHOR_NAMES:
            return n.id
        if isinstance(n, ast.Attribute) and n.attr in ANCHOR_NAMES:
            return n.attr
    return None


def _is_f32_expr(node: ast.AST) -> bool:
    """Explicit float32 cast/construction?"""
    if isinstance(node, ast.Call):
        cn = call_name(node)
        if cn in _F32_CASTS:
            return True
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "astype":
            return _names_f32(node.args[0]) if node.args else False
        # np.asarray(x, np.float32) / jnp.zeros(shape, jnp.float32)
        for arg in [*node.args[1:], *(kw.value for kw in node.keywords
                                      if kw.arg == "dtype")]:
            if _names_f32(arg):
                return True
    return False


def _names_f32(node: ast.AST) -> bool:
    d = dotted(node)
    if d in _F32_CASTS:
        return True
    return isinstance(node, ast.Constant) and node.value == "float32"


def _names_f64(node: ast.AST) -> bool:
    d = dotted(node)
    if d in _F64_MAKERS:
        return True
    return isinstance(node, ast.Constant) and node.value == "float64"


def _check_file(src) -> list[Finding]:
    out: list[Finding] = []
    in_kernel = src.rel in KERNEL_MODULES
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            cn = call_name(node)
            # anchor-f32: float32 cast whose payload or binding mentions
            # a clock anchor
            if _is_f32_expr(node):
                anchor = _mentions_anchor(node)
                if anchor is not None:
                    out.append(Finding(
                        RULE_DTYPE, src.rel, node.lineno,
                        f"f32 cast/construction touching f64 clock "
                        f"anchor `{anchor}` — the `clock_us` freeze "
                        f"bug class (anchors stop advancing once "
                        f"dt < ulp/2)"))
            # anchor passed as keyword into a constructor while cast f32
            if cn is not None:
                for kw in node.keywords:
                    if kw.arg in ANCHOR_NAMES and _is_f32_expr(kw.value):
                        out.append(Finding(
                            RULE_DTYPE, src.rel, kw.value.lineno,
                            f"`{kw.arg}=` constructed as float32 in "
                            f"`{cn}(...)` — f64 anchor contract"))
            # column-f64: .at[...].set/add fed by float64 / wall clock
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("set", "add") and node.args:
                payload = node.args[0]
                for n in ast.walk(payload):
                    if isinstance(n, ast.Call):
                        pcn = call_name(n)
                        if pcn in _F64_MAKERS or pcn in _TIME_CALLS:
                            out.append(Finding(
                                RULE_DTYPE, src.rel, node.lineno,
                                f"`{pcn}(...)` feeds an f32 column "
                                f"scatter — implicit f64→f32 downcast; "
                                f"cast explicitly or keep host-side"))
                            break
            # f64 dtype requests inside kernel modules
            if in_kernel and cn is not None:
                f64 = (cn in _F64_MAKERS
                       or any(_names_f64(kw.value) for kw in node.keywords
                              if kw.arg == "dtype")
                       or any(_names_f64(a) for a in node.args[1:]))
                if f64:
                    out.append(Finding(
                        RULE_DTYPE, src.rel, node.lineno,
                        f"float64 dtype request in kernel module "
                        f"(`{cn}`): the SoA contract is f32 and x64 "
                        f"is disabled — the request is a silent no-op "
                        f"or a host leak"))
    return out
