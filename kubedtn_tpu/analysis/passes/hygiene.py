"""Hygiene pass — the ruff-subset dtnlint enforces even where ruff is
not installed: unused imports, bare ``except:``, and stdlib →
third-party → first-party import-group ordering. ``make lint`` runs
ruff *additionally* when the environment has it (same rule families:
F401, E722, I); this pass keeps the floor in plain-CI containers.

Waiver: ``# dtnlint: hygiene-ok(reason)``.
"""

from __future__ import annotations

import ast
import sys

from kubedtn_tpu.analysis.core import RULE_HYGIENE, Finding, Project

_FIRST_PARTY = "kubedtn_tpu"
_STDLIB = set(sys.stdlib_module_names)
_GROUPS = {"future": 0, "stdlib": 1, "third": 2, "first": 3}


def _group(module: str) -> int:
    top = module.split(".")[0]
    if top == "__future__":
        return _GROUPS["future"]
    if top == _FIRST_PARTY or module.startswith("."):
        return _GROUPS["first"]
    if top in _STDLIB:
        return _GROUPS["stdlib"]
    return _GROUPS["third"]


def run(project: Project, graph: object = None) -> list[Finding]:
    findings: list[Finding] = []
    for src in project:
        findings.extend(_unused_imports(src))
        findings.extend(_bare_excepts(src))
        findings.extend(_import_order(src))
    return findings


def _unused_imports(src) -> list[Finding]:
    if src.rel.endswith("__init__.py"):
        return []  # re-export surface
    imported: dict[str, tuple[int, str]] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            for al in node.names:
                name = (al.asname or al.name).split(".")[0]
                imported[name] = (node.lineno, al.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for al in node.names:
                if al.name == "*":
                    continue
                imported[al.asname or al.name] = (node.lineno, al.name)
    if not imported:
        return []
    used: set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and \
                isinstance(node.value, str):
            used.add(node.value)  # string annotations / __all__
    return [Finding(RULE_HYGIENE, src.rel, ln,
                    f"unused import `{name}`")
            for name, (ln, _orig) in sorted(imported.items(),
                                            key=lambda kv: kv[1][0])
            if name not in used]


def _bare_excepts(src) -> list[Finding]:
    out: list[Finding] = []
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            out.append(Finding(
                RULE_HYGIENE, src.rel, node.lineno,
                "bare `except:` swallows KeyboardInterrupt/SystemExit "
                "— name the exceptions"))
    return out


def _import_order(src) -> list[Finding]:
    """Top-of-module import groups must not interleave (future <
    stdlib < third-party < first-party). Function-local imports are
    deliberate (lazy jax) and exempt."""
    out: list[Finding] = []
    last = -1
    last_name = ""
    for node in src.tree.body:
        if isinstance(node, ast.Import):
            mod = node.names[0].name
        elif isinstance(node, ast.ImportFrom):
            mod = ("." * node.level) + (node.module or "")
        else:
            continue
        g = _group(mod)
        if g < last:
            out.append(Finding(
                RULE_HYGIENE, src.rel, node.lineno,
                f"import `{mod}` out of group order (after "
                f"`{last_name}`): future < stdlib < third-party < "
                f"first-party"))
        else:
            last, last_name = g, mod
    return out
