"""Pass 3 — host-sync leaks: no implicit device→host syncs on hot paths.

The fused tick's contract is ONE async dispatch per tick; any
``np.asarray`` / ``.item()`` / ``float()`` / ``bool()`` coercion on a
jax array inside the dispatch path blocks the host on the device and
serializes the pipeline. The completion side (``_complete``) is the
*designated* sync point and carries a def-level waiver saying so —
everything else must stay future-shaped.

Mechanics: the pass seeds from the configured hot-path roots, takes
the same-module call closure, and runs a light device-taint over each
function: parameters with device-ish names, results of ``jnp.*`` /
``jax.*`` calls, and attribute/subscript projections of tainted names
are device values; ``np.asarray``/``np.array``/``.item()``/
``jax.device_get``/``block_until_ready`` on them — or ``float``/
``int``/``bool``/``if``-tests over them — are findings. Untainted
arguments (host lists, native-ring byte counts) pass untouched.

Waiver: ``# dtnlint: sync-ok(reason)`` — line-level or on the ``def``.
"""

from __future__ import annotations

import ast

from kubedtn_tpu.analysis.callgraph import CallGraph, FuncRef
from kubedtn_tpu.analysis.core import (
    RULE_SYNC,
    Finding,
    Project,
    call_name,
)

# (file rel path, qualname) roots of the fused-tick / dispatch /
# complete hot paths. The closure over same-module resolvable calls
# extends each root.
HOT_ROOTS: tuple[tuple[str, str], ...] = (
    ("kubedtn_tpu/runtime.py", "_fused_tick"),
    ("kubedtn_tpu/runtime.py", "_class_tick"),
    ("kubedtn_tpu/runtime.py", "_shape_class"),
    ("kubedtn_tpu/runtime.py", "_tel_class"),
    ("kubedtn_tpu/runtime.py", "_roll_clocks"),
    ("kubedtn_tpu/runtime.py", "WireDataPlane._tick_inner"),
    ("kubedtn_tpu/runtime.py", "WireDataPlane._dispatch"),
    ("kubedtn_tpu/runtime.py", "WireDataPlane._dispatch_inner"),
    ("kubedtn_tpu/runtime.py", "WireDataPlane._complete"),
    ("kubedtn_tpu/runtime.py", "WireDataPlane._complete_or_requeue"),
    ("kubedtn_tpu/runtime.py", "WireDataPlane._release"),
    ("kubedtn_tpu/telemetry.py", "tel_matrix"),
    ("kubedtn_tpu/telemetry.py", "tel_accumulate"),
)

# parameter names that carry device values on the hot paths
_DEVICE_PARAMS = {"state", "dyn", "key", "sub", "tel", "acc", "res",
                  "sim", "edges"}
# attribute names that hold device values wherever they appear
# (engine.state, self._pipe_state, job.outs, state.props, ...)
_DEVICE_ATTRS = {"state", "_state", "_pipe_state", "props", "outs",
                 "tokens", "t_last", "backlog_until", "corr",
                 "pkt_count", "counters", "dropped_ring"}
_DEVICE_CALL_PREFIXES = ("jnp.", "jax.", "lax.")
_SYNC_METHODS = {"item", "block_until_ready", "tolist", "copy_to_host"}
_MATERIALIZERS = {"np.asarray", "np.array", "numpy.asarray",
                  "numpy.array", "jax.device_get", "device_get",
                  "np.ascontiguousarray"}
_COERCIONS = {"float", "int", "bool"}
# array metadata: reading these never transfers
_META_ATTRS = {"shape", "dtype", "ndim", "size", "capacity", "sharding"}


def run(project: Project, graph: CallGraph,
        hot_roots: tuple[tuple[str, str], ...] | None = None,
        ) -> list[Finding]:
    findings: list[Finding] = []
    roots = [FuncRef(p, q) for p, q in (hot_roots if hot_roots is not None
                                        else HOT_ROOTS)
             if FuncRef(p, q) in graph.functions]
    hot = {ref for ref in graph.closure(roots)
           if any(ref.path == r.path for r in roots)}
    for ref in sorted(hot, key=lambda r: (r.path, r.qual)):
        findings.extend(_check_function(
            project, graph.functions[ref], ref))
    return findings


def _check_function(project: Project, fn: ast.FunctionDef,
                    ref: FuncRef) -> list[Finding]:
    out: list[Finding] = []
    tainted: set[str] = set()
    for a in (*fn.args.posonlyargs, *fn.args.args, *fn.args.kwonlyargs):
        if a.arg in _DEVICE_PARAMS:
            tainted.add(a.arg)

    def expr_tainted(node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id in tainted:
                return True
            if isinstance(n, ast.Attribute) and n.attr in _DEVICE_ATTRS:
                return True
            if isinstance(n, ast.Call):
                cn = call_name(n)
                if cn and (cn.startswith(_DEVICE_CALL_PREFIXES)):
                    return True
        return False

    def materializes(value: ast.AST) -> bool:
        """RHS that yields host data even from a device operand: an
        explicit materializer/coercion call (the sync is flagged at its
        own line; downstream is free), array *metadata* (shape/
        capacity/dtype — no transfer), or an identity/membership test
        (a host bool)."""
        if isinstance(value, ast.Call):
            cn = call_name(value)
            if cn in _MATERIALIZERS or cn in _COERCIONS:
                return True
        if isinstance(value, ast.Attribute) and \
                value.attr in _META_ATTRS:
            return True
        if isinstance(value, ast.Subscript) and \
                isinstance(value.value, ast.Attribute) and \
                value.value.attr in _META_ATTRS:
            return True  # state.props.shape[0]
        if isinstance(value, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in value.ops):
            return True
        return False

    # forward pass in source order: propagate taint through assignments
    for node in _own_nodes_ordered(fn):
        if not isinstance(node, ast.Assign):
            continue
        goes_device = expr_tainted(node.value) and \
            not materializes(node.value)
        for t in node.targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name) and \
                        isinstance(n.ctx, ast.Store):
                    (tainted.add if goes_device
                     else tainted.discard)(n.id)

    for node in _own_nodes_ordered(fn):
        if isinstance(node, ast.Call):
            cn = call_name(node)
            if cn in _MATERIALIZERS and node.args and \
                    expr_tainted(node.args[0]):
                out.append(Finding(
                    RULE_SYNC, ref.path, node.lineno,
                    f"`{cn}` on a device value in hot `{ref.qual}` — "
                    f"blocks the host on the dispatch"))
            elif cn in _COERCIONS and node.args and \
                    expr_tainted(node.args[0]):
                out.append(Finding(
                    RULE_SYNC, ref.path, node.lineno,
                    f"`{cn}()` coerces a device value in hot "
                    f"`{ref.qual}` — implicit device→host sync"))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SYNC_METHODS and \
                    expr_tainted(node.func.value):
                out.append(Finding(
                    RULE_SYNC, ref.path, node.lineno,
                    f"`.{node.func.attr}()` on a device value in hot "
                    f"`{ref.qual}` — implicit device→host sync"))
        elif isinstance(node, (ast.If, ast.While)):
            test = node.test
            if isinstance(test, (ast.Name, ast.Compare, ast.UnaryOp)) \
                    and expr_tainted(test) and _is_bare_device_test(test,
                                                                    tainted):
                out.append(Finding(
                    RULE_SYNC, ref.path, test.lineno,
                    f"branching on a device value in hot "
                    f"`{ref.qual}` — bool coercion syncs the host"))
    return out


def _is_bare_device_test(test: ast.AST, tainted: set[str]) -> bool:
    """Only flag direct truthiness of a tainted NAME (or `not name` /
    comparison against one) — `if rows is None` style identity tests
    never sync."""
    if isinstance(test, ast.Name):
        return test.id in tainted
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_bare_device_test(test.operand, tainted)
    if isinstance(test, ast.Compare):
        if any(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
               for op in test.ops):
            return False
        return any(isinstance(n, ast.Name) and n.id in tainted
                   for n in [test.left, *test.comparators])
    return False


def _own_nodes_ordered(fn: ast.FunctionDef):
    stack = list(reversed(list(ast.iter_child_nodes(fn))))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(reversed(list(ast.iter_child_nodes(node))))
