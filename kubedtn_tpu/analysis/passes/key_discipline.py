"""Pass 2 — key-discipline: every PRNG sample comes off a fresh key.

The PR 3 vmap-drift bug class: a key that feeds two sampling calls (or
a raw ``jax.random.key(seed)`` handed straight into a sampling path)
produces correlated draws — replicas that should be independent share
entropy, and a replayed schedule silently diverges from the reference
program. Contract, per function:

- a sampling call's key must be a ``split``/``fold_in`` product, a key
  parameter (the leaf-kernel idiom — the caller did the split), or key
  state split in place;
- no key name feeds two sampling calls without a rebinding between;
- a sampler inside a loop must not reuse a loop-invariant key;
- a raw root key (``jax.random.key(...)`` / ``PRNGKey(...)``) must
  pass through ``split``/``fold_in`` before any other call consumes it.

Waiver: ``# dtnlint: key-ok(reason)``.
"""

from __future__ import annotations

import ast

from kubedtn_tpu.analysis.core import (
    RULE_KEY,
    Finding,
    Project,
    call_name,
    iter_functions,
)

_SAMPLERS = {
    "uniform", "normal", "bernoulli", "poisson", "randint", "choice",
    "categorical", "gamma", "beta", "exponential", "truncated_normal",
    "gumbel", "laplace", "cauchy", "dirichlet", "permutation",
    "shuffle", "bits", "rademacher", "t", "loggamma", "multivariate_normal",
}
_KEY_OPS = {"split", "fold_in", "clone"}
_KEY_ROOTS = {"key", "PRNGKey"}


def _random_call_kind(cn: str | None) -> str | None:
    """'sampler' | 'keyop' | 'root' for a jax.random.* call name."""
    if cn is None:
        return None
    parts = cn.split(".")
    tail = parts[-1]
    if len(parts) >= 2 and parts[-2] == "random" or \
            (len(parts) == 2 and parts[0] in ("jrandom", "jr")):
        if tail in _SAMPLERS:
            return "sampler"
        if tail in _KEY_OPS:
            return "keyop"
        if tail in _KEY_ROOTS:
            return "root"
    return None


def run(project: Project, graph: object = None) -> list[Finding]:
    findings: list[Finding] = []
    for src in project:
        for qual, fn in iter_functions(src.tree):
            findings.extend(_check_function(src.rel, qual, fn))
    return findings


def _check_function(path: str, qual: str,
                    fn: ast.FunctionDef) -> list[Finding]:
    out: list[Finding] = []
    params = {a.arg for a in (*fn.args.posonlyargs, *fn.args.args,
                              *fn.args.kwonlyargs)}

    # name -> list of (lineno, origin) bindings in source order, where
    # origin is 'derived' (split/fold_in product), 'root'
    # (jax.random.key/PRNGKey), or 'other'
    binds: dict[str, list[tuple[int, str]]] = {}
    sampler_uses: dict[str, list[int]] = {}
    loop_spans: list[tuple[int, int, set[str]]] = []  # start, end, rebound

    def origin_of(value: ast.AST) -> str:
        if isinstance(value, ast.Call):
            kind = _random_call_kind(call_name(value))
            if kind == "keyop":
                return "derived"
            if kind == "root":
                return "root"
        return "other"

    for node in _own_nodes(fn):
        if isinstance(node, ast.Assign):
            org = origin_of(node.value)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    binds.setdefault(t.id, []).append((node.lineno, org))
                elif isinstance(t, ast.Tuple):
                    # k1, k2 = split(key): every element is derived
                    for el in t.elts:
                        if isinstance(el, ast.Name):
                            binds.setdefault(el.id, []).append(
                                (node.lineno,
                                 org if org != "other" else "other"))
        elif isinstance(node, (ast.For, ast.While)):
            rebound: set[str] = set()
            if isinstance(node, ast.For):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        rebound.add(n.id)
            for n in ast.walk(node):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    rebound.add(n.id)
            loop_spans.append((node.lineno, node.end_lineno or node.lineno,
                               rebound))

    def key_arg(call: ast.Call) -> ast.AST | None:
        for kw in call.keywords:
            if kw.arg == "key":
                return kw.value
        return call.args[0] if call.args else None

    for node in _own_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        kind = _random_call_kind(cn)
        if kind == "sampler":
            k = key_arg(node)
            if k is None:
                continue
            if isinstance(k, ast.Call):
                kk = _random_call_kind(call_name(k))
                if kk == "root":
                    out.append(Finding(
                        RULE_KEY, path, node.lineno,
                        f"`{cn}` in `{qual}` consumes a raw "
                        f"`jax.random.key(...)` — fold a purpose in "
                        f"(`fold_in`/`split`) before sampling"))
                # keyop call inline: derived, fine
                continue
            name = k.id if isinstance(k, ast.Name) else None
            if name is None:
                continue  # attribute/subscript keys: trust the carrier
            sampler_uses.setdefault(name, []).append(node.lineno)
            last = _last_bind(binds.get(name, []), node.lineno)
            if last == "root":
                out.append(Finding(
                    RULE_KEY, path, node.lineno,
                    f"`{cn}` in `{qual}` samples from root key "
                    f"`{name}` — derive a subkey via `split`/"
                    f"`fold_in` first"))
            elif last is None and name not in params:
                out.append(Finding(
                    RULE_KEY, path, node.lineno,
                    f"`{cn}` in `{qual}` samples from `{name}`, which "
                    f"is neither a parameter nor a `split`/`fold_in` "
                    f"product in this scope"))
            # loop-invariant reuse
            for start, end, rebound in loop_spans:
                if start <= node.lineno <= end and name not in rebound:
                    out.append(Finding(
                        RULE_KEY, path, node.lineno,
                        f"`{cn}` in `{qual}` reuses loop-invariant "
                        f"key `{name}` across iterations — every pass "
                        f"draws the same bits"))
                    break
        elif kind is None and cn is not None:
            # raw root key passed into an arbitrary call (the sampling
            # path continues inside): jax.random.key(...) as an argument
            for arg in [*node.args, *(kw.value for kw in node.keywords)]:
                if isinstance(arg, ast.Call) and \
                        _random_call_kind(call_name(arg)) == "root":
                    out.append(Finding(
                        RULE_KEY, path, node.lineno,
                        f"raw `jax.random.key(...)` passed directly "
                        f"into `{cn}` in `{qual}` — two call sites "
                        f"with the same seed collide; `fold_in` a "
                        f"purpose first"))

    # a key name feeding two samplers with no rebinding in between
    for name, uses in sampler_uses.items():
        if len(uses) < 2:
            continue
        uses = sorted(uses)
        rebinds = sorted(ln for ln, _ in binds.get(name, []))
        for a, b in zip(uses, uses[1:]):
            if not any(a < r <= b for r in rebinds):
                out.append(Finding(
                    RULE_KEY, path, b,
                    f"key `{name}` feeds a second sampling call in "
                    f"`{qual}` (first at line {a}) without an "
                    f"intervening `split`/`fold_in` rebinding — "
                    f"identical draws"))
    return out


def _last_bind(bindings: list[tuple[int, str]],
               before: int) -> str | None:
    last: str | None = None
    for ln, org in sorted(bindings):
        if ln <= before:
            last = org
    return last


def _own_nodes(fn: ast.FunctionDef):
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
