"""jdtype — IR-level f64 taint through the traced programs.

The PR 3 clock-freeze class, checked where it actually happens: a wall
clock held in float32 stops advancing once the tick delta drops below
half its ulp (~2.4 h of µs uptime), so the contract is that f64
wall-clock anchors stay f64 until a RELATIVE quantity is formed, and
no truncating cast lands an anchored value in the f32 SoA.

At the IR level that is a forward taint: every f64 input or constant
of the program is a taint root; a `convert_element_type` that narrows
a tainted float and a scatter of tainted updates into an f32 operand
are findings. The shipped tick programs run with x64 disabled, so a
clean tree proves the *absence* of f64 in traced code outright (the
third check); the mutation fixtures trace under
`jax.experimental.enable_x64` to demonstrate the taint machinery on
the historical bug shape.
"""

from __future__ import annotations

import numpy as np

from kubedtn_tpu.analysis.core import Finding
from kubedtn_tpu.analysis.verify.jaxpr_tools import Dataflow, iter_eqns

RULE_JDTYPE = "jdtype"

_NARROW_FLOATS = ("float32", "bfloat16", "float16")


def _is_f64(aval) -> bool:
    dtype = getattr(aval, "dtype", None)
    return dtype is not None and dtype == np.dtype("float64")


class _TaintF64(Dataflow):
    bottom = False

    def join(self, a, b):
        return bool(a) or bool(b)

    def invar(self, var, index):
        return _is_f64(var.aval)

    def constvar(self, var):
        return _is_f64(getattr(var, "aval", None))

    def literal(self, lit):
        return _is_f64(getattr(lit, "aval", None))

    def transfer(self, eqn, in_vals):
        name = eqn.primitive.name
        tainted = any(in_vals)
        if name == "convert_element_type" and in_vals and in_vals[0]:
            new = str(eqn.params.get("new_dtype"))
            if new in _NARROW_FLOATS:
                self.emit(f"truncating cast f64→{new} on a wall-clock-"
                          f"anchored value inside traced code (the "
                          f"clock-freeze class — keep anchors f64 "
                          f"until a relative time is formed)")
            # the narrowed value still descends from the anchor
            return [True] * len(eqn.outvars)
        if name in ("scatter", "scatter-add", "scatter-mul",
                    "scatter-min", "scatter-max"):
            # invars: (operand, indices, updates)
            if len(in_vals) >= 3 and in_vals[2]:
                op_dtype = str(getattr(eqn.invars[0].aval, "dtype", ""))
                if op_dtype in _NARROW_FLOATS:
                    self.emit(f"f64-anchored updates scattered into "
                              f"an {op_dtype} SoA column")
            return [tainted] * len(eqn.outvars)
        if name == "dynamic_update_slice":
            if len(in_vals) >= 2 and in_vals[1]:
                op_dtype = str(getattr(eqn.invars[0].aval, "dtype", ""))
                if op_dtype in _NARROW_FLOATS:
                    self.emit(f"f64-anchored update written into an "
                              f"{op_dtype} SoA column")
            return [tainted] * len(eqn.outvars)
        return None


def check_dtype_flow(entry, findings: list[Finding]) -> None:
    msgs: list[str] = []
    flow = _TaintF64(emit=lambda m: msgs.append(m))
    flow.run(entry.jaxpr.jaxpr)
    for m in dict.fromkeys(msgs):
        findings.append(Finding(RULE_JDTYPE, entry.path, entry.line,
                                f"[{entry.name}] {m}"))
    if entry.expect_f32_only:
        hits = 0
        for eqn in iter_eqns(entry.jaxpr.jaxpr):
            for v in eqn.outvars:
                if _is_f64(getattr(v, "aval", None)):
                    hits += 1
                    if hits <= 2:
                        findings.append(Finding(
                            RULE_JDTYPE, entry.path, entry.line,
                            f"[{entry.name}] float64 value produced by "
                            f"`{eqn.primitive.name}` inside the f32 "
                            f"tick program (x64 leak doubles HBM "
                            f"traffic and breaks SoA bit-layout)"))
        if hits > 2:
            findings.append(Finding(
                RULE_JDTYPE, entry.path, entry.line,
                f"[{entry.name}] ...and {hits - 2} further float64 "
                f"values in this program"))
