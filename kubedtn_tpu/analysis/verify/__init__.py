"""dtnverify — jaxpr-level contract verification of the compiled tick
programs.

dtnlint (the AST layer, `kubedtn_tpu.analysis.passes`) checks the
determinism / key / host-sync / lock / dtype contracts where they are
WRITTEN; this package checks them where they are STAKED — in the
lowered programs the plane actually dispatches. The real entry points
(the fused tick at depths 1/2, the degradation ladder's `_class_tick`,
the sharded `shard_map` program, the twin's replica scan, the update
gate's sweep) are traced into jaxprs and compiled executables, then
four pass families run over the IR:

========  ==============================================================
rule      contract (NOT waivable — see below)
========  ==============================================================
jops      op-allowlist determinism: no primitive outside the vetted
          set, no nondeterministic collective/host-callback primitives
          on the tick path
jkey      every ``random_bits`` is reachable only through a
          ``split``/``fold_in`` chain rooted at the tick key argument —
          no key minted, baked, or consumed raw inside traced code
jdtype    IR-level f64 taint: no truncating cast on a wall-clock-
          anchored f64 value, no f64-anchored value scattered into an
          f32 SoA column, no stray f64 inside the f32 tick programs
jshard    sharding audit: key/batch args replicated into the shard_map
          program, ppermute the only collective (scatters stay local
          to the owning shard), foreign mailbox bits move through
          ``select_n`` only — never arithmetic
jcost     dispatch & cost budget: compiled dispatches per tick and XLA
          cost-analysis FLOPs/bytes per entry point against the
          checked-in ``COST_BUDGET.json``
========  ==============================================================

Unlike the AST layer, jaxpr findings carry NO waiver mechanism: a
finding here means a compiled program violates a byte-identity or
fusion contract, and the sanctioned overrides are structural — extend
the vetted allowlist (a reviewed code change) or re-baseline the
budgets (``--update-budgets``). A ``# dtnlint: jops-ok(...)``-style
comment does nothing and is reported as a dead waiver.

The eBPF-verifier analogy (SURVEY §2.9) is deliberate: the reference
enforces its data-plane contracts with kernel verifier constraints at
load time; the TPU-native equivalent is verification over the jaxprs
and executables themselves, gating tier-1 before any bench run.
"""

from __future__ import annotations

from kubedtn_tpu.analysis.verify.runner import (
    VERIFY_RULES,
    VerifyReport,
    run_verify,
)

__all__ = ["run_verify", "VerifyReport", "VERIFY_RULES"]
