"""jshard — audit of the sharded tick's `shard_map` program.

The sharded plane's byte-identity contract (PR 5, ARCHITECTURE.md
"Sharded live plane") rests on four IR-checkable facts:

1. the per-tick KEY and the padded batch args enter the shard_map
   REPLICATED, so every shard draws the unsharded kernels' exact
   uniforms over the exact padded [R, K] shapes;
2. edge-state columns enter sharded along axis 0 of the edge axis and
   nothing else — no surprise partitioning;
3. the ONLY collective inside the body is the mailbox ring's
   `ppermute` (each step a bijective neighbor shift): every scatter is
   therefore local to the owning shard by shard_map's per-shard SPMD
   semantics;
4. foreign bits arriving over the ring reach the shaping kernels
   through `select_n` ONLY — the ownership flag picks the owner's bits
   verbatim; any arithmetic on a pre-select mailbox payload would
   round and break N-shard ≡ 1-shard bit-identity
   (parallel/exchange.py documents the select-combine contract; the
   ownership flag rides int payload column `exchange.OWNER_COL`).
"""

from __future__ import annotations

from kubedtn_tpu.analysis.core import Finding
from kubedtn_tpu.analysis.verify.jaxpr_tools import (
    Dataflow,
    is_key_dtype,
    iter_eqns,
)

RULE_JSHARD = "jshard"

# taint PROPAGATES through pure data movement (the payload is still
# foreign bits, just rearranged — and a dtype convert is still the
# payload's bits, rounded: letting it launder taint would hide an
# arithmetic combine behind a leading astype)...
_PASS_THROUGH = {
    "slice", "dynamic_slice", "squeeze", "reshape", "broadcast_in_dim",
    "concatenate", "transpose", "pad", "rev", "copy",
    "expand_dims", "bitcast_convert_type", "convert_element_type",
    "gather",
}
# ...is CONSUMED (and stops) at the ownership select and at flag
# comparisons (the predicate is the owner bit, not payload)
_CONSUMERS = {"select_n", "eq", "ne", "ge", "gt", "le", "lt", "and",
              "or", "not"}


class _ForeignTaint(Dataflow):
    """Taint = 'came off the ring, not yet ownership-selected'."""

    bottom = False

    def join(self, a, b):
        return bool(a) or bool(b)

    def transfer(self, eqn, in_vals):
        name = eqn.primitive.name
        if name == "ppermute":
            return [True] * len(eqn.outvars)
        tainted = any(in_vals)
        if not tainted:
            return [False] * len(eqn.outvars)
        if name in _PASS_THROUGH:
            return [True] * len(eqn.outvars)
        if name in _CONSUMERS:
            return [False] * len(eqn.outvars)
        self.emit(f"`{name}` consumes foreign mailbox bits BEFORE the "
                  f"ownership select — cross-shard state must move "
                  f"verbatim (`select_n` on the owner flag), never "
                  f"through arithmetic")
        return [False] * len(eqn.outvars)


def _find_shard_maps(jaxpr):
    return [eqn for eqn in iter_eqns(jaxpr)
            if eqn.primitive.name == "shard_map"]


def check_sharding(entry, findings: list[Finding]) -> None:
    def add(msg: str) -> None:
        findings.append(Finding(RULE_JSHARD, entry.path, entry.line,
                                f"[{entry.name}] {msg}"))

    maps = _find_shard_maps(entry.jaxpr.jaxpr)
    if not maps:
        add("expected a shard_map program, found none — the sharded "
            "tick no longer runs under shard_map")
        return
    axis = entry.edge_axis
    for eqn in maps:
        in_names = eqn.params.get("in_names", ())
        for i, (var, names) in enumerate(zip(eqn.invars, in_names)):
            spec = dict(names)
            if is_key_dtype(getattr(var, "aval", None)):
                if spec:
                    add(f"PRNG key input #{i} enters the shard_map "
                        f"SHARDED ({spec}) — keys must replicate so "
                        f"every shard draws identical uniforms")
                continue
            if spec not in ({}, {0: (axis,)}):
                add(f"input #{i} uses partitioning {spec} — only "
                    f"replicated or axis-0 `{axis}` block-sharding is "
                    f"part of the plane's layout contract")
        for i, names in enumerate(eqn.params.get("out_names", ())):
            spec = dict(names)
            if spec not in ({}, {0: (axis,)}):
                add(f"output #{i} uses partitioning {spec} — outside "
                    f"the replicated/edge-sharded layout contract")

        body = eqn.params["jaxpr"]
        body = body.jaxpr if hasattr(body, "jaxpr") else body
        seen: set[str] = set()
        for inner in iter_eqns(body):
            name = inner.primitive.name
            if name == "ppermute":
                perm = inner.params.get("perm", ())
                srcs = [s for s, _ in perm]
                dsts = [d for _, d in perm]
                if (len(set(srcs)) != len(srcs)
                        or len(set(dsts)) != len(dsts)):
                    add("ppermute permutation is not a bijection — a "
                        "duplicated source/destination makes the "
                        "exchange order-dependent")
                continue
            if name in ("psum", "pmax", "pmin", "pmean", "all_gather",
                        "all_to_all", "reduce_scatter", "psum_scatter",
                        "pshuffle") and name not in seen:
                seen.add(name)
                add(f"collective `{name}` inside the shard_map body — "
                    f"the mailbox ring (`ppermute`/remote DMA) is the "
                    f"only vetted cross-shard movement; reductions "
                    f"across shards break scatter locality")

        msgs: list[str] = []
        flow = _ForeignTaint(emit=lambda m: msgs.append(m))
        flow._sub(eqn.params["jaxpr"],
                  [False] * len(body.invars))
        for m in dict.fromkeys(msgs):
            add(m)
