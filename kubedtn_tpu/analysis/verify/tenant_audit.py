"""jtenant — tenant-isolation audit of the compiled tick programs.

The multi-tenant plane's isolation contract (ARCHITECTURE.md
"Multi-tenant plane") rests on an IR-checkable fact: every scatter in
the tick program lands on row indices that derive from the dispatch's
ROW-INDEX INPUTS through index-preserving ops only — selects against
the padding sentinel, clamps, dtype converts, the sharded body's
axis-offset translation. No arithmetic ever SHIFTS an index: an
`add`/`mul` on the index path could relocate a write into another
tenant's edge range, silently corrupting a neighbor's shaping state
while every per-tenant counter still balances.

Mechanics (the same forward-taint machinery as the mailbox
ownership-select rule, sharding_audit._ForeignTaint): each value
carries (arith, axis) flags. `axis_index` outputs are axis-derived;
index arithmetic with an axis-derived operand stays clean (the sharded
body's `rows - shard_offset` translation is the vetted shift); any
other add/sub/mul/div/rem taints. A scatter whose index operand is
arith-tainted is a finding. The seeded cross-tenant-scatter mutant
(tests/fixtures/dtnverify/mutants.py: mutant_cross_tenant_scatter)
re-introduces the exact bug shape — `rows + stride` before the
write-back scatter — and the pass must kill it while the real fused /
class / sharded programs stay silent.
"""

from __future__ import annotations

from kubedtn_tpu.analysis.core import Finding
from kubedtn_tpu.analysis.verify.jaxpr_tools import Dataflow, iter_eqns

RULE_JTENANT = "jtenant"

# index arithmetic that can SHIFT a row index across a range boundary
_INDEX_ARITH = {"add", "sub", "mul", "div", "rem", "pow",
                "integer_pow", "dot_general"}
# scatter-family primitives whose index operand must stay shift-free
# (operand 0 = target, operand 1 = scatter indices, rest = updates)
_SCATTER_PRIMS = {"scatter", "scatter-add", "scatter-mul",
                  "scatter-min", "scatter-max", "scatter_add",
                  "scatter_mul", "scatter_min", "scatter_max"}


class _IndexTaint(Dataflow):
    """Value lattice: (arith, axis) — `arith` marks a value that passed
    through index-shifting arithmetic with no axis-derived operand;
    `axis` marks descent from `axis_index` (the shard-local offset
    translation, the one vetted shift)."""

    bottom = (False, False)

    def join(self, a, b):
        a = a or self.bottom
        b = b or self.bottom
        return (a[0] or b[0], a[1] or b[1])

    def transfer(self, eqn, in_vals):
        name = eqn.primitive.name
        vals = [v or self.bottom for v in in_vals]
        if name == "axis_index":
            return [(False, True)] * len(eqn.outvars)
        arith = any(v[0] for v in vals)
        axis = any(v[1] for v in vals)
        if name in _INDEX_ARITH:
            # arithmetic taints UNLESS an operand descends from
            # axis_index (the sharded body's offset translation) — and
            # propagates existing taint regardless
            out = (arith or not axis, axis)
            return [out] * len(eqn.outvars)
        if name == "select_n" and len(vals) > 1:
            # jax's indexed-update lowering normalizes negative
            # indices as select_n(idx < 0, idx, idx + N): a select
            # with AT LEAST ONE clean data branch yields the clean
            # provenance (the shifted copy is only taken where the
            # clean one wraps). A select whose EVERY branch is shifted
            # — the cross-tenant mutant's shape — stays tainted.
            data = vals[1:]
            out = (all(v[0] for v in data),
                   any(v[1] for v in data))
            return [out] * len(eqn.outvars)
        if name in _SCATTER_PRIMS and len(eqn.invars) >= 2:
            idx_val = vals[1] if len(vals) >= 2 else self.bottom
            if idx_val[0]:
                self.emit(
                    f"`{name}` scatter indices pass through index "
                    f"ARITHMETIC with no axis-offset provenance — a "
                    f"shifted row index can write into another "
                    f"tenant's edge range; indices must derive from "
                    f"the dispatch's row inputs via select/clamp/"
                    f"convert only")
        # default: propagate the join (selects, clamps, converts,
        # gathers, reshapes all preserve whatever taint flows in)
        return None


def check_tenant_isolation(entry, findings: list[Finding]) -> None:
    """Run the index-taint audit over one traced entry point; also
    sanity-check that the program HAS write-back scatters at all — a
    tick program with no scatter would mean the audit is pointed at
    the wrong entry (harness drift), which must be loud."""

    def add(msg: str) -> None:
        findings.append(Finding(RULE_JTENANT, entry.path, entry.line,
                                f"[{entry.name}] {msg}"))

    jaxpr = entry.jaxpr.jaxpr
    has_scatter = any(e.primitive.name in _SCATTER_PRIMS
                      for e in iter_eqns(jaxpr))
    if not has_scatter:
        add("expected write-back scatters in the tick program, found "
            "none — the tenant-isolation audit is pointed at a "
            "program with no row writes (harness drift)")
        return
    msgs: list[str] = []
    flow = _IndexTaint(emit=lambda m: msgs.append(m))
    flow.run(jaxpr)
    for m in dict.fromkeys(msgs):
        add(m)
