"""dtnverify harness: trace the REAL entry points into jaxprs.

The canonical probe topology is three link pairs, one per shaping
kernel class (slot-independent, TBF, correlated-sequential), built
through the production path (store → reconciler → engine → daemon →
WireDataPlane) with telemetry ON. The fused tick's arguments are then
CAPTURED from real `plane.tick()` dispatches — not hand-built — so the
traced program is the byte-for-byte production one, statics included.
The sharded program, the degradation ladder's `_class_tick`, the twin
sweep, and the update gate's sweep trace from the same captured shapes
through their production assembly helpers (`twin.engine.prepare_sweep`,
`updates.gate.gate_scenarios`).

Shapes are pinned (capacity 16, one padded row per class, 16 slots, 3
sweep steps) so the XLA cost-analysis numbers in COST_BUDGET.json are
reproducible run-to-run on a given backend.
"""

from __future__ import annotations

import dataclasses
import functools
import inspect
from pathlib import Path

from kubedtn_tpu.analysis import default_root

PROBE_CAPACITY = 16
SWEEP_STEPS = 3
SWEEP_REPLICAS = 2

ENTRY_NAMES = (
    "fused_tick_d1", "fused_tick_d2",
    "class_tick_tbf", "class_tick_seq", "class_tick_ind",
    "sharded_fused",
    "twin_sweep", "update_gate_sweep",
)


@dataclasses.dataclass
class EntryPoint:
    """One traced program plus the contract knobs the passes read."""

    name: str
    path: str                 # repo-relative source anchor
    line: int
    jaxpr: object = None      # ClosedJaxpr (None when skipped)
    cost: dict | None = None  # {"flops":..., "bytes":...} when compiled
    skip_reason: str | None = None
    allowed_collectives: tuple = ()
    expect_f32_only: bool = True
    expect_shard_map: bool = False
    edge_axis: str = "edge"
    n_eqns: int = 0
    n_prims: int = 0


def _anchor(fn) -> tuple[str, int]:
    """Repo-relative (path, line) of a callable (through jit wrappers)."""
    f = inspect.unwrap(getattr(fn, "__wrapped__", fn))
    try:
        src = Path(inspect.getsourcefile(f)).resolve()
        line = inspect.getsourcelines(f)[1]
        return src.relative_to(default_root()).as_posix(), line
    except Exception:
        return "kubedtn_tpu/runtime.py", 1


# -- the probe plane ----------------------------------------------------

def _probe_props():
    from kubedtn_tpu.api.types import LinkProperties

    return [
        LinkProperties(latency="3ms", jitter="1ms", loss="5"),    # ind
        LinkProperties(rate="2Gbit"),                             # tbf
        LinkProperties(latency="2ms", loss="10", loss_corr="25"),  # seq
    ]


def build_probe_plane(depth: int = 2, telemetry: bool = True):
    """The canonical three-class plane, built through the production
    control path. Returns (plane, ingress_wires)."""
    from kubedtn_tpu.api.types import Link, Topology, TopologySpec
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=PROBE_CAPACITY)
    props = _probe_props()
    for i, p in enumerate(props):
        a, b = f"a{i}", f"b{i}"
        store.create(Topology(name=a, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=i + 1, properties=p)])))
        store.create(Topology(name=b, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=i + 1, properties=p)])))
        engine.setup_pod(a)
        engine.setup_pod(b)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    win = []
    for i in range(len(props)):
        win.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"a{i}", kube_ns="default", link_uid=i + 1,
            intf_name_in_pod="eth1")))
        daemon._add_wire(pb.WireDef(
            local_pod_name=f"b{i}", kube_ns="default", link_uid=i + 1,
            intf_name_in_pod="eth1"))
    plane = WireDataPlane(daemon, dt_us=2_000.0, pipeline_depth=depth)
    plane.pipeline_explicit_clock = True
    if telemetry:
        plane.enable_telemetry(window_s=0.05, sample_period=4)
    return plane, win


def capture_fused_calls():
    """Run real ticks and capture `_fused_tick`'s production arguments
    for the all-classes dispatch at depth 1 (chain head, has_dyn=False)
    and depth 2 (chained dyn). Returns {"d1": (args, statics),
    "d2": ...}."""
    from kubedtn_tpu import runtime as rt

    captured: dict[str, tuple] = {}
    orig = rt._fused_tick

    def recorder(*args, **statics):
        if all(statics.get(f)
               for f in ("has_seq", "has_tbf", "has_ind", "has_tel")):
            captured.setdefault(
                "d2" if statics.get("has_dyn") else "d1",
                (args, dict(statics)))
        return orig(*args, **statics)

    rt._fused_tick = recorder
    try:
        plane, win = build_probe_plane(depth=2)
        t = 100.0
        for j in range(6):
            for wa in win:
                wa.ingress.extend(bytes([j]) * 64 for _ in range(8))
            t += 0.002
            plane.tick(now_s=t)
        plane.flush()
    finally:
        rt._fused_tick = orig
    missing = {"d1", "d2"} - set(captured)
    if missing:
        raise RuntimeError(
            f"probe plane never dispatched an all-classes fused tick "
            f"for {sorted(missing)} — harness drifted from the plane")
    return captured


# -- tracing ------------------------------------------------------------

def _trace(fn, *args):
    import jax

    return jax.make_jaxpr(fn)(*args)


def _cost_of(jitted_callable, args) -> dict | None:
    """XLA cost analysis of the compiled program (flops / bytes
    accessed); None when the backend does not report them."""
    try:
        compiled = jitted_callable.lower(*args).compile()
        ca = compiled.cost_analysis()
        ca0 = ca[0] if isinstance(ca, (list, tuple)) else ca
        if not ca0:
            return None
        return {"flops": float(ca0.get("flops", 0.0)),
                "bytes": float(ca0.get("bytes accessed", 0.0))}
    except Exception:
        return None


def _finish(ep: EntryPoint, closed, cost) -> EntryPoint:
    from kubedtn_tpu.analysis.verify.jaxpr_tools import (
        count_eqns,
        primitive_set,
    )

    ep.jaxpr = closed
    ep.cost = cost
    ep.n_eqns = count_eqns(closed.jaxpr)
    ep.n_prims = len(primitive_set(closed.jaxpr))
    return ep


def trace_entry_points(entries: tuple[str, ...] | None = None,
                       compile_costs: bool = True) -> list[EntryPoint]:
    """Trace every requested entry point; entries that cannot run in
    this environment come back with `skip_reason` instead of a jaxpr
    (honest skip, surfaced in the report)."""
    import jax

    from kubedtn_tpu import runtime as rt

    wanted = tuple(entries) if entries else ENTRY_NAMES
    unknown = set(wanted) - set(ENTRY_NAMES)
    if unknown:
        raise ValueError(f"unknown entry point(s): {sorted(unknown)} "
                         f"(have: {', '.join(ENTRY_NAMES)})")
    out: list[EntryPoint] = []
    need_fused = any(e.startswith(("fused_", "class_", "sharded"))
                     for e in wanted)
    caps = capture_fused_calls() if need_fused else {}

    fpath, fline = _anchor(rt._fused_tick)
    for depth_name, cap_key in (("fused_tick_d1", "d1"),
                                ("fused_tick_d2", "d2")):
        if depth_name not in wanted:
            continue
        args, statics = caps[cap_key]
        fn = functools.partial(rt._fused_tick, **statics)
        ep = EntryPoint(depth_name, fpath, fline)
        closed = _trace(lambda *a: fn(*a), *args)
        cost = (_cost_of(jax.jit(lambda *a: fn(*a)), args)
                if compile_costs else None)
        out.append(_finish(ep, closed, cost))

    cpath, cline = _anchor(rt._class_tick)
    class_wanted = [e for e in wanted if e.startswith("class_tick_")]
    if class_wanted:
        # the ladder's un-fused rung: same captured state/args, the
        # production per-class chaining (tick key split, per-class
        # fold_in happens inside via _shape_class)
        args, _statics = caps["d2"]
        state, dyn, key, elapsed, seq_a, tbf_a, ind_a, tel = args
        _key2, sub = jax.random.split(key)
        class_args = {"class_tick_seq": seq_a, "class_tick_tbf": tbf_a,
                      "class_tick_ind": ind_a}
        for name in class_wanted:
            kind = name.rsplit("_", 1)[1]
            fn = functools.partial(rt._class_tick, kind=kind,
                                   has_dyn=True, has_tel=True)
            a = (state, dyn, sub, elapsed, class_args[name], tel)
            ep = EntryPoint(name, cpath, cline)
            closed = _trace(lambda *x: fn(*x), *a)
            cost = (_cost_of(jax.jit(lambda *x: fn(*x)), a)
                    if compile_costs else None)
            out.append(_finish(ep, closed, cost))

    if "sharded_fused" in wanted:
        out.append(_trace_sharded(caps, compile_costs))

    if "twin_sweep" in wanted or "update_gate_sweep" in wanted:
        out.extend(_trace_sweeps(wanted, compile_costs))

    return out


def _trace_sharded(caps, compile_costs: bool) -> EntryPoint:
    import jax

    from kubedtn_tpu import runtime as rt
    from kubedtn_tpu.parallel.mesh import (
        EDGE_AXIS,
        edge_sharding,
        make_mesh,
    )

    spath, sline = _anchor(rt._make_sharded_fused)
    ep = EntryPoint("sharded_fused", spath, sline,
                    allowed_collectives=("ppermute", "axis_index"),
                    expect_shard_map=True, edge_axis=EDGE_AXIS)
    if len(jax.devices()) < 2:
        ep.skip_reason = (f"needs ≥2 devices for a real mailbox ring, "
                          f"environment exposes {len(jax.devices())}")
        return ep
    mesh = make_mesh(2)
    sharded = rt._make_sharded_fused(mesh)
    args, statics = caps["d2"]
    state, dyn, key, elapsed, seq_a, tbf_a, ind_a, tel = args
    sh = edge_sharding(mesh)
    put = lambda x: jax.device_put(x, sh)  # noqa: E731
    state = jax.tree.map(put, state)
    dyn = jax.tree.map(put, dyn)
    tel = put(tel)
    a = (state, dyn, key, elapsed, seq_a, tbf_a, ind_a, tel)
    fn = functools.partial(sharded, **statics)
    closed = _trace(lambda *x: fn(*x), *a)
    cost = (_cost_of(jax.jit(lambda *x: fn(*x)), a)
            if compile_costs else None)
    return _finish(ep, closed, cost)


def _small_snapshot():
    """A tiny engine-built snapshot shared by the sweep entries."""
    from kubedtn_tpu.api.types import Link, Topology, TopologySpec
    from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore
    from kubedtn_tpu.twin.snapshot import snapshot_from_engine

    store = TopologyStore()
    engine = SimEngine(store, capacity=8)
    props = _probe_props()
    for i, p in enumerate(props[:2]):
        a, b = f"a{i}", f"b{i}"
        store.create(Topology(name=a, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=i + 1, properties=p)])))
        store.create(Topology(name=b, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=i + 1, properties=p)])))
        engine.setup_pod(a)
        engine.setup_pod(b)
    Reconciler(store, engine).drain()
    links = [t.spec.links[0] for t in
             (store.get("default", "a0"), store.get("default", "a1"))]
    with engine._lock:
        pod_ids = dict(engine._pod_ids)
    return snapshot_from_engine(engine, q=8), links, pod_ids


def _trace_sweeps(wanted, compile_costs: bool) -> list[EntryPoint]:
    from kubedtn_tpu.twin.spec import Perturbation, Scenario

    out: list[EntryPoint] = []
    snap, links, pod_ids = _small_snapshot()

    if "twin_sweep" in wanted:
        scenarios = [Scenario(name="baseline"),
                     Scenario(name="degrade", perturbations=(
                         Perturbation("fail", uid=links[0].uid),))]
        out.append(_trace_one_sweep("twin_sweep", snap, scenarios,
                                    pod_ids, compile_costs))

    if "update_gate_sweep" in wanted:
        import dataclasses as dc

        from kubedtn_tpu.updates.gate import gate_scenarios
        from kubedtn_tpu.updates.planner import plan_update

        old = list(links)
        new = [dc.replace(
            old[0], properties=dc.replace(old[0].properties,
                                          latency="9ms")), old[1]]
        plan = plan_update(old, new, name="a0", check=False)
        scenarios, _adds, _edits = gate_scenarios(plan, snap,
                                                  pod_ids=pod_ids)
        if not scenarios:
            ep = EntryPoint("update_gate_sweep", *_anchor(gate_scenarios))
            ep.skip_reason = ("probe plan produced no replayable "
                              "rounds — harness drifted from the gate")
            out.append(ep)
        else:
            out.append(_trace_one_sweep("update_gate_sweep", snap,
                                        scenarios, pod_ids,
                                        compile_costs))
    return out


def _trace_one_sweep(name, snap, scenarios, pod_ids,
                     compile_costs: bool) -> EntryPoint:
    import jax

    from kubedtn_tpu.twin.engine import prepare_sweep

    jitted, args, _sig, _n = prepare_sweep(
        snap, scenarios, steps=SWEEP_STEPS, dt_us=1_000.0, k_slots=4,
        seed=0, pod_ids=pod_ids)
    ep = EntryPoint(name, *_anchor(jitted))
    closed = jax.make_jaxpr(jitted.__wrapped__)(*args)
    cost = _cost_of(jitted, args) if compile_costs else None
    return _finish(ep, closed, cost)
