"""jops / jkey — op-allowlist determinism over the traced programs.

Two checks per entry point:

1. **Allowlist** (`jops`): every primitive in the jaxpr must come from
   the vetted set below. The set is the union of what the real tick /
   sweep programs legitimately lower to, curated by family; a new
   primitive appearing in a traced program is a *review event*, not
   noise — nondeterministic reductions, host callbacks, and unvetted
   collectives are exactly what this catches. Collectives are
   entry-scoped: only the sharded program may ppermute.

2. **Key provenance** (`jkey`): dataflow over the typed-PRNG values
   proving every `random_bits` is reachable only through a
   `split`/`fold_in` chain rooted at a key ARGUMENT of the program.
   `random_seed` inside traced code (a key minted at trace time — the
   historical "raw `jax.random.key(seed)` into a sampler" bug, PR 6's
   engine.ping finding) and a key argument consumed raw (no
   split/fold_in before sampling — the PR 3 vmap-drift class) are both
   findings at the IR level, where decorator indirection and helper
   layers cannot hide them from the AST pass.
"""

from __future__ import annotations

import dataclasses

from kubedtn_tpu.analysis.core import Finding
from kubedtn_tpu.analysis.verify.jaxpr_tools import (
    Dataflow,
    is_key_dtype,
    iter_eqns,
)

RULE_JOPS = "jops"
RULE_JKEY = "jkey"

# -- the vetted primitive set ------------------------------------------

STRUCTURAL = {
    "pjit", "closed_call", "core_call", "xla_call", "scan", "while",
    "cond", "custom_jvp_call", "custom_vjp_call",
    "custom_vjp_call_jaxpr", "remat", "remat2", "checkpoint",
    "shard_map",
}
ELEMENTWISE = {
    "add", "sub", "mul", "div", "rem", "pow", "integer_pow", "neg",
    "abs", "sign", "floor", "ceil", "round", "exp", "log", "log1p",
    "expm1", "sqrt", "rsqrt", "lgamma", "logistic", "erf", "erf_inv",
    "tanh", "sin", "cos", "max", "min", "clamp", "is_finite",
    "eq", "ne", "ge", "gt", "le", "lt", "and", "or", "not", "xor",
    # the total-order comparators XLA's variadic sort lowers through
    # (deterministic by construction — they define the total order)
    "le_to", "lt_to",
    "select_n", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "nextafter", "square",
}
DATA_MOVEMENT = {
    "broadcast_in_dim", "concatenate", "convert_element_type",
    "bitcast_convert_type", "dynamic_slice", "dynamic_update_slice",
    "gather", "scatter", "scatter-add", "scatter-mul", "scatter-min",
    "scatter-max", "pad", "reshape", "rev", "slice", "squeeze",
    "transpose", "iota", "copy", "expand_dims",
}
REDUCTIONS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_or",
    "reduce_and", "reduce_prod", "argmax", "argmin", "cumsum",
    "cummax", "cumlogsumexp",
    # XLA variadic sort is deterministic (total order over the
    # comparator + index tiebreak in jnp wrappers); searchsorted
    # lowers through it on this backend
    "sort",
}
RNG = {
    # random_seed is DELIBERATELY absent: a key minted inside a traced
    # program is the jkey finding below, never an allowed op
    "random_split", "random_fold_in", "random_bits", "random_wrap",
    "random_unwrap", "threefry2x32",
}
ALLOWED_COMMON = (STRUCTURAL | ELEMENTWISE | DATA_MOVEMENT
                  | REDUCTIONS | RNG)

# collectives are allowed per entry point (ALLOWED_COLLECTIVES on the
# EntryPoint); anything here that is not granted flags as jops
COLLECTIVE = {
    "ppermute", "pshuffle", "psum", "pmax", "pmin", "pmean",
    "all_gather", "all_to_all", "reduce_scatter", "axis_index",
    "psum_scatter",
}

# primitives that are findings with a specific message even if someone
# adds them to a local allowlist: they break determinism or reach the
# host mid-program
DENY = {
    "random_seed": "key minted inside a traced program (raw "
                   "`jax.random.key(...)` reaches the compiled tick — "
                   "the sampler replays the same stream every call)",
    "pure_callback": "host callback inside a traced program",
    "io_callback": "host callback inside a traced program",
    "debug_callback": "host callback inside a traced program",
    "infeed": "host transfer inside a traced program",
    "outfeed": "host transfer inside a traced program",
    "approx_top_k": "approximate (nondeterministic) reduction",
}


def check_ops(entry, findings: list[Finding]) -> None:
    """The allowlist walk (jops)."""
    allowed = ALLOWED_COMMON | set(entry.allowed_collectives)
    seen: set[str] = set()
    for eqn in iter_eqns(entry.jaxpr.jaxpr):
        name = eqn.primitive.name
        if name in seen:
            continue
        seen.add(name)
        if name in DENY:
            findings.append(Finding(
                RULE_JOPS, entry.path, entry.line,
                f"[{entry.name}] denied primitive `{name}`: "
                f"{DENY[name]}"))
        elif name in COLLECTIVE and name not in allowed:
            findings.append(Finding(
                RULE_JOPS, entry.path, entry.line,
                f"[{entry.name}] collective `{name}` outside the "
                f"sharded exchange — cross-shard traffic must ride "
                f"the mailbox ring"))
        elif name not in allowed and name not in COLLECTIVE:
            findings.append(Finding(
                RULE_JOPS, entry.path, entry.line,
                f"[{entry.name}] unvetted primitive `{name}` — extend "
                f"the dtnverify allowlist only after a determinism "
                f"review"))


# -- key provenance -----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _KeyVal:
    rooted: bool    # transitively reaches a key ARGUMENT of the program
    derived: bool   # a split/fold_in sits between root and here
    minted: bool    # random_seed/random_wrap product or baked constant


class _KeyFlow(Dataflow):
    bottom = None

    def join(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return _KeyVal(a.rooted and b.rooted, a.derived and b.derived,
                       a.minted or b.minted)

    def invar(self, var, index):
        if is_key_dtype(var.aval):
            return _KeyVal(rooted=True, derived=False, minted=False)
        return None

    def constvar(self, var):
        if is_key_dtype(getattr(var, "aval", None)):
            return _KeyVal(rooted=False, derived=False, minted=True)
        return None

    def transfer(self, eqn, in_vals):
        name = eqn.primitive.name
        if name == "random_seed":
            self.emit("`random_seed` inside the traced program — "
                      + DENY["random_seed"])
            return [_KeyVal(False, False, True)] * len(eqn.outvars)
        if name == "random_wrap":
            return [_KeyVal(False, False, True)] * len(eqn.outvars)
        if name in ("random_split", "random_fold_in"):
            k = next((v for v in in_vals if isinstance(v, _KeyVal)),
                     None)
            if k is None:
                k = _KeyVal(False, False, True)
            return [_KeyVal(k.rooted, True, k.minted)] \
                * len(eqn.outvars)
        if name == "random_bits":
            k = next((v for v in in_vals if isinstance(v, _KeyVal)),
                     None)
            if k is None or k.minted or not k.rooted:
                self.emit("`random_bits` drawn from a key that is not "
                          "rooted at a key argument of the program "
                          "(minted or baked at trace time)")
            elif not k.derived:
                self.emit("key argument consumed RAW by `random_bits` "
                          "— no `split`/`fold_in` between the tick key "
                          "and the sampler (two call sites would draw "
                          "identical streams)")
            return [None] * len(eqn.outvars)
        return None


def check_keys(entry, findings: list[Finding]) -> None:
    """The key-provenance dataflow (jkey). Messages dedupe per entry:
    loop bodies run to fixpoint and would repeat them otherwise."""
    msgs: list[str] = []
    flow = _KeyFlow(emit=lambda m: msgs.append(m))
    flow.run(entry.jaxpr.jaxpr)
    for m in dict.fromkeys(msgs):
        findings.append(Finding(RULE_JKEY, entry.path, entry.line,
                                f"[{entry.name}] {m}"))
