"""Shared jaxpr machinery for the dtnverify passes: recursive equation
iteration and a small forward-dataflow engine that understands jax's
structural primitives (pjit / scan / while / cond / shard_map /
custom_* calls), so a pass written against flat equations sees through
every nesting level the tracer produces.

The dataflow engine is deliberately minimal: per-variable abstract
values from a tiny lattice (key provenance, f64 taint, foreign-bit
taint), a join, and a per-equation transfer hook. Loop bodies
(scan/while) run to a bounded fixpoint on their carries — the lattices
here are a few booleans deep, so convergence takes at most as many
passes as there are flags.
"""

from __future__ import annotations

from typing import Callable, Iterator

from jax import core as jax_core

# primitives whose params hold sub-jaxprs the engine maps structurally
# (operand values seed inner invars 1:1; inner outvars land on eqn
# outvars 1:1 — the jax calling conventions below)
_CALL_LIKE = ("pjit", "closed_call", "core_call", "xla_call", "remat",
              "remat2", "checkpoint", "custom_jvp_call",
              "custom_vjp_call", "custom_vjp_call_jaxpr", "shard_map")
_FIXPOINT_CAP = 8


def _as_jaxpr(obj) -> jax_core.Jaxpr | None:
    if isinstance(obj, jax_core.ClosedJaxpr):
        return obj.jaxpr
    if isinstance(obj, jax_core.Jaxpr):
        return obj
    return None


def subjaxprs(eqn) -> Iterator[jax_core.Jaxpr]:
    """Every inner Jaxpr referenced by `eqn`'s params (any nesting
    style: single, tuple of branches, ClosedJaxpr-wrapped)."""
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for sub in vals:
            j = _as_jaxpr(sub)
            if j is not None:
                yield j


def iter_eqns(jaxpr: jax_core.Jaxpr) -> Iterator[jax_core.JaxprEqn]:
    """Every equation in `jaxpr` and every nested sub-jaxpr, outermost
    first."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in subjaxprs(eqn):
            yield from iter_eqns(sub)


def primitive_set(jaxpr: jax_core.Jaxpr) -> set[str]:
    return {eqn.primitive.name for eqn in iter_eqns(jaxpr)}


def count_eqns(jaxpr: jax_core.Jaxpr) -> int:
    return sum(1 for _ in iter_eqns(jaxpr))


def is_key_dtype(aval) -> bool:
    """True for jax's typed PRNG key arrays (key<fry> etc.)."""
    import jax

    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return False
    try:
        return jax.dtypes.issubdtype(dtype, jax.dtypes.prng_key)
    except Exception:
        return False


class Dataflow:
    """Forward dataflow over a nested jaxpr.

    Subclass hooks:
    - ``bottom``: the no-information value (default None).
    - ``join(a, b)``: lattice join; must treat ``bottom`` as identity.
    - ``invar(var, index)`` / ``constvar(var)`` / ``literal(lit)``:
      initial values at the top level.
    - ``transfer(eqn, in_vals)``: return the eqn's out values (a list
      matching ``eqn.outvars``) or None for the default — join of the
      inputs broadcast to every output. Called for NON-structural
      primitives only; structural ones recurse automatically.

    Findings are the subclass's business: append to ``self.emit`` (a
    caller-supplied callable) inside ``transfer``.
    """

    bottom = None

    def __init__(self, emit: Callable[[str], None] | None = None) -> None:
        self.emit = emit if emit is not None else (lambda msg: None)

    # -- hooks ---------------------------------------------------------
    def join(self, a, b):
        return a if b is self.bottom else b if a is self.bottom else a

    def invar(self, var, index: int):
        return self.bottom

    def constvar(self, var):
        return self.bottom

    def literal(self, lit):
        return self.bottom

    def transfer(self, eqn, in_vals):
        return None

    # -- engine --------------------------------------------------------
    def run(self, jaxpr: jax_core.Jaxpr, in_vals=None):
        if in_vals is None:
            in_vals = [self.invar(v, i)
                       for i, v in enumerate(jaxpr.invars)]
        return self._run(jaxpr, list(in_vals),
                         [self.constvar(v) for v in jaxpr.constvars])

    def _run(self, jaxpr, in_vals, const_vals):
        env: dict = {}
        for v, val in zip(jaxpr.constvars, const_vals):
            env[v] = val
        for v, val in zip(jaxpr.invars, in_vals):
            env[v] = val

        def read(a):
            if isinstance(a, jax_core.Literal):
                return self.literal(a)
            return env.get(a, self.bottom)

        for eqn in jaxpr.eqns:
            ivals = [read(x) for x in eqn.invars]
            ovals = self._structural(eqn, ivals)
            if ovals is None:
                ovals = self.transfer(eqn, ivals)
            if ovals is None:
                j = self.bottom
                for x in ivals:
                    j = self.join(j, x)
                ovals = [j] * len(eqn.outvars)
            for v, val in zip(eqn.outvars, ovals):
                if not isinstance(v, jax_core.DropVar):
                    env[v] = val
        return [read(v) for v in jaxpr.outvars]

    def _sub(self, obj, in_vals):
        """Run an inner jaxpr: ClosedJaxpr consts get bottom-or-const
        treatment via `constvar`, bare Jaxpr constvars likewise."""
        inner = _as_jaxpr(obj)
        return self._run(inner, list(in_vals),
                         [self.constvar(v) for v in inner.constvars])

    def _structural(self, eqn, ivals):
        name = eqn.primitive.name
        if name in _CALL_LIKE:
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                obj = eqn.params.get(key)
                if obj is not None and _as_jaxpr(obj) is not None:
                    inner = _as_jaxpr(obj)
                    # custom_* calls carry extra operands (the jvp/bwd
                    # closures) beyond the body's invars; align tail
                    take = ivals[len(ivals) - len(inner.invars):] \
                        if len(inner.invars) <= len(ivals) else ivals
                    out = self._sub(obj, take)
                    return self._pad_out(out, eqn)
            return None
        if name == "scan":
            return self._scan(eqn, ivals)
        if name == "while":
            return self._while(eqn, ivals)
        if name == "cond":
            return self._cond(eqn, ivals)
        return None

    def _pad_out(self, out, eqn):
        n = len(eqn.outvars)
        if len(out) == n:
            return out
        return (out + [self.bottom] * n)[:n]

    def _scan(self, eqn, ivals):
        nc = eqn.params["num_consts"]
        ncar = eqn.params["num_carry"]
        consts, carry, xs = (ivals[:nc], ivals[nc:nc + ncar],
                             ivals[nc + ncar:])
        body = eqn.params["jaxpr"]
        for _ in range(_FIXPOINT_CAP):
            out = self._sub(body, consts + carry + xs)
            new_carry = [self.join(a, b)
                         for a, b in zip(carry, out[:ncar])]
            if new_carry == carry:
                break
            carry = new_carry
        out = self._sub(body, consts + carry + xs)
        return self._pad_out(out[:ncar] + out[ncar:], eqn)

    def _while(self, eqn, ivals):
        cn = eqn.params["cond_nconsts"]
        bn = eqn.params["body_nconsts"]
        cond_consts = ivals[:cn]
        body_consts = ivals[cn:cn + bn]
        carry = ivals[cn + bn:]
        body = eqn.params["body_jaxpr"]
        cond = eqn.params["cond_jaxpr"]
        for _ in range(_FIXPOINT_CAP):
            self._sub(cond, cond_consts + carry)  # visit for findings
            out = self._sub(body, body_consts + carry)
            new_carry = [self.join(a, b) for a, b in zip(carry, out)]
            if new_carry == carry:
                break
            carry = new_carry
        return self._pad_out(carry, eqn)

    def _cond(self, eqn, ivals):
        args = ivals[1:]  # operand 0 is the branch index
        outs = None
        for br in eqn.params["branches"]:
            out = self._sub(br, args)
            outs = out if outs is None else [
                self.join(a, b) for a, b in zip(outs, out)]
        return self._pad_out(outs or [], eqn)
