"""jcost — the checked-in dispatch & cost budgets (COST_BUDGET.json).

The budget file pins, per entry point, the XLA cost-analysis FLOPs and
bytes-accessed of the compiled program at the harness's canonical
shapes, plus the measured dispatches-per-tick of the fused tick. A
refactor that silently splits the fused dispatch (dispatch count is
matched EXACTLY) or bloats an entry point's compiled cost past the
tolerance fails tier-1 before any bench run.

Honesty rules:
- budgets are backend-specific (cost analysis differs across
  backends); a mismatched backend skips the flops/bytes comparison
  with an explicit note but still enforces dispatch counts, which are
  a host-level property;
- a jax version change can legitimately shift lowering costs — the
  recorded version is reported on mismatch so the reviewer knows to
  re-baseline with ``--update-budgets`` instead of hunting a phantom
  regression;
- an entry point with no pinned budget is itself a finding: new
  programs enter the gate deliberately, not by default.
"""

from __future__ import annotations

import json
from pathlib import Path

from kubedtn_tpu.analysis.core import Finding

RULE_JCOST = "jcost"

BUDGET_FILE = "COST_BUDGET.json"
# growth tolerance before a cost regression flags: generous enough for
# minor lowering drift, far below the 2× "silently split/doubled"
# failure mode this gate exists to catch
COST_TOLERANCE = 1.5


def budget_path(root: Path) -> Path:
    return root / BUDGET_FILE


def load_budget(root: Path) -> dict | None:
    p = budget_path(root)
    if not p.exists():
        return None
    return json.loads(p.read_text())


def write_budget(root: Path, entries: list, dispatch: dict) -> dict:
    """Re-baseline: record every traced entry's measured cost plus the
    dispatch counts. Returns the written document."""
    import jax

    doc = {
        "schema_version": 1,
        "backend": jax.default_backend(),
        "jax": jax.__version__,
        "tolerance": COST_TOLERANCE,
        "entries": {
            ep.name: {
                "flops": ep.cost["flops"],
                "bytes": ep.cost["bytes"],
                "eqns": ep.n_eqns,
            }
            for ep in entries
            if ep.jaxpr is not None and ep.cost is not None
        },
        "dispatch": dispatch,
    }
    budget_path(root).write_text(json.dumps(doc, indent=2,
                                            sort_keys=True) + "\n")
    return doc


def check_budget(root: Path, entries: list, dispatch: dict,
                 findings: list[Finding]) -> dict:
    """Compare measured entries/dispatch counts against the checked-in
    budget; append jcost findings. Returns a status dict for the
    report."""
    import jax

    status: dict = {"file": BUDGET_FILE, "checked": False}
    doc = load_budget(root)
    if doc is None:
        findings.append(Finding(
            RULE_JCOST, BUDGET_FILE, 1,
            "COST_BUDGET.json missing — run `python -m "
            "kubedtn_tpu.analysis --verify --update-budgets` to pin "
            "the current dispatch counts and compiled costs"))
        return status
    backend = jax.default_backend()
    tol = float(doc.get("tolerance", COST_TOLERANCE))
    same_backend = doc.get("backend") == backend
    status.update(backend=backend, budget_backend=doc.get("backend"),
                  checked=True, cost_compared=same_backend)
    if doc.get("jax") != jax.__version__:
        status["note"] = (
            f"budget recorded on jax {doc.get('jax')}, running "
            f"{jax.__version__}: a cost flag may be lowering drift — "
            f"re-baseline with --update-budgets if so")

    budgets = doc.get("entries", {})
    traced = {ep.name: ep for ep in entries if ep.jaxpr is not None}
    for name, ep in traced.items():
        b = budgets.get(name)
        if b is None:
            findings.append(Finding(
                RULE_JCOST, ep.path, ep.line,
                f"[{name}] no budget pinned for this entry point — "
                f"add it via --update-budgets (new programs enter the "
                f"gate deliberately)"))
            continue
        if not same_backend or ep.cost is None:
            continue
        for metric in ("flops", "bytes"):
            have = float(ep.cost[metric])
            want = float(b[metric])
            if want > 0 and have > want * tol:
                findings.append(Finding(
                    RULE_JCOST, ep.path, ep.line,
                    f"[{name}] {metric} regression: {have:.0f} > "
                    f"budget {want:.0f} × {tol} — the compiled "
                    f"program grew past its pinned envelope "
                    f"(re-baseline with --update-budgets only if the "
                    f"growth is intentional and reviewed)"))

    # dispatch counts: exact, backend-independent
    for key, want in (doc.get("dispatch") or {}).items():
        have = dispatch.get(key)
        if have is None:
            continue
        if float(have) != float(want):
            findings.append(Finding(
                RULE_JCOST, "kubedtn_tpu/runtime.py", 1,
                f"[{key}] dispatches per tick = {have} (budget "
                f"{want}) — the one-fused-dispatch-per-tick contract "
                f"broke: the tick program was split or a new jitted "
                f"call joined the steady tick path"))
    return status
