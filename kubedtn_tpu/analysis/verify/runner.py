"""dtnverify runner: trace → passes → budget → report.

`run_verify` is the one entry: traces the requested entry points
(kubedtn_tpu.analysis.verify.entrypoints), runs the four pass families
over each jaxpr, measures the tick dispatch counts, checks the
checked-in COST_BUDGET.json, and returns ``(findings, report)`` where
`report` is the ANALYSIS.json ``jaxpr`` section (schema v2).

The on-disk result cache (`--cached` / `make verify-fast`) keys on a
content hash of every ``kubedtn_tpu/**/*.py`` file plus the budget
file: tracing and compiling the entry points costs tens of seconds,
and a pre-commit hook only needs that cost when something that can
change a traced program changed. A hit replays the recorded findings
verbatim (they are data); a miss falls through to the full run.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

from kubedtn_tpu.analysis import default_root
from kubedtn_tpu.analysis.core import JAXPR_RULES, Finding

# ONE definition of the jaxpr rule tags: core.JAXPR_RULES also drives
# the "not waivable" stale-waiver classification — two copies could
# silently diverge when a sixth rule lands
VERIFY_RULES = JAXPR_RULES
CACHE_FILE = ".dtnverify-cache.json"


class VerifyReport(dict):
    """The ANALYSIS.json `jaxpr` section (plain dict subclass so json
    serialization is direct)."""


def _tree_hash(root: Path) -> str:
    import jax

    h = hashlib.sha256()
    # the environment is part of the result's identity: a jax upgrade
    # or backend/device-count change alters lowered primitives, cost
    # analysis, and the sharded entry — a cached verdict from the old
    # environment must miss, not replay
    h.update(f"jax={jax.__version__};backend={jax.default_backend()};"
             f"devices={len(jax.devices())};".encode())
    for p in sorted((root / "kubedtn_tpu").rglob("*.py")):
        h.update(p.relative_to(root).as_posix().encode())
        h.update(p.read_bytes())
    budget = root / "COST_BUDGET.json"
    if budget.exists():
        h.update(budget.read_bytes())
    return h.hexdigest()


def _load_cache(root: Path, key: str):
    p = root / CACHE_FILE
    if not p.exists():
        return None
    try:
        doc = json.loads(p.read_text())
    except (OSError, ValueError):
        return None
    if doc.get("tree_hash") != key or doc.get("schema") != 2:
        return None
    findings = [Finding(**f) for f in doc.get("findings", [])]
    return findings, VerifyReport(doc.get("report", {}))


def _save_cache(root: Path, key: str, findings, report) -> None:
    doc = {"schema": 2, "tree_hash": key,
           "findings": [f.to_json() for f in findings],
           "report": dict(report)}
    try:
        (root / CACHE_FILE).write_text(json.dumps(doc) + "\n")
    except OSError:
        pass  # the cache is an optimization, never a failure


def run_verify(root: Path | None = None,
               entries: tuple[str, ...] | None = None,
               use_cache: bool = False,
               update_budgets: bool = False,
               ) -> tuple[list[Finding], VerifyReport]:
    """Run the jaxpr verification layer. `entries` selects a subset of
    entry points (None = all); `use_cache` replays a stored clean/dirty
    result when no package source changed; `update_budgets` re-baselines
    COST_BUDGET.json from the measured costs instead of checking."""
    root = Path(root) if root is not None else default_root()
    full_run = entries is None
    # every full run computes the key and SAVES at the end (hashing is
    # milliseconds next to the trace/compile cost), so `make verify` /
    # tier-1 warm the pre-commit `--cached` path; only `use_cache`
    # runs are allowed to replay a hit
    cache_key = (_tree_hash(root)
                 if full_run and not update_budgets else None)
    if use_cache and cache_key is not None:
        hit = _load_cache(root, cache_key)
        if hit is not None:
            findings, report = hit
            report["cache"] = "hit"
            return findings, report

    from kubedtn_tpu.analysis.verify import budget as budget_mod
    from kubedtn_tpu.analysis.verify.dispatch import fused_tick_dispatches
    from kubedtn_tpu.analysis.verify.dtype_flow import check_dtype_flow
    from kubedtn_tpu.analysis.verify.entrypoints import trace_entry_points
    from kubedtn_tpu.analysis.verify.ops_allowlist import (
        check_keys,
        check_ops,
    )
    from kubedtn_tpu.analysis.verify.sharding_audit import check_sharding
    from kubedtn_tpu.analysis.verify.tenant_audit import \
        check_tenant_isolation

    eps = trace_entry_points(entries=entries, compile_costs=True)
    findings: list[Finding] = []
    for ep in eps:
        if ep.jaxpr is None:
            continue
        check_ops(ep, findings)
        check_keys(ep, findings)
        check_dtype_flow(ep, findings)
        if ep.expect_shard_map:
            check_sharding(ep, findings)
        if ep.name.startswith(("fused_tick", "class_tick",
                               "sharded_fused")):
            # tenant-isolation: tick-program scatters must not shift
            # row indices across tenant ranges (sweep entries advance
            # whole-capacity state, no row-index scatters to audit)
            check_tenant_isolation(ep, findings)

    # dispatch counts: only measured on a full run (the probe builds
    # and ticks a live plane; a --entries subset run stays cheap)
    dispatch: dict = {}
    if full_run:
        dispatch["fused_tick_d1"] = fused_tick_dispatches(depth=1)
        dispatch["fused_tick_d2"] = fused_tick_dispatches(depth=2)

    budget_status: dict = {}
    if update_budgets:
        if not full_run:
            raise ValueError("--update-budgets needs the full entry "
                             "set (budgets are pinned per entry)")
        doc = budget_mod.write_budget(root, eps, dispatch)
        budget_status = {"file": budget_mod.BUDGET_FILE,
                         "updated": True,
                         "entries": sorted(doc["entries"])}
    elif full_run:
        budget_status = budget_mod.check_budget(root, eps, dispatch,
                                                findings)

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    report = VerifyReport({
        "rules": list(VERIFY_RULES),
        "entry_points": {
            ep.name: (
                {"skipped": ep.skip_reason} if ep.jaxpr is None else {
                    "path": ep.path,
                    "eqns": ep.n_eqns,
                    "primitives": ep.n_prims,
                    **({"flops": ep.cost["flops"],
                        "bytes": ep.cost["bytes"]} if ep.cost else {}),
                })
            for ep in eps
        },
        "dispatch": dispatch,
        "budget": budget_status,
        "summary": {
            "total": len(findings),
            "entries_traced": sum(1 for e in eps if e.jaxpr is not None),
            "entries_skipped": sum(1 for e in eps if e.jaxpr is None),
        },
    })
    if cache_key is not None:
        _save_cache(root, cache_key, findings, report)
    return findings, report
