"""Dispatch counting — the runtime half of the jcost family.

"One fused dispatch per tick" (PR 1) is the plane's core performance
contract, and it is invisible to jaxpr inspection: a refactor could
keep every traced program clean while quietly calling two of them per
tick. This probe pins it at the call layer: every module-level
jax-compiled callable in `runtime.TICK_DISPATCH_MODULES` is wrapped
with a counter, the canonical three-class probe plane runs a warmup
(compiles excluded by design — a compile is not a steady-state
dispatch), and then a counted window of steady ticks with fresh
ingress on all three kernel classes.

Definition pinned in COST_BUDGET.json: *dispatches per tick* = calls
of named jitted programs from the registered tick-path modules during
one `plane.tick()`, at steady state, all classes active. Transfers
(`device_put`, `np.asarray` at the completion sync point) are not
dispatches.
"""

from __future__ import annotations

import importlib


def count_dispatches(fn, module_names) -> int:
    """Run `fn()` with every module-level jitted callable in
    `module_names` wrapped by a counter; returns the number of calls.
    Wrapping is attribute-level, so callables resolved through module
    globals at call time (the plane's dispatch path) are all seen."""
    import jax

    counter = {"n": 0}
    patched: list[tuple[object, str, object]] = []
    try:
        for mod_name in module_names:
            mod = importlib.import_module(mod_name)
            for attr in dir(mod):
                obj = getattr(mod, attr)
                if not isinstance(obj, jax.stages.Wrapped):
                    continue

                def make(wrapped):
                    def counted(*a, **k):
                        counter["n"] += 1
                        return wrapped(*a, **k)

                    counted.__wrapped__ = wrapped
                    return counted

                patched.append((mod, attr, obj))
                setattr(mod, attr, make(obj))
        fn()
    finally:
        for mod, attr, obj in patched:
            setattr(mod, attr, obj)
    return counter["n"]


def fused_tick_dispatches(depth: int = 1, ticks: int = 3) -> float:
    """Measured dispatches per steady tick on the canonical probe
    plane (all three kernel classes active every tick)."""
    from kubedtn_tpu.runtime import TICK_DISPATCH_MODULES
    from kubedtn_tpu.analysis.verify.entrypoints import build_probe_plane

    plane, win = build_probe_plane(depth=depth)
    t = [100.0]

    def feed():
        for wa in win:
            wa.ingress.extend(bytes([7]) * 64 for _ in range(8))

    def one_tick():
        feed()
        t[0] += 0.002
        plane.tick(now_s=t[0])

    for _ in range(4):   # warmup: compiles + pipeline fill
        one_tick()

    def window():
        for _ in range(ticks):
            one_tick()

    n = count_dispatches(window, TICK_DISPATCH_MODULES)
    plane.flush()
    return n / float(ticks)
