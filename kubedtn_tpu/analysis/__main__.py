"""``python -m kubedtn_tpu.analysis`` — run dtnlint over the tree.

Exit status 0 iff every finding is waived (``# dtnlint:
<rule>-ok(reason)``). ``--json`` writes the machine-readable artifact
(the tier-1 test writes ``ANALYSIS.json`` at the repo root so benches
can track the findings-count trajectory).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from kubedtn_tpu.analysis import (
    PASSES,
    default_root,
    run_suite,
    summarize,
    write_json,
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubedtn_tpu.analysis",
        description="dtnlint: contract-checking static analysis for "
                    "the determinism / key / host-sync / lock / dtype "
                    "invariants")
    ap.add_argument("--root", type=Path, default=None,
                    help="repo root (default: the installed package's "
                         "parent)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of: "
                         + ",".join(PASSES))
    ap.add_argument("--json", type=Path, default=None, metavar="PATH",
                    help="write the machine-readable findings artifact")
    ap.add_argument("--show-waived", action="store_true",
                    help="print waived findings too")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="summary line only")
    args = ap.parse_args(argv)

    rules = None
    if args.rules:
        rules = tuple(r.strip() for r in args.rules.split(",") if r.strip())
        unknown = [r for r in rules if r not in PASSES]
        if unknown:
            ap.error(f"unknown rule(s): {', '.join(unknown)} "
                     f"(have: {', '.join(PASSES)})")

    root = args.root if args.root is not None else default_root()
    _project, findings = run_suite(root=root, rules=rules)
    if args.json is not None:
        write_json(args.json, findings, root)

    active = [f for f in findings if not f.waived]
    if not args.quiet:
        shown = findings if args.show_waived else active
        for f in shown:
            print(f.format())
    s = summarize(findings)
    by_rule = ", ".join(f"{k}={v}" for k, v in s["by_rule"].items())
    print(f"dtnlint: {s['total']} finding(s), {s['waived']} waived, "
          f"{s['unwaivered']} active ({by_rule or 'clean tree'})")
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
