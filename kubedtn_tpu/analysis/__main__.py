"""``python -m kubedtn_tpu.analysis`` — run the contract suite.

Three layers, one artifact:

- **dtnlint** (default): the AST passes over the tree. Exit 0 iff
  every finding is waived (``# dtnlint: <rule>-ok(reason)``).
- **dtnverify** (``--verify``): the jaxpr layer — trace the real tick/
  sweep programs and check the op-allowlist / key-provenance /
  dtype-flow / sharding contracts plus the COST_BUDGET.json dispatch &
  cost gate.
- **dtnscale** (``--scale``): the host-asymptotics layer — bound every
  scale-critical entry point's Python-level host complexity against
  SCALE_BUDGET.json (steady tick/drain capacity-independent, barrier
  bodies O(rows_touched), compact/save linear) and run the empirical
  scaling probe (fitted wall-time slopes over a row-count ladder).

``--cached`` replays the stored dtnverify/dtnscale results when no
package source changed (the `make verify-fast` / pre-commit path);
``--update-budgets`` re-baselines the budget file(s) of the layers
being run. ``--json PATH`` writes the machine-readable artifact
(schema v3; the tier-1 tests write ``ANALYSIS.json`` at the repo
root). ``--diff OLD.json`` compares artifacts (new / fixed /
waiver-flips) for reviewer use. ``--fix`` mechanically repairs
hygiene findings (unused imports, import-group order) in place.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

# the sharded entry point needs a multi-device mesh; harmless
# everywhere else, and it must land before jax initializes a backend
if "--verify" in sys.argv \
        and "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

from kubedtn_tpu.analysis import (  # noqa: E402  (XLA_FLAGS first)
    PASSES,
    default_root,
    run_suite,
    summarize,
    write_json,
)


def _merge_subset_section(path: Path, section: dict,
                          entries: tuple[str, ...]) -> dict:
    """An `--entries` subset run must not clobber the artifact's FULL
    jaxpr section (8 entry points, dispatch pins, budget status) with
    a partial one: merge the re-traced entries over the existing
    section, keeping every other entry's state and the full-run-only
    dispatch/budget results."""
    import json

    try:
        old = json.loads(Path(path).read_text()).get("jaxpr")
    except (OSError, ValueError):
        old = None
    if not old:
        return section
    merged = dict(old)
    merged["entry_points"] = {**old.get("entry_points", {}),
                              **section.get("entry_points", {})}
    tags = tuple(f"[{e}] " for e in entries)
    # drop only findings the subset run REGENERATES: the per-entry IR
    # passes re-ran, but jcost (dispatch counts + budget comparison) is
    # full-run-only — dropping an active jcost finding here would flip
    # the artifact to clean without anything re-measuring the regression
    kept = [f for f in old.get("findings", [])
            if f.get("rule") == "jcost"
            or not f.get("message", "").startswith(tags)]
    merged["findings"] = kept + section.get("findings", [])
    merged["summary"] = {
        **old.get("summary", {}),
        "total": len(merged["findings"]),
        "unwaivered": sum(1 for f in merged["findings"]
                          if not f.get("waived")),
        "entries_traced": len([v for v in
                               merged["entry_points"].values()
                               if "skipped" not in v]),
        "entries_skipped": len([v for v in
                                merged["entry_points"].values()
                                if "skipped" in v]),
    }
    return merged


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubedtn_tpu.analysis",
        description="dtnlint + dtnverify: contract checking for the "
                    "determinism / key / host-sync / lock / dtype "
                    "invariants, at the AST and jaxpr levels")
    ap.add_argument("--root", type=Path, default=None,
                    help="repo root (default: the installed package's "
                         "parent)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of: "
                         + ",".join(PASSES))
    ap.add_argument("--json", type=Path, default=None, metavar="PATH",
                    help="write the machine-readable findings artifact")
    ap.add_argument("--show-waived", action="store_true",
                    help="print waived findings too")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="summary line only")
    ap.add_argument("--verify", action="store_true",
                    help="additionally run dtnverify: trace the "
                         "compiled tick/sweep programs and check the "
                         "jaxpr-level contracts + cost budgets")
    ap.add_argument("--scale", action="store_true",
                    help="additionally run dtnscale: host-asymptotics "
                         "bounds over the scale-critical entry points "
                         "against SCALE_BUDGET.json, plus the "
                         "empirical scaling probe")
    ap.add_argument("--probe-sizes", default=None, metavar="N,N,...",
                    help="override the dtnscale probe's row-count "
                         "ladder (default: SCALE_BUDGET.json "
                         "probe.sizes)")
    ap.add_argument("--entries", default=None, metavar="NAMES",
                    help="comma-separated dtnverify entry-point subset "
                         "(skips the dispatch/budget gate, which needs "
                         "the full set)")
    ap.add_argument("--cached", action="store_true",
                    help="reuse the stored dtnverify result when no "
                         "kubedtn_tpu source changed (pre-commit path)")
    ap.add_argument("--update-budgets", action="store_true",
                    help="re-baseline COST_BUDGET.json from the "
                         "measured dispatch counts and compiled costs")
    ap.add_argument("--fix", action="store_true",
                    help="mechanically repair hygiene findings "
                         "(unused imports, import-group order)")
    ap.add_argument("--diff", type=Path, default=None, metavar="OLD",
                    help="compare OLD ANALYSIS artifact against "
                         "--json PATH (or a fresh run) and exit")
    args = ap.parse_args(argv)

    if args.diff is not None and args.json is None:
        # validated up front: a forgotten --json must not cost a full
        # --verify trace (and possibly a --fix rewrite) first
        ap.error("--diff needs --json PATH (the artifact to compare "
                 "against)")

    rules = None
    if args.rules:
        rules = tuple(r.strip() for r in args.rules.split(",") if r.strip())
        unknown = [r for r in rules if r not in PASSES]
        if unknown:
            ap.error(f"unknown rule(s): {', '.join(unknown)} "
                     f"(have: {', '.join(PASSES)})")

    root = args.root if args.root is not None else default_root()
    scale_out: dict | None = {} if args.scale else None
    project, findings = run_suite(root=root, rules=rules,
                                  scale=scale_out)

    if args.fix:
        from kubedtn_tpu.analysis.fix import fix_tree

        changed = fix_tree(root, project, findings)
        for rel in changed:
            print(f"fixed: {rel}")
        # re-lint the repaired tree so the report reflects reality
        scale_out = {} if args.scale else None
        project, findings = run_suite(root=root, rules=rules,
                                      scale=scale_out)

    scale_section = None
    if args.scale:
        from kubedtn_tpu.analysis.scale.runner import run_scale

        sizes = (tuple(int(s) for s in args.probe_sizes.split(",")
                       if s.strip()) if args.probe_sizes else None)
        pfindings, probe = run_scale(
            root, use_cache=args.cached,
            update_budgets=args.update_budgets,
            sizes=list(sizes) if sizes else None)
        from kubedtn_tpu.analysis.core import SCALE_RULES

        findings = findings + pfindings
        scost = [f for f in findings if f.rule in SCALE_RULES]
        scale_section = {
            "rules": list(SCALE_RULES),
            "entries": (scale_out or {}).get("entries", {}),
            "budget": (scale_out or {}).get("budget", {}),
            "probe": probe,
            "findings": [f.to_json() for f in scost],
            "summary": {
                "total": len(scost),
                "unwaivered": sum(1 for f in scost if not f.waived),
            },
        }
        # scost/savail findings live in the artifact's `scale`
        # section; the AST section keeps its v1 shape
        ast_findings_only = [f for f in findings
                             if f.rule not in SCALE_RULES]
    else:
        ast_findings_only = findings

    jaxpr_section = None
    if args.verify:
        from kubedtn_tpu.analysis.verify import run_verify

        entries = (tuple(e.strip() for e in args.entries.split(",")
                         if e.strip()) if args.entries else None)
        vfindings, report = run_verify(
            root=root, entries=entries, use_cache=args.cached,
            update_budgets=args.update_budgets)
        jaxpr_section = dict(report)
        jaxpr_section["findings"] = [f.to_json() for f in vfindings]
        jaxpr_section["summary"] = {
            **report.get("summary", {}),
            "total": len(vfindings),
            "unwaivered": sum(1 for f in vfindings if not f.waived),
        }
        if entries is not None and args.json is not None:
            jaxpr_section = _merge_subset_section(
                args.json, jaxpr_section, entries)
        findings = findings + vfindings

    if args.json is not None:
        write_json(args.json, ast_findings_only, root,
                   jaxpr=jaxpr_section, scale=scale_section)

    if args.diff is not None:
        from kubedtn_tpu.analysis.diff import run_diff

        return run_diff(args.diff, args.json)

    active = [f for f in findings if not f.waived]
    if not args.quiet:
        shown = findings if args.show_waived else active
        for f in shown:
            print(f.format())
    s = summarize(findings)
    by_rule = ", ".join(f"{k}={v}" for k, v in s["by_rule"].items())
    layer = "dtnlint" + ("+dtnverify" if args.verify else "") \
        + ("+dtnscale" if args.scale else "")
    print(f"{layer}: {s['total']} finding(s), {s['waived']} waived, "
          f"{s['unwaivered']} active ({by_rule or 'clean tree'})")
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
